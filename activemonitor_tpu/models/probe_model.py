"""The probe transformer — flagship payload of the training-step and
compile-smoke probes.

A deliberately canonical decoder (embed → N×[LN, causal attention,
residual, LN, MLP, residual] → LN → logits) written as a pure-functional
JAX model: the parameter tree is an explicit dict built next to a
parallel tree of `PartitionSpec`s, so the tensor/data-parallel layout is
visible in one place instead of being threaded through module metadata.

Design for the MXU: every matmul is a large dense einsum in bfloat16
(params kept in float32, cast at use); shapes are static; no Python
control flow under jit. Sharding follows the standard megatron layout —
attention heads and MLP hidden dim split over the "model" axis, batch
over "data" — so the only collectives jit inserts are the psums after
the down-projections, riding ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class ProbeModelConfig:
    vocab_size: int = 4096
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16
    # GQA/MQA: K/V heads (must divide n_heads); None = standard MHA.
    # The fused kernel path (ops/flash_attention.py) runs grouped heads
    # natively; the dense path repeats K/V heads for the einsum.
    n_kv_heads: int | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    def flops_per_token(self) -> float:
        """Approximate forward FLOPs/token (2·params matmul convention)."""
        kv_dim = self.kv_heads * self.head_dim
        per_layer = (
            2 * 2 * self.d_model * self.d_model  # q + out projections
            + 2 * 2 * self.d_model * kv_dim  # k + v projections
            + 2 * 2 * self.d_model * self.d_ff  # up + down
        )
        embed = 2 * self.d_model * self.vocab_size
        return per_layer * self.n_layers + embed


def tiny_config() -> ProbeModelConfig:
    """Small enough to train a step on CPU in tests."""
    return ProbeModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq_len=64
    )


def init_params(key: jax.Array, cfg: ProbeModelConfig) -> Dict:
    """Explicit parameter tree (float32 master copies)."""
    keys = jax.random.split(key, cfg.n_layers * 6 + 2)
    k = iter(keys)

    def dense(kk, shape, scale=None):
        scale = scale if scale is not None else (1.0 / jnp.sqrt(shape[0]))
        return (jax.random.normal(kk, shape, jnp.float32) * scale)

    params: Dict = {
        "embed": dense(next(k), (cfg.vocab_size, cfg.d_model), scale=0.02),
        "layers": [],
        "final_ln": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    for _ in range(cfg.n_layers):
        if cfg.kv_heads == cfg.n_heads:
            # MHA keeps the single fused projection (and its specs);
            # key-draw order is part of the init contract — wqkv first
            attn = {"wqkv": dense(next(k), (cfg.d_model, 3, cfg.n_heads, cfg.head_dim))}
        else:
            # GQA: separate q and (narrower) kv projections
            attn = {
                "wq": dense(next(k), (cfg.d_model, cfg.n_heads, cfg.head_dim)),
                "wkv": dense(next(k), (cfg.d_model, 2, cfg.kv_heads, cfg.head_dim)),
            }
        params["layers"].append(
            {
                "ln1": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
                **attn,
                "wo": dense(next(k), (cfg.n_heads, cfg.head_dim, cfg.d_model)),
                "ln2": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
                "w_up": dense(next(k), (cfg.d_model, cfg.d_ff)),
                "w_down": dense(next(k), (cfg.d_ff, cfg.d_model)),
            }
        )
    return params


def param_partition_rules(tp_axis: str = "model"):
    """The megatron tensor-parallel layout as DATA — regex partition
    rules resolved over the (MHA or GQA) parameter tree by
    ``parallel/partition.match_partition_rules``. Attention heads and
    the MLP hidden dim shard over ``tp_axis``; norms/embeddings fall
    through to the replicated default. Re-meshing the probe model is an
    edit to this tuple, never to the forward code."""
    return (
        ("^embed$", P(None, None)),
        (r"wqkv$", P(None, None, tp_axis, None)),  # heads sharded
        (r"wkv$", P(None, None, tp_axis, None)),  # kv heads sharded
        (r"wq$", P(None, tp_axis, None)),
        (r"wo$", P(tp_axis, None, None)),
        (r"w_up$", P(None, tp_axis)),  # hidden dim sharded
        (r"w_down$", P(tp_axis, None)),
        # ln/final_ln scales: unmatched → replicated P()
    )


def param_specs(cfg: ProbeModelConfig, tp_axis: str = "model") -> Dict:
    """PartitionSpec tree matching init_params — the
    :func:`param_partition_rules` regex rules resolved over the
    abstract parameter tree (tests pin the result against the
    hand-threaded megatron layout this replaced)."""
    from activemonitor_tpu.parallel.partition import match_partition_rules

    abstract = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    return match_partition_rules(param_partition_rules(tp_axis), abstract)


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale.astype(x.dtype)


def apply_block(
    x: jax.Array, layer: Dict, cfg: ProbeModelConfig, attention_fn=None
) -> jax.Array:
    """One decoder block on [B, S, D]. ``attention_fn(q, k, v) -> attn``
    overrides the attention mechanism (ring attention for the
    context-parallel path); the default is dense causal. Shared by the
    dense, context-parallel, and pipeline-parallel forwards so the
    paths cannot drift."""
    dt = cfg.dtype
    if attention_fn is None:
        attention_fn = partial(dense_causal_attention, cfg=cfg)
    h = _rmsnorm(x, layer["ln1"]["scale"])
    if "wqkv" in layer:
        qkv = jnp.einsum("bsd,dthk->tbshk", h, layer["wqkv"].astype(dt))
        q, key, val = qkv[0], qkv[1], qkv[2]
    else:  # GQA: separate q and narrower kv projections
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
        kv = jnp.einsum("bsd,dthk->tbshk", h, layer["wkv"].astype(dt))
        key, val = kv[0], kv[1]
    attn = attention_fn(q, key, val)  # [B, S, H, K]
    x = x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(dt))
    h = _rmsnorm(x, layer["ln2"]["scale"])
    up = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt)))
    return x + jnp.einsum("bsf,fd->bsd", up, layer["w_down"].astype(dt))


def flash_attention_fn(cfg: ProbeModelConfig, mesh=None, axis: str = "model"):
    """Attention override running the fused Pallas kernel
    (ops/flash_attention.py, differentiable via its custom VJP).

    Unsharded (no mesh, or a 1-sized axis) the kernel is called
    directly. With heads tensor-parallel over ``mesh[axis]`` it runs
    under ``shard_map`` — attention is embarrassingly parallel across
    heads, so each shard computes its local heads with zero
    communication, exactly what XLA's sharding propagation does for the
    unfused path. Unlike GSPMD (which pads uneven shardings for the
    dense path), shard_map needs the heads dim to divide evenly — a
    too-large tp axis is rejected up front with the actual constraint
    rather than a trace-time shape error."""
    from activemonitor_tpu.parallel.partition import shard_map

    from activemonitor_tpu.ops.flash_attention import flash_attention

    def fused(q, k, v):
        return flash_attention(q, k, v, causal=True)

    if mesh is None or mesh.shape.get(axis, 1) == 1:
        return fused
    axis_size = mesh.shape[axis]
    if cfg.n_heads % axis_size:
        raise ValueError(
            f"flash attention needs n_heads ({cfg.n_heads}) divisible by "
            f"the '{axis}' mesh axis ({axis_size}); use dense attention "
            "or a smaller tensor-parallel group"
        )
    if cfg.kv_heads % axis_size:
        raise ValueError(
            f"flash attention needs n_kv_heads ({cfg.kv_heads}) divisible "
            f"by the '{axis}' mesh axis ({axis_size}); each shard must "
            "hold whole K/V heads for its query-head group"
        )
    spec = P("data" if "data" in mesh.shape else None, None, axis, None)
    return shard_map(
        fused, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False
    )


def ring_attention_fn(
    cfg: ProbeModelConfig, mesh, axis: str = "sp", tp_axis: str = "model"
):
    """Attention override running sequence-parallel ring attention
    (ops/ring_attention.py, differentiable via its custom VJP) inside a
    composed train step.

    The sequence dim shards over ``mesh[axis]``; batch rides "data" and
    heads ride ``tp_axis`` when those axes exist — both are
    embarrassingly parallel for the ring (the only communication is the
    K/V rotation over ``axis``), so a dp×tp×sp step needs no extra
    collectives beyond what the ring and XLA's sharding propagation
    already insert."""
    from activemonitor_tpu.ops.ring_attention import ring_attention

    if axis not in mesh.shape:
        raise ValueError(
            f"ring attention needs a {axis!r} mesh axis, mesh has {dict(mesh.shape)}"
        )
    heads_axis = None
    if tp_axis in mesh.shape and mesh.shape[tp_axis] > 1:
        if cfg.n_heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"ring attention needs n_heads ({cfg.n_heads}) divisible by "
                f"the {tp_axis!r} mesh axis ({mesh.shape[tp_axis]})"
            )
        if cfg.kv_heads % mesh.shape[tp_axis]:
            raise ValueError(
                f"ring attention needs n_kv_heads ({cfg.kv_heads}) divisible "
                f"by the {tp_axis!r} mesh axis ({mesh.shape[tp_axis]}); each "
                "shard must hold whole K/V heads for its query-head group"
            )
        heads_axis = tp_axis
    # the composed layout is DATA: a rules tuple resolved inside
    # ring_attention, not a spec threaded through kernel code
    from activemonitor_tpu.ops.ring_attention import ring_partition_rules

    rules = ring_partition_rules(
        axis,
        batch_axis="data" if "data" in mesh.shape else None,
        heads_axis=heads_axis,
    )

    def ring(q, k, v):
        return ring_attention(q, k, v, mesh, axis, causal=True, rules=rules)

    return ring


def dense_causal_attention(q, k, v, cfg: ProbeModelConfig):
    dt = cfg.dtype
    seq = q.shape[1]
    if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads for the einsum
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    causal = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
    scores = jnp.einsum("bshk,bthk->bhst", q, k) / jnp.sqrt(
        jnp.asarray(cfg.head_dim, dt)
    )
    scores = jnp.where(causal[None, None, :, :], scores, jnp.asarray(-1e9, dt))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
    return jnp.einsum("bhst,bthk->bshk", probs, v)


def _forward_with_attention(
    params: Dict, tokens: jax.Array, cfg: ProbeModelConfig, attention_fn,
    remat: bool = False,
) -> jax.Array:
    """Shared decoder body around :func:`apply_block`. ``remat``
    rematerializes each block's activations in the backward pass
    (``jax.checkpoint``) — the standard FLOPs-for-HBM trade that lets
    sequence length or depth grow past what saved activations allow."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]  # [B, S, D]

    def block(x, layer):
        return apply_block(x, layer, cfg, attention_fn)

    if remat:
        block = jax.checkpoint(block)
    for layer in params["layers"]:
        x = block(x, layer)
    x = _rmsnorm(x, params["final_ln"]["scale"])
    return jnp.einsum("bsd,vd->bsv", x, params["embed"].astype(dt)).astype(jnp.float32)


def forward(params: Dict, tokens: jax.Array, cfg: ProbeModelConfig) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V]. Jit-friendly: static
    shapes, lax-only control flow, bf16 compute."""
    return _forward_with_attention(
        params, tokens, cfg, partial(dense_causal_attention, cfg=cfg)
    )


def loss_fn(
    params: Dict, tokens: jax.Array, cfg: ProbeModelConfig, attention_fn=None,
    remat: bool = False,
) -> jax.Array:
    """Next-token cross-entropy (the training-step probe's objective).
    ``attention_fn`` overrides the attention mechanism (e.g.
    :func:`flash_attention_fn` for the fused-kernel training path);
    None means dense causal (apply_block's default). ``remat``
    rematerializes block activations in the backward."""
    logits = _forward_with_attention(
        params, tokens[:, :-1], cfg, attention_fn, remat=remat
    )
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)
    return jnp.mean(nll)


def forward_context_parallel(
    params: Dict, tokens: jax.Array, cfg: ProbeModelConfig, mesh, axis: str = "sp"
) -> jax.Array:
    """Long-context forward: the sequence axis lives sharded across
    ``mesh[axis]`` and attention runs as ring attention
    (ops/ring_attention.py), so a sequence n× longer than one device's
    memory fits. Everything else (embedding, norms, MLP) is pointwise
    along the sequence and needs no communication — XLA keeps those ops
    local to each shard; the only inter-device traffic is the K/V ring.
    """
    from activemonitor_tpu.ops.ring_attention import ring_attention

    def ring(q, k, v):
        return ring_attention(q, k, v, mesh, axis, causal=True)

    return _forward_with_attention(params, tokens, cfg, ring)


def init_kv_cache(cfg: ProbeModelConfig, batch: int, max_seq: int) -> Dict:
    """KV cache for autoregressive decoding: one [B, Hkv, S, Dh] pair
    per layer (heads-major — the fused decode kernel's tiling wants
    contiguous [S, Dh] planes per head), float-typed in the compute
    dtype. GQA caches only the kv_heads — the memory win that motivates
    grouped heads in serving. Capacity rounds up to a multiple of 8
    (Mosaic's tiling unit); position masking makes the slack inert."""
    cap = -(-max_seq // 8) * 8
    shape = (cfg.n_layers, batch, cfg.kv_heads, cap, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def prefill(
    params: Dict,
    cache: Dict,
    tokens: jax.Array,
    cfg: ProbeModelConfig,
    use_flash: bool = False,
):
    """Batched prompt ingestion — the serving cold half.

    Runs the causal forward over ``tokens`` [B, S] ONCE (big MXU-shaped
    matmuls; ``use_flash`` routes attention through the fused kernel)
    while writing every position's K/V into the cache, so decoding can
    start at position S. Returns (last-token logits [B, V], cache) —
    equivalent to S ``decode_step`` calls but without S tiny dispatches.
    """
    dt = cfg.dtype
    seq = tokens.shape[1]
    x = params["embed"].astype(dt)[tokens]  # [B, S, D]
    if use_flash:
        from activemonitor_tpu.ops.flash_attention import flash_attention

        attention_fn = lambda q, k, v: flash_attention(q, k, v, causal=True)
    else:
        attention_fn = partial(dense_causal_attention, cfg=cfg)
    for li, layer in enumerate(params["layers"]):
        # reuse apply_block (the single decoder-block definition — the
        # paths must not drift); the wrapper captures this layer's K/V
        # projections at trace time for cache banking
        banked: Dict = {}

        def capturing(q, k, v, _banked=banked):
            _banked["k"], _banked["v"] = k, v
            return attention_fn(q, k, v)

        x = apply_block(x, layer, cfg, capturing)
        # bank K/V heads-major ([B, Hkv, S, K]) for the decode kernel
        cache["k"] = cache["k"].at[li, :, :, :seq].set(
            jnp.swapaxes(banked["k"], 1, 2)
        )
        cache["v"] = cache["v"].at[li, :, :, :seq].set(
            jnp.swapaxes(banked["v"], 1, 2)
        )
    x = _rmsnorm(x[:, -1], params["final_ln"]["scale"])  # last position only
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(dt))
    return logits.astype(jnp.float32), cache


def decode_step(
    params: Dict, cache: Dict, token: jax.Array, pos: jax.Array,
    cfg: ProbeModelConfig, use_flash: bool = False,
):
    """One autoregressive decode step (the serving hot loop).

    token: [B] int32, pos: scalar int32 position. Returns (logits [B,V],
    updated cache). Static shapes throughout: the cache is full-length
    and masked by position, so the step jits once and reruns for every
    token (lax-friendly, no dynamic shapes). ``use_flash`` routes the
    cache attention through the fused decode kernel
    (ops/flash_attention.flash_decode): one blockwise HBM pass with the
    online-softmax state in VMEM, dead cache capacity skipped."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[token]  # [B, D]
    cap = cache["k"].shape[3]
    visible = jnp.arange(cap) <= pos  # [S]
    group = cfg.n_heads // cfg.kv_heads
    if use_flash:
        from activemonitor_tpu.ops.flash_attention import flash_decode
    for li, layer in enumerate(params["layers"]):
        h = _rmsnorm(x, layer["ln1"]["scale"])
        if "wqkv" in layer:
            qkv = jnp.einsum("bd,dthk->tbhk", h, layer["wqkv"].astype(dt))
            q, k_new, v_new = qkv[0], qkv[1], qkv[2]  # [B, H, K]
        else:  # GQA: q over n_heads, k/v over the narrower kv_heads
            q = jnp.einsum("bd,dhk->bhk", h, layer["wq"].astype(dt))
            kv = jnp.einsum("bd,dthk->tbhk", h, layer["wkv"].astype(dt))
            k_new, v_new = kv[0], kv[1]  # [B, Hkv, K]
        cache["k"] = cache["k"].at[li, :, :, pos].set(k_new)
        cache["v"] = cache["v"].at[li, :, :, pos].set(v_new)
        keys = cache["k"][li]  # [B, Hkv, S, K]
        values = cache["v"][li]
        if use_flash:
            attn = flash_decode(q, keys, values, pos)  # [B, H, K]
        else:
            # grouped view: [B, H, K] -> [B, Hkv, G, K]; each group of
            # query heads attends its shared kv head out of the cache
            qg = q.reshape(q.shape[0], cfg.kv_heads, group, cfg.head_dim)
            scores = jnp.einsum("bhgk,bhsk->bhgs", qg, keys) / jnp.sqrt(
                jnp.asarray(cfg.head_dim, dt)
            )
            scores = jnp.where(
                visible[None, None, None, :], scores, jnp.asarray(-1e9, dt)
            )
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
            attn = jnp.einsum("bhgs,bhsk->bhgk", probs, values)
            attn = attn.reshape(q.shape[0], cfg.n_heads, cfg.head_dim)
        x = x + jnp.einsum("bhk,hkd->bd", attn, layer["wo"].astype(dt))
        h = _rmsnorm(x, layer["ln2"]["scale"])
        up = jax.nn.gelu(jnp.einsum("bd,df->bf", h, layer["w_up"].astype(dt)))
        x = x + jnp.einsum("bf,fd->bd", up, layer["w_down"].astype(dt))
    x = _rmsnorm(x, params["final_ln"]["scale"])
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(dt))
    return logits.astype(jnp.float32), cache


def param_count(cfg: ProbeModelConfig) -> int:
    d, f, v, h, k = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_heads, cfg.head_dim
    qkv = d * h * k + 2 * d * cfg.kv_heads * k  # q + (possibly grouped) kv
    per_layer = d + qkv + h * k * d + d + d * f + f * d
    return v * d + cfg.n_layers * per_layer + d
