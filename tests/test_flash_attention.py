"""Fused flash-attention kernel (ops/flash_attention.py) + probe.

Runs in Pallas interpret mode on the CPU mesh — the same code path
Mosaic compiles on TPU (measured there: ~90 TFLOP/s causal on v5e at
S=4096 with the default blocks, ~4-5x unfused XLA attention).
"""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.flash_attention import attention_flops, flash_attention
from activemonitor_tpu.ops.ring_attention import reference_attention


def _qkv(batch=1, seq=256, heads=2, head_dim=64, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(0), 3)
    return tuple(
        jax.random.normal(k, (batch, seq, heads, head_dim), dtype) for k in keys
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(256, 256), (64, 64), (64, 128), (128, 64)])
def test_matches_reference(causal, block_q, block_k):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == want.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_bf16_inputs_match_reference():
    q, k, v = _qkv(batch=2, seq=128, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=64, block_k=64)
    want = reference_attention(q, k, v)
    err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )
    assert got.dtype == jnp.bfloat16
    assert err < 2e-2  # bf16 output rounding


def test_blocks_clamped_to_seq():
    # default blocks (1024/512) exceed seq — must clamp, not raise
    q, k, v = _qkv(seq=128)
    got = flash_attention(q, k, v)
    want = reference_attention(q, k, v)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_bhsd_layout_matches_bshd():
    q, k, v = _qkv(seq=128)
    want = flash_attention(q, k, v, block_q=64, block_k=64)
    got = flash_attention(
        *(jnp.swapaxes(x, 1, 2) for x in (q, k, v)),
        block_q=64,
        block_k=64,
        layout="bhsd",
    )
    assert float(jnp.max(jnp.abs(jnp.swapaxes(got, 1, 2) - want))) == 0.0


def test_bad_layout_rejected():
    q, k, v = _qkv(seq=128)
    with pytest.raises(ValueError, match="layout"):
        flash_attention(q, k, v, layout="sbhd")


def test_indivisible_seq_rejected():
    q, k, v = _qkv(seq=192)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, block_q=128, block_k=128)


def test_mismatched_shapes_rejected():
    q, k, v = _qkv(seq=128)
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(q, k[:, :64], v)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _qkv(seq=256)
    tgt = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(
            (flash_attention(q, k, v, causal=causal, block_q=128, block_k=128) - tgt)
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum((reference_attention(q, k, v, causal=causal) - tgt) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", got, want):
        err = float(jnp.max(jnp.abs(a - b)))
        assert err < 1e-4, f"d{name} err {err}"


def test_gradients_adapt_blocks_to_any_forward_seq():
    # seq=384 divides the forward's 128-blocks but not the backward's
    # preferred 1024x256 — the backward must shrink its blocks, not raise
    q, k, v = _qkv(seq=384)
    got = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, block_q=128, block_k=128) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(got, want):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_fit_block_prefers_tileable_divisors():
    from activemonitor_tpu.ops.flash_attention import _fit_block

    assert _fit_block(4096, 1024) == 1024
    assert _fit_block(384, 256) == 192  # divisor, multiple of 8
    assert _fit_block(640, 256) == 160
    assert _fit_block(24, 1024) == 24  # 8-aligned seq: whole seq is legal
    with pytest.raises(ValueError, match="no TPU-tileable block"):
        _fit_block(100, 256)  # non-8-aligned: Mosaic would reject any tile


def test_non_tileable_seq_rejected():
    # seq=100 divides its clamped block (100) but a 100-row tile is not
    # a multiple of 8 — Mosaic rejects it on real TPU, so the validator
    # must reject it on CPU too instead of letting interpret mode pass
    q, k, v = _qkv(seq=100)
    with pytest.raises(ValueError, match="multiples of 8"):
        flash_attention(q, k, v)


def test_gradients_bf16_and_uneven_blocks():
    # bwd uses its own block shape (1024x256 clamped to seq) — distinct
    # q/k blocking must still produce reference-level gradients
    q, k, v = _qkv(seq=128, dtype=jnp.bfloat16)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

        return inner

    got = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert a.dtype == jnp.bfloat16
        scale = max(1e-9, float(jnp.max(jnp.abs(b.astype(jnp.float32)))))
        rel = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))) / scale
        assert rel < 5e-2  # bf16 grads


def test_attention_flops_causal_half():
    full = attention_flops(2, 256, 4, 64, causal=False)
    causal = attention_flops(2, 256, 4, 64, causal=True)
    assert full == 4.0 * 64 * 2 * 4 * 256 * 256
    assert abs(causal / full - 0.5) < 0.01  # (S+1)/2S


def test_model_flash_attention_matches_dense_on_mesh():
    # the probe model's flash path (shard_map over tp heads on the
    # dp x tp mesh) must agree with dense attention in loss and grads
    from activemonitor_tpu.models.probe_model import (
        flash_attention_fn,
        init_params,
        loss_fn,
        tiny_config,
    )
    from activemonitor_tpu.parallel.mesh import make_2d_mesh

    mesh = make_2d_mesh()
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    dense = float(loss_fn(params, tokens, cfg))
    flash = float(loss_fn(params, tokens, cfg, flash_attention_fn(cfg, mesh)))
    assert abs(dense - flash) < 1e-3  # bf16 compute
    grads_dense = jax.grad(lambda p: loss_fn(p, tokens, cfg))(params)
    grads_flash = jax.grad(
        lambda p: loss_fn(p, tokens, cfg, flash_attention_fn(cfg, mesh))
    )(params)
    errs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), grads_dense, grads_flash
    )
    assert max(jax.tree.leaves(errs)) < 5e-3


def test_model_flash_rejects_oversized_tp_axis():
    from activemonitor_tpu.models.probe_model import flash_attention_fn, tiny_config
    from jax.sharding import Mesh
    import numpy as np

    # tiny_config has 4 heads; an 8-wide model axis cannot shard them
    mesh = Mesh(np.array(jax.devices()).reshape(1, 8), ("data", "model"))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention_fn(tiny_config(), mesh)


def test_training_step_probe_flash_attention():
    from activemonitor_tpu.probes import training_step

    result = training_step.run(
        tiny=True, batch_per_device=2, seq=32, steps=1, attention="flash"
    )
    assert result.ok
    assert result.details["attention"] == "flash"


def test_probe_runs_on_cpu():
    from activemonitor_tpu.probes import flash

    result = flash.run(batch=1, seq=256, heads=2, head_dim=64, iters=2)
    assert result.ok
    names = {m.name for m in result.metrics}
    assert "flash-attention-max-error" in names
    assert "flash-attention-tflops" in names
    assert result.details["max_error"] < 1e-2
    # off-TPU: timing falls back to the XLA expression
    assert result.details["kernel"] == "xla"


def test_probe_contract_line_parses():
    import json

    from activemonitor_tpu.probes import flash

    result = flash.run(batch=1, seq=128, heads=2, head_dim=64, iters=2)
    parsed = json.loads(result.contract_line())
    assert {m["name"] for m in parsed["metrics"]} >= {
        "flash-attention-max-error",
        "flash-attention-tflops",
    }


def test_probe_tolerance_drives_gradient_gate():
    from activemonitor_tpu.probes import flash

    # an absurdly tight tolerance must fail the combined verdict (the
    # gradient gate is 2.5x of it — ADVICE r2: --tolerance must bite)
    result = flash.run(batch=1, seq=128, heads=2, head_dim=64, iters=2, tolerance=1e-9)
    assert not result.ok
    assert result.details["grad_tolerance"] == 2.5e-9


def test_sweep_produces_block_tables():
    from activemonitor_tpu.probes import flash

    result = flash.sweep(
        batch=1, seq=128, heads=2, head_dim=64, iters=1, rounds=1,
        fwd_blocks=(64, 128), bwd_blocks=((64, 64), (128, 64)),
    )
    assert result.ok
    fwd = result.details["forward_table_tflops"]
    assert set(fwd) == {"64x64", "64x128", "128x64", "128x128"}
    assert result.details["best_forward"] in fwd
    train = result.details["train_table_tflops"]
    assert set(train) == {"64x64", "128x64"}
    names = {m.name for m in result.metrics}
    assert "flash-sweep-best-fwd-tflops" in names
