"""Pipeline parallelism — layer stages across the mesh, GPipe-style.

The layer stack is split into S contiguous stages, one per device on
the "pp" axis; a batch is split into M microbatches that flow through
the stages, each hop a single neighbor ``ppermute``. The schedule is a
``lax.scan`` over M + S − 1 ticks: at tick t, stage s computes
microbatch t − s (bubbles at the ends are masked out).

WEIGHT memory is the pipelined resource here: each device holds only
its stage's layers — the property that lets a model taller than one
device's HBM run at all. Activations are NOT minimized in this
implementation: the microbatch set is replicated to every stage and
outputs are combined with a full psum, which is the right fidelity for
a correctness/health probe but not a memory-optimal training pipeline
(production pipelines stream microbatches into stage 0 and emit from
the last stage only).

Layer parameters arrive STACKED: every leaf of the layer dict gains a
leading ``n_layers`` axis (see :func:`stack_layer_params`), which is
sharded over "pp" so each stage holds its own slice — inside
``shard_map`` each device scans over its ``layers_per_stage`` local
layers with the shared :func:`~activemonitor_tpu.models.probe_model.apply_block`.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from activemonitor_tpu.parallel.partition import (
    match_partition_rules,
    resolve_tiers,
    shard_map,
)
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.models.probe_model import ProbeModelConfig, apply_block


def stack_layer_params(layers) -> Dict:
    """List-of-layer-dicts -> one dict whose leaves have a leading
    n_layers axis (sharding-friendly: the leading axis splits over pp)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *layers)


def stacked_layer_rules(pp_axis: str = "pp", tp_axis: str = "model"):
    """Partition rules for a :func:`stack_layer_params` tree: every
    leaf's leading layer axis splits over ``pp_axis`` (each stage holds
    its own layers) and within each layer the megatron tensor-parallel
    layout (probe_model.param_partition_rules, shifted one dim right)
    splits over ``tp_axis``. Being DATA, the pp×tp layout re-meshes —
    including the GQA wq/wkv split the hand-written spec dict never
    covered — by editing this tuple, not the pipeline schedule."""
    return (
        (r"scale$", P(pp_axis, None)),
        (r"wqkv$", P(pp_axis, None, None, tp_axis, None)),  # heads sharded
        (r"wkv$", P(pp_axis, None, None, tp_axis, None)),  # kv heads sharded
        (r"wq$", P(pp_axis, None, tp_axis, None)),
        (r"wo$", P(pp_axis, tp_axis, None, None)),
        (r"w_up$", P(pp_axis, None, tp_axis)),  # hidden dim sharded
        (r"w_down$", P(pp_axis, tp_axis, None)),
    )


def stacked_layer_specs(
    pp_axis: str = "pp", tp_axis: str = "model", layers=None
) -> Dict:
    """PartitionSpec tree matching :func:`stack_layer_params` output —
    :func:`stacked_layer_rules` resolved over ``layers`` (a stacked
    parameter tree; default: an abstract MHA-shaped template, the
    layout the hand-threaded spec dict this replaced covered)."""
    if layers is None:
        leaf = jax.ShapeDtypeStruct
        layers = {
            "ln1": {"scale": leaf((2, 2), jnp.float32)},
            "wqkv": leaf((2, 2, 3, 2, 2), jnp.float32),
            "wo": leaf((2, 2, 2, 2), jnp.float32),
            "ln2": {"scale": leaf((2, 2), jnp.float32)},
            "w_up": leaf((2, 2, 2), jnp.float32),
            "w_down": leaf((2, 2, 2), jnp.float32),
        }
    return match_partition_rules(stacked_layer_rules(pp_axis, tp_axis), layers)


def pipeline_io_rules(axis: str = "pp"):
    """Rules for the pipelined shard_map boundary itself: stacked layer
    leaves shard their leading layer axis over ``axis``; the microbatch
    block (and the collected outputs) replicate to every stage (module
    docstring: probe fidelity, not a memory-optimal pipeline)."""
    return (
        (r"^layers(/|$)", P(axis)),
        (r"^(micro|out)$", P(None, None, None, None)),
    )


def pipeline_forward_blocks(
    stacked_layers: Dict,
    x: jax.Array,
    cfg: ProbeModelConfig,
    mesh: Mesh,
    axis: str = "pp",
    num_microbatches: int = 0,
    composed: bool = False,
    overlap: bool = False,
    rules=None,
    allreduce_schedule: str = "auto",
) -> jax.Array:
    """Run the block stack over ``x`` [B, S, D] with the layers
    pipelined across ``mesh[axis]``. Embedding/head stay outside (they
    are cheap and replicated). Returns [B, S, D].

    With ``composed=True`` the shard_map is MANUAL only over ``axis``
    (``axis_names={axis}``): every other mesh axis stays
    compiler-managed, so each stage's layer compute keeps whatever
    data/tensor shardings its parameters and activations carry — this
    is how dp×tp×pp composes on one mesh (the pipeline schedule is
    hand-written ppermute over "pp"; the per-stage matmul collectives
    over "model" and the gradient psum over "data" are still inserted
    by XLA from the sharding annotations, the scaling-book split of
    labor). Composed mode must run under ``jax.jit`` — partially-manual
    shard_map has no eager path (JAX 0.9 rejects it outside a trace).

    With ``overlap=True`` the schedule pre-rotates stage activations:
    each tick first ISSUES the ppermute of the previous tick's output
    (an ``optimization_barrier`` pins the send ahead of the compute in
    the schedule) and then runs this tick's stage compute on the
    activation that arrived last tick — per-tick ICI time hides under
    layer math instead of serializing after it. The stage boundary
    gains one tick of latency, so fill/drain stretches from S−1 to
    2(S−1) bubble ticks (M + 2(S−1) total): a win when hop time is a
    visible slice of tick time (comm-bound), a small loss when
    microbatches are so small that bubbles dominate (docs/training.md
    "Compute–communication overlap"). Numerics are identical either
    way — the schedule only changes WHEN activations ride the links.

    The shard_map boundary's specs resolve from partition RULES
    (:func:`pipeline_io_rules` by default; pass ``rules=`` to re-mesh).
    The final output combine routes through
    ``parallel/autotune.all_reduce`` with ``allreduce_schedule``
    (default ``"auto"``: the tuned decision table picks the schedule
    per payload octave, falling back to the bitwise-identical XLA psum
    when nothing is tuned for this axis size).

    On a two-tier ("dcn", "ici") mesh that carries the tiers instead
    of ``axis`` (``parallel/partition.resolve_tiers``), the stage ring
    linearizes over both tiers dcn-major (the inter-stage ppermute
    rides an axis pair) and the output combine dispatches the
    HIERARCHICAL all-reduce with per-tier tuned winners — zero
    call-site changes.
    """
    stage_axes, _tier_reason = resolve_tiers(mesh, axis)
    axis = stage_axes[0] if len(stage_axes) == 1 else stage_axes
    if len(stage_axes) > 1 and allreduce_schedule not in ("auto", "xla"):
        # a flat zoo token names a single-tier schedule; silently
        # downgrading it to "auto" would attribute measurements to a
        # schedule that never ran (the resolve_grad_sync discipline)
        raise ValueError(
            f"allreduce_schedule {allreduce_schedule!r} is a flat "
            "schedule token; the two-tier combine takes auto/xla"
        )
    n_stages = 1
    for a in stage_axes:
        n_stages *= mesh.shape[a]
    batch = x.shape[0]
    m = num_microbatches or n_stages
    if batch % m:
        raise ValueError(f"batch {batch} not divisible into {m} microbatches")
    n_layers = jax.tree.leaves(stacked_layers)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers do not split over {n_stages} stages")

    # composed mode keeps the shard_map boundary (inputs, carries, the
    # final psum) in float32: XLA's CPU AllReducePromotion pass (as of
    # ~2026-07) crashes cloning the bf16 all-reduces that the
    # partially-manual transpose emits ("Invalid binary instruction
    # opcode copy"). Stage compute still runs in cfg.dtype; on TPU this
    # costs 2x ppermute bytes in a path whose job is correctness.
    wire_dt = jnp.float32 if composed else x.dtype
    micro = x.astype(wire_dt).reshape(m, batch // m, *x.shape[1:])  # [M, mb, S, D]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    io_specs = match_partition_rules(
        rules if rules is not None else pipeline_io_rules(axis),
        {"layers": stacked_layers, "micro": micro, "out": micro},
        mesh=mesh,
    )

    def stage_apply(local_layers, act):
        """Scan this stage's local layers over the activation."""

        def body(h, layer):
            return apply_block(h, layer, cfg), None

        out, _ = jax.lax.scan(body, act.astype(x.dtype), local_layers)
        return out.astype(wire_dt)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(io_specs["layers"], io_specs["micro"]),
        out_specs=io_specs["out"],
        check_vma=False,
        axis_names=frozenset(stage_axes) if composed else frozenset(),
    )
    def pipelined(local_layers, micro_all):
        # local_layers leaves: [layers_per_stage, ...]; micro_all: [M, mb, S, D]
        stage = jax.lax.axis_index(axis)
        mb_shape = micro_all.shape[1:]

        def bank(outputs, y, out_idx):
            """The last stage banks microbatch ``out_idx`` when real."""
            valid = (stage == n_stages - 1) & (out_idx >= 0)
            return jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(valid, y, outputs[jnp.clip(out_idx, 0, m - 1)]),
                jnp.clip(out_idx, 0, m - 1),
                axis=0,
            )

        def tick(carry, t):
            act, outputs = carry
            # stage 0 injects microbatch t (clamped; bubbles are masked)
            inject = jnp.clip(t, 0, m - 1)
            x_in = jnp.where(stage == 0, micro_all[inject], act)
            y = stage_apply(local_layers, x_in)
            outputs = bank(outputs, y, t - (n_stages - 1))
            # hand activations to the next stage
            act = jax.lax.ppermute(y, axis, perm)
            return (act, outputs), None

        def tick_overlap(carry, t):
            act_recv, y_prev, outputs = carry
            # pre-rotate: last tick's output starts its hop NOW, riding
            # the links while this tick's stage compute runs (the
            # barrier pins the send ahead of the compute)
            act_next = jax.lax.ppermute(y_prev, axis, perm)
            act_next, act_recv = jax.lax.optimization_barrier(
                (act_next, act_recv)
            )
            inject = jnp.clip(t, 0, m - 1)
            x_in = jnp.where(stage == 0, micro_all[inject], act_recv)
            y = stage_apply(local_layers, x_in)
            # each stage boundary costs 2 ticks (compute, then the
            # overlapped transfer lands next tick): stage s runs
            # microbatch t - 2s, the last stage banks t - 2(S-1)
            outputs = bank(outputs, y, t - 2 * (n_stages - 1))
            return (act_next, y, outputs), None

        act0 = jnp.zeros(mb_shape, micro_all.dtype)
        outputs0 = jnp.zeros((m, *mb_shape), micro_all.dtype)
        if overlap:
            (_, _, outputs), _ = jax.lax.scan(
                tick_overlap,
                (act0, act0, outputs0),
                jnp.arange(m + 2 * (n_stages - 1)),
            )
        else:
            (_, outputs), _ = jax.lax.scan(
                tick, (act0, outputs0), jnp.arange(m + n_stages - 1)
            )
        # broadcast the last stage's collected outputs to every stage —
        # the ops-layer reduction the PR-8 decision table now reaches:
        # schedule="auto" dispatches the tuned winner for this payload
        # octave (untuned: the XLA psum, bitwise-identical to before)
        from activemonitor_tpu.parallel import autotune

        is_last = (stage == n_stages - 1).astype(outputs.dtype)
        # on a two-tier stage ring the combine reduces over the axis
        # PAIR — the hierarchical dispatch (per-tier n sizes required;
        # flat zoo tokens were rejected up front)
        combine_n = (
            tuple(mesh.shape[a] for a in stage_axes)
            if len(stage_axes) > 1 else n_stages
        )
        return autotune.all_reduce(
            outputs * is_last, axis, schedule=allreduce_schedule,
            n=combine_n,
        )

    out = pipelined(stacked_layers, micro)  # [M, mb, S, D]
    return out.reshape(batch, *x.shape[1:]).astype(x.dtype)
