"""MXU matmul probe.

Times large bf16 matmuls — the op the systolic array exists for — and
compares the best achieved TFLOP/s against the chip's rated bf16 peak.
A chip delivering well under rated peak on a clean square matmul is
throttled, misconfigured, or sick.

A small dimension sweep, not one size: which dim the compiler tiles
best varies by chip generation (on v5e, 4096 consistently lands nearer
peak than 8192), and the probe's job is to measure what the chip CAN
do — the max over dims is the right health signal, with the per-dim
numbers kept in the details.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds

log = logging.getLogger("activemonitor.probes")


def _measure(dim: int, iters: int) -> float:
    a = jax.random.normal(jax.random.key(0), (dim, dim), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (dim, dim), jnp.bfloat16)

    def make_chain(k):
        @jax.jit
        def chain(a, b):
            x = b
            for _ in range(k):  # data-dependent: each feeds the next
                x = jnp.dot(a, x, preferred_element_type=jnp.bfloat16)
            return x.astype(jnp.float32).sum()

        return chain

    # wide k spread: the delta must tower over per-sample overhead
    # variance, or the min-based estimate can overshoot physically
    # impossible FLOP rates (>1.0 of rated) as easily as undershoot
    seconds = chain_delta_seconds(make_chain, a, b, k1=4, k2=16, iters=iters)
    return 2 * dim**3 / seconds / 1e12


def run(
    dim: Optional[int] = None,
    iters: int = 10,
    threshold: float = 0.75,
    dims: Sequence[int] = (4096, 8192),
) -> ProbeResult:
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if dim is not None:
        dims = (dim,)  # explicit dim: no sweep (CLI --dim)
    requested_dims = tuple(sorted(set(dims)))
    dims = requested_dims
    if not on_tpu:
        # any large dim is downsized off-TPU (a 4096 bf16 chain takes
        # minutes on CPU and there is no rated comparison there) —
        # loudly, and recorded in the details below, so numbers are
        # never silently compared across the clamp
        dims = tuple(sorted({1024 if d > 2048 else d for d in requested_dims}))
        if dims != requested_dims:
            log.warning(
                "matmul dims %s downsized to %s off-TPU; numbers are NOT "
                "comparable to a TPU run", requested_dims, dims,
            )

    per_dim = {d: _measure(d, iters) for d in dims}
    dim, tflops = max(per_dim.items(), key=lambda kv: kv[1])
    seconds = 2 * dim**3 / tflops / 1e12

    rated = rated_for(device.device_kind)
    metrics = [
        ProbeMetric("mxu-matmul-tflops", tflops, help="Achieved bf16 matmul TFLOP/s")
    ]
    details = {
        "dim": dim,
        "per_dim_tflops": {d: round(v, 1) for d, v in per_dim.items()},
        "seconds_per_op": seconds,
        "device_kind": device.device_kind,
    }
    if tuple(dims) != requested_dims:
        details["requested_dims"] = list(requested_dims)  # downsized off-TPU
    ok = True
    if rated is not None and on_tpu:
        fraction = tflops / rated.bf16_tflops
        metrics.append(
            ProbeMetric(
                "mxu-fraction-of-rated", fraction, help="Achieved / rated bf16 peak"
            )
        )
        details["rated_tflops"] = rated.bf16_tflops
        details["fraction"] = round(fraction, 3)
        ok = fraction >= threshold
        summary = f"matmul {tflops:.0f} TFLOP/s = {fraction:.0%} of rated {rated.bf16_tflops:.0f}"
    else:
        summary = f"matmul {tflops:.2f} TFLOP/s on {device.platform} (no rated comparison)"
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
