"""Prometheus metrics collectors."""

from activemonitor_tpu.metrics.collector import (
    LABEL_HC,
    LABEL_WF,
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)

__all__ = [
    "LABEL_HC",
    "LABEL_WF",
    "MetricsCollector",
    "WORKFLOW_LABEL_HEALTHCHECK",
    "WORKFLOW_LABEL_REMEDY",
]
