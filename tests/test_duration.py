import pytest

from activemonitor_tpu.utils import parse_go_duration


@pytest.mark.parametrize(
    "text,seconds",
    [
        ("1m", 60.0),
        ("3s", 3.0),
        ("1m30s", 90.0),
        ("1.5h", 5400.0),
        ("2h45m", 9900.0),
        ("300ms", 0.3),
        ("0", 0.0),
        ("-10s", -10.0),
    ],
)
def test_parse_valid(text, seconds):
    assert parse_go_duration(text) == pytest.approx(seconds)


@pytest.mark.parametrize("text", ["", "abc", "10", "1d", "s", "1m 30s"])
def test_parse_invalid(text):
    with pytest.raises(ValueError):
        parse_go_duration(text)
