"""HBM memory-headroom probe.

Two layers of signal:

1. ``memory_stats()`` from the PJRT device (bytes in use / limit /
   peak) when the runtime exposes it — on-host TPUs do; tunneled or
   virtual devices may not, in which case those gauges are omitted;
2. an allocation smoke test: materialize-and-free a caller-sized
   buffer, proving that much contiguous headroom actually exists (an
   OOM here means the chip is carrying leaked buffers — the
   slow-creep failure mode long-lived TPU workloads hit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def run(probe_gb: float = 1.0) -> ProbeResult:
    device = jax.devices()[0]
    metrics = []
    details = {"device_kind": device.device_kind}

    stats = None
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        in_use = float(stats.get("bytes_in_use", 0))
        limit = float(stats.get("bytes_limit", 0))
        peak = float(stats.get("peak_bytes_in_use", 0))
        metrics.append(
            ProbeMetric("hbm-bytes-in-use", in_use, help="HBM bytes currently allocated")
        )
        if limit:
            metrics.append(
                ProbeMetric(
                    "hbm-utilization",
                    in_use / limit,
                    help="HBM bytes in use / bytes limit",
                )
            )
            details["bytes_limit_gb"] = round(limit / 1e9, 2)
        if peak:
            metrics.append(
                ProbeMetric("hbm-peak-bytes", peak, help="Peak HBM bytes in use")
            )
        details["bytes_in_use_gb"] = round(in_use / 1e9, 3)
    else:
        details["memory_stats"] = "unavailable on this runtime"

    # allocation smoke: the headroom must really exist
    elems = max(1, int(probe_gb * 1e9 / 4))
    cols = 1024
    rows = max(1, elems // cols)
    alloc_ok = True
    try:
        buf = jax.device_put(jnp.ones((rows, cols), jnp.float32), device)
        float(buf[0, 0])  # force materialization
        del buf
    except Exception as e:
        alloc_ok = False
        details["allocation_error"] = repr(e)[:200]
    metrics.append(
        ProbeMetric(
            "hbm-headroom-probe-ok",
            1.0 if alloc_ok else 0.0,
            help=f"1 when a {probe_gb} GB buffer could be allocated and freed",
        )
    )
    details["probe_gb"] = probe_gb

    summary = (
        f"{probe_gb} GB headroom {'OK' if alloc_ok else 'FAILED'}"
        + (
            f", {details.get('bytes_in_use_gb', '?')} GB in use"
            if stats
            else " (no memory_stats on this runtime)"
        )
    )
    return ProbeResult(ok=alloc_ok, summary=summary, metrics=metrics, details=details)
