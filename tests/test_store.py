"""Artifact store tests (reference test model: internal/store/store_test.go:
httptest servers as fake endpoints, TLS-verify secure default, 404/network errors)."""

import ssl
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from activemonitor_tpu.api import ArtifactLocation, FileArtifact, URLArtifact
from activemonitor_tpu.store import (
    FileReader,
    InlineReader,
    URLReader,
    UnknownArtifactLocation,
    get_artifact_reader,
)

WF = b"apiVersion: argoproj.io/v1alpha1\nkind: Workflow\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/wf.yaml":
            self.send_response(200)
            self.end_headers()
            self.wfile.write(WF)
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *args):
        pass


@pytest.fixture()
def http_server():
    srv = HTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_inline_reader():
    assert InlineReader("hello: world").read() == b"hello: world"


def test_inline_reader_empty_rejected():
    with pytest.raises(ValueError):
        InlineReader("")


def test_dispatch_inline_first():
    loc = ArtifactLocation(inline="a: b", url=URLArtifact(path="http://x/"))
    assert isinstance(get_artifact_reader(loc), InlineReader)


def test_dispatch_unknown_location():
    with pytest.raises(UnknownArtifactLocation):
        get_artifact_reader(ArtifactLocation())


def test_url_reader_reads(http_server):
    r = URLReader(URLArtifact(path=f"{http_server}/wf.yaml"))
    assert r.read() == WF


def test_url_reader_404(http_server):
    r = URLReader(URLArtifact(path=f"{http_server}/missing.yaml"))
    with pytest.raises(IOError):
        r.read()


def test_url_reader_network_error():
    r = URLReader(URLArtifact(path="http://127.0.0.1:1/wf.yaml"))
    with pytest.raises(Exception):
        r.read()


def test_url_verify_cert_nil_defaults_to_verify(tmp_path):
    """Secure default (reference: store_test.go
    TestURLReader_VerifyCert_Nil_DefaultsToVerify, url.go:29-32):
    a self-signed TLS server must be REJECTED when verifyCert is omitted
    and accepted when verifyCert: false."""
    import datetime

    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        pytest.skip("cryptography not available to mint a self-signed cert")

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "cert.pem"
    keyfile = tmp_path / "key.pem"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )

    srv = HTTPServer(("127.0.0.1", 0), _Handler)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=str(certfile), keyfile=str(keyfile))
    srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        url = f"https://127.0.0.1:{srv.server_port}/wf.yaml"
        # nil -> verify -> self-signed must fail
        with pytest.raises(Exception):
            URLReader(URLArtifact(path=url)).read()
        # explicit false -> skip verification -> succeeds
        r = URLReader(URLArtifact(path=url, verify_cert=False))
        assert r.read() == WF
    finally:
        srv.shutdown()


def test_file_reader(tmp_path):
    p = tmp_path / "wf.yaml"
    p.write_bytes(WF)
    r = get_artifact_reader(ArtifactLocation(file=FileArtifact(path=str(p))))
    assert isinstance(r, FileReader)
    assert r.read() == WF


def test_file_reader_missing_file(tmp_path):
    r = FileReader(FileArtifact(path=str(tmp_path / "nope.yaml")))
    with pytest.raises(FileNotFoundError):
        r.read()


def test_file_reader_empty_path_rejected():
    with pytest.raises(ValueError):
        FileReader(FileArtifact(path=""))
