"""Probe protocol and the custom-metrics output contract.

A probe is a callable returning a :class:`ProbeResult`. Run as a
workflow payload (any engine), its last stdout line is the JSON
custom-metrics contract the controller parses into Prometheus gauges
(reference contract: internal/metrics/collector.go:68-115 —
``{"metrics": [{name, value, metrictype, help}]}``), and its exit code
is the probe verdict Argo/the local engine turn into Succeeded/Failed.
"""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ProbeMetric:
    name: str
    value: float
    help: str = ""
    metrictype: str = "gauge"

    def to_contract(self) -> dict:
        return {
            "name": self.name,
            "value": float(self.value),
            "metrictype": self.metrictype,
            "help": self.help,
        }


@dataclass
class ProbeResult:
    ok: bool
    summary: str
    metrics: List[ProbeMetric] = field(default_factory=list)
    details: Dict = field(default_factory=dict)

    def contract_line(self) -> str:
        return json.dumps({"metrics": [m.to_contract() for m in self.metrics]})

    def emit(self) -> int:
        """Human-readable report to stderr, contract line to stdout,
        exit code for the engine."""
        print(("OK: " if self.ok else "FAIL: ") + self.summary, file=sys.stderr)
        for key, value in sorted(self.details.items()):
            print(f"  {key}: {value}", file=sys.stderr)
        print(self.contract_line(), flush=True)
        return 0 if self.ok else 1
