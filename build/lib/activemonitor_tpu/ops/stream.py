"""HBM streaming kernel (Pallas) — the memory-bandwidth probe's hot op.

A blocked scale-copy: each grid step moves one (block, 1024) tile
HBM → VMEM, scales on the VPU, and writes back — 2 bytes moved per
payload byte, the STREAM "scale" pattern. A hand-set grid keeps each
tile within VMEM while the pipeline overlaps the next tile's DMA with
the current tile's compute (Pallas double-buffers automatically).

On non-TPU platforms the kernel runs in interpret mode (correct but
slow), so tests exercise the same code path on CPU; the probe falls
back to a plain jnp expression for *timing* there.
"""

from __future__ import annotations

from functools import partial

import jax


def _scale_copy_kernel(in_ref, out_ref, *, scale):
    out_ref[:] = in_ref[:] * scale


def stream_scale_pallas(x: jax.Array, scale: float = 2.0, block_rows: int = 512):
    """Blocked scale-copy via Pallas; requires x.shape = (rows, 1024)
    with rows % block_rows == 0."""
    from jax.experimental import pallas as pl

    rows, cols = x.shape
    if rows % block_rows:
        raise ValueError(f"rows {rows} not divisible by block {block_rows}")
    interpret = jax.devices()[0].platform != "tpu"
    return pl.pallas_call(
        partial(_scale_copy_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        interpret=interpret,
    )(x)


def stream_scale_pallas_db(
    x: jax.Array, scale: float = 2.0, block_rows: int = 512
):
    """Explicitly double-buffered variant: the whole array stays in HBM
    (memory_space=ANY) and the kernel drives its own DMA pipeline — two
    VMEM slots per direction, chunk i+1's copy-in and chunk i-2's
    copy-out in flight while chunk i computes. This is what the
    automatic grid pipeline of :func:`stream_scale_pallas` does under
    the hood; owning the schedule lets the copy-out overlap too and
    gives a second, independent measurement of achievable bandwidth."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, cols = x.shape
    if rows % block_rows:
        raise ValueError(f"rows {rows} not divisible by block {block_rows}")
    num_chunks = rows // block_rows
    interpret = jax.devices()[0].platform != "tpu"

    def kernel(hbm_ref, out_ref):
        def body(scratch_in, scratch_out, in_sems, out_sems):
            def in_dma(slot, i):
                return pltpu.make_async_copy(
                    hbm_ref.at[pl.ds(i * block_rows, block_rows)],
                    scratch_in.at[slot],
                    in_sems.at[slot],
                )

            def out_dma(slot, i):
                return pltpu.make_async_copy(
                    scratch_out.at[slot],
                    out_ref.at[pl.ds(i * block_rows, block_rows)],
                    out_sems.at[slot],
                )

            in_dma(0, 0).start()

            def loop_body(i, _):
                slot = i % 2
                nxt = (i + 1) % 2

                @pl.when(i + 1 < num_chunks)
                def _():
                    in_dma(nxt, i + 1).start()

                in_dma(slot, i).wait()

                # this slot's previous copy-out must land before the
                # compute below overwrites the scratch it reads from
                @pl.when(i >= 2)
                def _():
                    out_dma(slot, i - 2).wait()

                scratch_out[slot] = scratch_in[slot] * scale
                out_dma(slot, i).start()

            jax.lax.fori_loop(0, num_chunks, loop_body, None)
            # drain the (up to two) outstanding copy-outs
            @pl.when(num_chunks >= 2)
            def _():
                out_dma(num_chunks % 2, num_chunks - 2).wait()

            out_dma((num_chunks - 1) % 2, num_chunks - 1).wait()

        pl.run_scoped(
            body,
            scratch_in=pltpu.VMEM((2, block_rows, cols), x.dtype),
            scratch_out=pltpu.VMEM((2, block_rows, cols), x.dtype),
            in_sems=pltpu.SemaphoreType.DMA((2,)),
            out_sems=pltpu.SemaphoreType.DMA((2,)),
        )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        interpret=interpret,
    )(x)


def stream_scale_xla(x: jax.Array, scale: float = 2.0):
    """XLA fallback of the same op. The optimization barrier stops XLA
    from algebraically collapsing a chain of these into a single
    multiply (x * scale**k), which would fake k× the real bandwidth."""
    return jax.lax.optimization_barrier(x * scale)
