"""Tracing tests: the span tracer itself, its correlation with logs and
events, and the full-lifecycle e2e trace of a FakeEngine reconcile —
the acceptance slice of ISSUE 1 (one cycle ⇒ one trace with the
dequeue/parse/submit/poll/status-write phases, all carrying the same
trace_id as the cycle's log lines and events).
"""

import asyncio
import json
import logging

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine, succeed_after
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import Tracer, current_span, current_trace_id
from activemonitor_tpu.utils.clock import FakeClock
from activemonitor_tpu.utils.logfmt import JsonFormatter

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


def make_hc(name="hc-a", repeat=60):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "workflow": {
                    "generateName": f"{name}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "sa",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


# ---------------------------------------------------------------------
# tracer unit behavior
# ---------------------------------------------------------------------


def test_span_nesting_and_context_restore():
    tracer = Tracer(FakeClock())
    assert current_span() is None
    with tracer.span("outer") as outer:
        assert current_span() is outer
        with tracer.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        assert current_span() is outer
    assert current_span() is None
    names = [s.name for s in tracer.finished_spans]
    assert names == ["inner", "outer"]  # finish order: inner closed first


def test_sibling_spans_without_root_get_separate_traces():
    tracer = Tracer(FakeClock())
    with tracer.span("a"):
        pass
    with tracer.span("b"):
        pass
    a, b = tracer.finished_spans
    assert a.trace_id != b.trace_id


def test_trace_forces_new_root_even_inside_a_span():
    tracer = Tracer(FakeClock())
    with tracer.span("old-cycle") as old:
        with tracer.trace("new-cycle") as fresh:
            assert fresh.trace_id != old.trace_id
            assert fresh.parent_id == ""


def test_durations_come_from_injected_clock():
    clock = FakeClock()

    async def run():
        tracer = Tracer(clock)
        with tracer.span("timed"):
            await clock.advance(7.5)
        return tracer.finished_spans[0]

    span = asyncio.run(run())
    assert span.duration == 7.5


def test_span_records_escaped_exception_type():
    tracer = Tracer(FakeClock())
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    assert tracer.finished_spans[0].error == "ValueError"


def test_ring_is_bounded():
    tracer = Tracer(FakeClock(), capacity=10)
    for i in range(35):
        with tracer.span(f"s{i}"):
            pass
    spans = tracer.finished_spans
    assert len(spans) <= 10
    assert spans[-1].name == "s34"  # newest retained, oldest dropped


def test_record_span_attaches_to_current_trace():
    clock = FakeClock(start=100.0)
    tracer = Tracer(clock)
    with tracer.span("root") as root:
        recorded = tracer.record_span("queue-wait", start=90.0)
    assert recorded.trace_id == root.trace_id
    assert recorded.parent_id == root.span_id
    assert recorded.duration == 10.0


def test_context_propagates_into_created_tasks():
    tracer = Tracer(FakeClock())

    async def run():
        async def child():
            return current_trace_id()

        with tracer.span("parent") as span:
            inherited = await asyncio.create_task(child())
        return span.trace_id, inherited

    trace_id, inherited = asyncio.run(run())
    assert inherited == trace_id


def test_timer_callbacks_fire_outside_any_span():
    """A timer armed inside a cycle's span must not adopt its callback
    into that (long-finished) trace — the wheel fires trace-clean."""
    from activemonitor_tpu.scheduler import TimerWheel

    clock = FakeClock()
    tracer = Tracer(clock)

    async def drive():
        wheel = TimerWheel(clock)
        seen = {}

        async def callback():
            seen["trace_id"] = current_trace_id()

        with tracer.span("arming-cycle"):
            wheel.schedule("k", 5.0, callback)
        await clock.advance(6.0)
        await wheel.shutdown()
        return seen["trace_id"]

    assert asyncio.run(drive()) == ""


def test_export_jsonl_roundtrip(tmp_path):
    tracer = Tracer(FakeClock())
    with tracer.span("a"):
        with tracer.span("b"):
            pass
    with tracer.span("c"):
        pass
    path = str(tmp_path / "traces.jsonl")
    assert tracer.export_jsonl(path) == 2  # two traces, one line each
    traces = list(Tracer.read_jsonl(path))
    assert len(traces) == 2
    assert traces[0]["span_count"] == 2
    assert {s["name"] for s in traces[0]["spans"]} == {"a", "b"}


def test_export_jsonl_rotates_through_the_shared_cap(tmp_path):
    """Satellite 1 (ISSUE 17): --trace-export routes through the same
    rotate_capped the journal and flight recorder use — a restart loop
    can no longer grow one unbounded trace dump, and the previous
    incarnation's export survives as the -1 rotation."""
    path = tmp_path / "traces.jsonl"
    for round_no in range(2):
        tracer = Tracer(FakeClock())
        with tracer.span(f"cycle-{round_no}"):
            pass
        # a 1-byte cap forces rotation on every export after the first
        assert tracer.export_jsonl(str(path), max_bytes=1) == 1
    assert path.exists()
    assert (tmp_path / "traces-1.jsonl").exists()
    # both generations still parse: the active file holds the newest
    # export, the rotation the previous one
    [current] = list(Tracer.read_jsonl(str(path)))
    [previous] = list(Tracer.read_jsonl(str(tmp_path / "traces-1.jsonl")))
    assert current["spans"][0]["name"] == "cycle-1"
    assert previous["spans"][0]["name"] == "cycle-0"


# ---------------------------------------------------------------------
# correlation: log lines and events carry the active trace
# ---------------------------------------------------------------------


def fmt_record(logger_name, msg, **extra):
    record = logging.LogRecord(
        logger_name, logging.INFO, __file__, 1, msg, (), None
    )
    for key, value in extra.items():
        setattr(record, key, value)
    return json.loads(JsonFormatter().format(record))


def test_json_formatter_emits_extra_fields():
    # the silent-drop fix: extra={...} structured fields survive
    doc = fmt_record("x", "hello", healthcheck="ns/hc", attempt=3)
    assert doc["msg"] == "hello"
    assert doc["healthcheck"] == "ns/hc"
    assert doc["attempt"] == 3


def test_json_formatter_does_not_leak_record_internals():
    doc = fmt_record("x", "hello")
    for internal in ("args", "levelno", "msecs", "process", "taskName"):
        assert internal not in doc


def test_json_formatter_stamps_trace_inside_span():
    tracer = Tracer(FakeClock())
    with tracer.span("poll") as span:
        doc = fmt_record("x", "polling")
    assert doc["trace_id"] == span.trace_id
    assert doc["span"] == "poll"
    # outside any span: no phantom correlation keys
    assert "trace_id" not in fmt_record("x", "idle")


def test_event_recorder_stamps_trace_id():
    tracer = Tracer(FakeClock())
    recorder = EventRecorder()
    hc = make_hc()
    with tracer.span("cycle") as span:
        recorder.event(hc, "Normal", "Normal", "inside")
    recorder.event(hc, "Normal", "Normal", "outside")
    inside, outside = recorder.events_for("health", "hc-a")
    assert inside.trace_id == span.trace_id
    assert outside.trace_id == ""
    assert inside.to_dict()["trace_id"] == span.trace_id


# ---------------------------------------------------------------------
# e2e: one FakeEngine reconcile ⇒ one full-lifecycle trace
# ---------------------------------------------------------------------


class CapturingHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(self.format(record))


def make_stack(clock=None):
    clock = clock or FakeClock()
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    recorder = EventRecorder()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=recorder,
        metrics=MetricsCollector(),
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
    return manager, client, reconciler


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


@pytest.mark.asyncio
async def test_fake_engine_reconcile_produces_full_trace(tmp_path):
    handler = CapturingHandler()
    handler.setFormatter(JsonFormatter())
    events_log = logging.getLogger("activemonitor.events")
    events_log.addHandler(handler)
    old_level = events_log.level
    events_log.setLevel(logging.INFO)
    manager, client, reconciler = make_stack()
    await manager.start()
    try:
        await client.apply(make_hc())
        await settle()
        await reconciler.wait_watches()
        await settle()
    finally:
        events_log.removeHandler(handler)
        events_log.setLevel(old_level)
        await manager.stop()

    traces = reconciler.tracer.traces()
    # exactly one cycle SUBMITS (the status write's own watch event
    # re-enqueues, but that second cycle dedupes out as a no-op trace)
    [trace] = [
        t for t in traces if any(s["name"] == "submit" for s in t["spans"])
    ]
    names = [s["name"] for s in trace["spans"]]
    for phase in ("dequeue", "parse", "submit", "poll", "status_write"):
        assert phase in names, f"missing phase span {phase!r} in {names}"
    assert trace["span_count"] >= 5
    for span in trace["spans"]:
        assert span["duration_seconds"] is not None
        assert span["duration_seconds"] >= 0.0
        assert span["trace_id"] == trace["trace_id"]

    # events of the cycle carry the same trace_id
    recorder = reconciler.recorder
    cycle_events = [
        e for e in recorder.events_for("health", "hc-a") if e.trace_id
    ]
    assert cycle_events, "no events stamped with the cycle trace"
    assert {e.trace_id for e in cycle_events} == {trace["trace_id"]}

    # ... and so do the JSON log lines those events emitted
    logged = [json.loads(line) for line in handler.lines]
    traced_lines = [d for d in logged if "trace_id" in d]
    assert traced_lines, "no correlated log lines captured"
    assert {d["trace_id"] for d in traced_lines} == {trace["trace_id"]}

    # the --trace-export payload for this cycle round-trips
    path = str(tmp_path / "export.jsonl")
    assert reconciler.tracer.export_jsonl(path) == len(traces)
    read_back = [t for t in Tracer.read_jsonl(path) if t["trace_id"] == trace["trace_id"]]
    assert read_back and read_back[0]["span_count"] == trace["span_count"]


@pytest.mark.asyncio
async def test_debug_endpoints_serve_traces_and_events():
    import aiohttp

    manager, client, reconciler = make_stack()
    manager._health_addr = "127.0.0.1:0"  # ephemeral: no port clashes
    await manager.start()
    port = manager._http_runners[0].addresses[0][1]
    try:
        await client.apply(make_hc())
        await settle()
        await reconciler.wait_watches()
        await settle()
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces"
            ) as r:
                assert r.status == 200
                payload = await r.json()
        assert payload["traces"], "no traces served"
        trace = next(
            t
            for t in payload["traces"]
            if any(s["name"] == "submit" for s in t["spans"])
        )
        trace_id = trace["trace_id"]
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/events",
                params={"trace_id": trace_id},
            ) as r:
                assert r.status == 200
                events = (await r.json())["events"]
        assert events and all(e["trace_id"] == trace_id for e in events)
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_debug_endpoints_enforce_metrics_auth_on_shared_site():
    """When /debug shares the socket with an auth-filtered /metrics,
    the same token gate applies — the merged site must not leak the
    operational data the operator put a token in front of."""
    import aiohttp

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(succeed_after(1)),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    manager = Manager(
        client=client,
        reconciler=reconciler,
        metrics_bind_address="127.0.0.1:0",
        health_probe_bind_address="127.0.0.1:0",
        metrics_auth_token="sekrit",
    )
    await manager.start()
    port = manager._http_runners[0].addresses[0][1]
    try:
        async with aiohttp.ClientSession() as session:
            for path in ("/debug/traces", "/debug/events", "/statusz", "/metrics"):
                async with session.get(f"http://127.0.0.1:{port}{path}") as r:
                    assert r.status == 401, path
            # the kubelet's probes stay open
            async with session.get(f"http://127.0.0.1:{port}/healthz") as r:
                assert r.status == 200
            headers = {"Authorization": "Bearer sekrit"}
            for path in ("/debug/traces", "/debug/events", "/statusz", "/metrics"):
                async with session.get(
                    f"http://127.0.0.1:{port}{path}", headers=headers
                ) as r:
                    assert r.status == 200, path
    finally:
        await manager.stop()


def test_trace_export_flag_is_wired():
    from activemonitor_tpu.__main__ import build_parser

    args = build_parser().parse_args(
        ["run", "--trace-export", "/tmp/traces.jsonl"]
    )
    assert args.trace_export == "/tmp/traces.jsonl"
    # default: no export
    assert build_parser().parse_args(["run"]).trace_export == ""
