"""Cron parser tests (reference test model:
healthcheck_controller_unit_test.go:617-660 cron parse cases)."""

import datetime

import pytest

from activemonitor_tpu.scheduler import (
    CronParseError,
    EverySchedule,
    parse_cron,
    seconds_until_next,
)

UTC = datetime.timezone.utc


def dt(*args):
    return datetime.datetime(*args, tzinfo=UTC)


def test_every_minute():
    s = parse_cron("* * * * *")
    assert s.next(dt(2026, 7, 28, 10, 0, 30)) == dt(2026, 7, 28, 10, 1)
    assert s.next(dt(2026, 7, 28, 10, 0, 0)) == dt(2026, 7, 28, 10, 1)


def test_specific_minute_hour():
    s = parse_cron("30 14 * * *")
    assert s.next(dt(2026, 7, 28, 10, 0)) == dt(2026, 7, 28, 14, 30)
    assert s.next(dt(2026, 7, 28, 15, 0)) == dt(2026, 7, 29, 14, 30)


def test_step_and_range():
    s = parse_cron("*/15 9-17 * * *")
    assert s.next(dt(2026, 7, 28, 9, 16)) == dt(2026, 7, 28, 9, 30)
    assert s.next(dt(2026, 7, 28, 17, 46)) == dt(2026, 7, 29, 9, 0)


def test_list_and_names():
    s = parse_cron("0 12 * JAN,JUL MON-FRI")
    # 2026-07-28 is a Tuesday
    assert s.next(dt(2026, 7, 28, 13, 0)) == dt(2026, 7, 29, 12, 0)
    # from late December, jumps into January
    assert s.next(dt(2026, 12, 31, 13, 0)) == dt(2027, 1, 1, 12, 0)


def test_dow_seven_is_sunday():
    a = parse_cron("0 0 * * 0")
    b = parse_cron("0 0 * * 7")
    t = dt(2026, 7, 28)
    assert a.next(t) == b.next(t)
    # 2026-08-02 is a Sunday
    assert a.next(t) == dt(2026, 8, 2)


def test_dom_dow_or_semantics():
    # standard cron: both restricted -> either matches
    s = parse_cron("0 0 15 * MON")
    # from the 10th (Fri Jul 10 2026? -> check): next is the first Monday or the 15th
    nxt = s.next(dt(2026, 7, 10))
    assert nxt == dt(2026, 7, 13)  # Monday Jul 13 comes before Wed Jul 15
    nxt2 = s.next(nxt)
    assert nxt2 == dt(2026, 7, 15)


def test_step_on_wildcard_keeps_star_bit():
    # robfig sets the star bit for '*/2'-style fields: dow stays a
    # wildcard for the dom-OR-dow rule, so this fires only on the 15th.
    s = parse_cron("0 0 15 * */2")
    assert s.next(dt(2026, 7, 1)) == dt(2026, 7, 15)


def test_every_fractional_seconds_truncate():
    s = parse_cron("@every 1.5s")
    assert s.next(dt(2026, 1, 1)) == dt(2026, 1, 1, 0, 0, 1)


def test_descriptors():
    assert parse_cron("@hourly").next(dt(2026, 7, 28, 10, 30)) == dt(2026, 7, 28, 11, 0)
    assert parse_cron("@daily").next(dt(2026, 7, 28, 10, 30)) == dt(2026, 7, 29, 0, 0)
    assert parse_cron("@weekly").next(dt(2026, 7, 28, 10, 30)) == dt(2026, 8, 2, 0, 0)
    assert parse_cron("@monthly").next(dt(2026, 7, 28)) == dt(2026, 8, 1)
    assert parse_cron("@yearly").next(dt(2026, 7, 28)) == dt(2027, 1, 1)


def test_every_duration():
    s = parse_cron("@every 1m")
    assert isinstance(s, EverySchedule)
    assert s.next(dt(2026, 7, 28, 10, 0, 30)) == dt(2026, 7, 28, 10, 1, 30)
    s3 = parse_cron("@every 3s")  # examples/bdd/inlineCustomBackoffTest.yaml
    assert s3.next(dt(2026, 7, 28, 10, 0, 0)) == dt(2026, 7, 28, 10, 0, 3)


def test_feb29():
    s = parse_cron("0 0 29 2 *")
    assert s.next(dt(2026, 1, 1)) == dt(2028, 2, 29)


@pytest.mark.parametrize(
    "expr",
    ["", "bogus", "* * * *", "* * * * * *", "61 * * * *", "* 25 * * *",
     "*/0 * * * *", "@every", "@every nope", "@every -3s", "@fortnightly",
     "5-1 * * * *", "a,b * * * *"],
)
def test_invalid_expressions(expr):
    with pytest.raises(CronParseError):
        parse_cron(expr)


def test_seconds_until_next_adds_rounding_second():
    # reference: healthcheck_controller.go:259-262
    now = dt(2026, 7, 28, 10, 0, 30)
    # next fire 10:01:00 -> delta 30s -> int(30)+1
    assert seconds_until_next("* * * * *", now) == 31
    assert seconds_until_next("@every 1m", now) == 61


def test_tz_prefix_interprets_wall_clock_in_zone():
    """robfig ParseStandard parity: CRON_TZ=/TZ= prefixes (reference
    parses with cron.ParseStandard, healthcheck_controller.go:253)."""
    import datetime

    from activemonitor_tpu.scheduler.cron import parse_cron

    # 09:00 Tokyo == 00:00 UTC (no DST in Asia/Tokyo)
    now = datetime.datetime(2026, 3, 1, 22, 0, tzinfo=datetime.timezone.utc)
    schedule = parse_cron("CRON_TZ=Asia/Tokyo 0 9 * * *")
    nxt = schedule.next(now)
    assert nxt.astimezone(datetime.timezone.utc) == datetime.datetime(
        2026, 3, 2, 0, 0, tzinfo=datetime.timezone.utc
    )
    # TZ= spelling, and descriptors compose with the prefix: now is
    # already Mar 2 07:00 in Tokyo, so the next Tokyo midnight is Mar 3
    schedule = parse_cron("TZ=Asia/Tokyo @daily")
    nxt = schedule.next(now)
    assert nxt.astimezone(datetime.timezone.utc) == datetime.datetime(
        2026, 3, 2, 15, 0, tzinfo=datetime.timezone.utc
    )


def test_tz_prefix_errors_and_every_passthrough():
    import pytest as _pytest

    from activemonitor_tpu.scheduler.cron import (
        CronParseError,
        EverySchedule,
        parse_cron,
    )

    with _pytest.raises(CronParseError, match="unknown timezone"):
        parse_cron("CRON_TZ=Not/AZone * * * * *")
    with _pytest.raises(CronParseError, match="malformed timezone"):
        parse_cron("TZ= * * * * *")
    with _pytest.raises(CronParseError, match="malformed timezone"):
        parse_cron("CRON_TZ=UTC")
    # @every is a constant interval: the zone cannot matter
    assert isinstance(parse_cron("TZ=Asia/Tokyo @every 90s"), EverySchedule)


def test_tz_prefix_naive_after_is_treated_as_utc():
    import datetime

    from activemonitor_tpu.scheduler.cron import parse_cron

    schedule = parse_cron("CRON_TZ=UTC 30 12 * * *")
    nxt = schedule.next(datetime.datetime(2026, 5, 1, 12, 0))
    assert (nxt.hour, nxt.minute) == (12, 30)


def test_tz_prefix_rejects_stacking_and_naive_seconds_until_next():
    import pytest as _pytest

    from activemonitor_tpu.scheduler.cron import (
        CronParseError,
        parse_cron,
        seconds_until_next,
    )

    with _pytest.raises(CronParseError, match="multiple timezone prefixes"):
        parse_cron("TZ=UTC CRON_TZ=Asia/Tokyo 0 9 * * *")
    # naive now works through the exported helper too
    import datetime

    delta = seconds_until_next(
        "CRON_TZ=UTC 30 12 * * *", datetime.datetime(2026, 5, 1, 12, 0)
    )
    assert delta == 30 * 60 + 1


def test_dst_spring_forward_gap_fire_is_canonical():
    """US spring forward (2026-03-08, 02:00 EST -> 03:00 EDT): a fire
    scheduled inside the skipped hour lands on the canonical
    post-transition wall time (03:30 EDT) — the same normalization
    Go's time.Date gives the reference's robfig cron — never a
    nonexistent 02:30-05:00 rendering."""
    s = parse_cron("TZ=America/New_York 30 2 * * *")
    after = datetime.datetime(2026, 3, 7, 12, 0, tzinfo=datetime.timezone.utc)
    fire = s.next(after)
    assert fire.isoformat() == "2026-03-08T03:30:00-04:00"
    # the day after, the schedule is back on its nominal wall time
    fire2 = s.next(fire)
    assert fire2.isoformat() == "2026-03-09T02:30:00-04:00"


def test_dst_spring_forward_chained_fires_stay_monotonic_in_utc():
    """Chaining next(next(...)) across the gap must be strictly
    monotonic in REAL time — before canonicalization the gap produced
    duplicate UTC instants rendered as different wall times."""
    s = parse_cron("TZ=America/New_York */30 * * * *")
    t = datetime.datetime(2026, 3, 8, 6, 45, tzinfo=datetime.timezone.utc)
    instants = []
    for _ in range(5):
        t = s.next(t)
        instants.append(t.astimezone(datetime.timezone.utc))
    assert instants == sorted(set(instants)), instants
    # half-hourly through the skip: 07:00Z (02:00 EST) then straight
    # into EDT wall times — 30 real minutes apart throughout
    deltas = {
        (b - a).total_seconds() for a, b in zip(instants, instants[1:])
    }
    assert deltas == {1800.0}, instants


def test_dst_fall_back_ambiguous_fire_runs_once():
    """US fall back (2026-11-01, 02:00 EDT -> 01:00 EST): 01:30 exists
    twice; the schedule fires ONCE (first occurrence) and resumes the
    next day — no double-fire for the repeated hour."""
    s = parse_cron("TZ=America/New_York 30 1 * * *")
    t = datetime.datetime(2026, 10, 31, 12, 0, tzinfo=datetime.timezone.utc)
    first = s.next(t)
    assert (
        first.astimezone(datetime.timezone.utc).isoformat()
        == "2026-11-01T05:30:00+00:00"  # 01:30 EDT, the first pass
    )
    second = s.next(first)
    assert second.date().isoformat() == "2026-11-02"
