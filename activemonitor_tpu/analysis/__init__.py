"""Baseline & anomaly detection: numeric probe metrics → degradation
verdicts.

The controller's pass/fail verdict only fires when a probe crosses its
own hard threshold; a slice that creeps from 90 % to 60 % of rated
TFLOPs while staying above the probe's floor never trips anything. This
package closes that gap (the ML-Productivity-Goodput / ReFrame framing
from PAPERS.md: the signal is a run *compared against a learned
baseline*, not the point reading):

- :mod:`baseline` — per-(check, metric) rolling statistics (Welford +
  EWMA + median/MAD over a bounded recent ring), compactly serializable
  into ``.status.analysis`` so baselines survive controller restarts;
- :mod:`detector` — pluggable detectors (robust z-score,
  relative-to-rated, trend/slope) producing ``ok | warning | degraded``
  per metric, plus the hysteresis state machine that keeps one noisy
  run from flapping the verdict;
- :mod:`fleet` — cross-check straggler ranking over checks sharing a
  ``spec.analysis.cohort`` label;
- :mod:`engine` — the reconciler-owned façade wiring the three
  together: feeds run samples, persists/adopts durable baselines,
  exports the ``healthcheck_metric_baseline`` / ``_metric_zscore`` /
  ``_anomaly_state`` families, and reports into ``/statusz``;
- :mod:`matrix` — the declarative scenario matrix (ISSUE 12): a
  config-file spec expanded into bench cells, each riding the same
  baseline/hysteresis/roofline evidence stack with a durable
  ``BENCH_BASELINES.json`` sidecar, auto-bisect on confirmed
  regression, and the ``healthcheck_matrix_*`` /statusz/CLI surfaces.
"""

from activemonitor_tpu.analysis.baseline import (
    BASELINE_STATS,
    CheckBaselines,
    MetricBaseline,
)
from activemonitor_tpu.analysis.detector import (
    ANOMALY_STATES,
    DetectorConfig,
    Hysteresis,
    LEVEL_DEGRADED,
    LEVEL_OK,
    LEVEL_WARNING,
    RatedFractionDetector,
    RobustZScoreDetector,
    TrendDetector,
    default_detectors,
    level_name,
)
from activemonitor_tpu.analysis.engine import AnalysisEngine, AnalysisVerdict
from activemonitor_tpu.analysis.fleet import CohortIndex
from activemonitor_tpu.analysis.matrix import MatrixObservatory, SidecarView

__all__ = [
    "ANOMALY_STATES",
    "AnalysisEngine",
    "AnalysisVerdict",
    "BASELINE_STATS",
    "CheckBaselines",
    "CohortIndex",
    "DetectorConfig",
    "Hysteresis",
    "LEVEL_DEGRADED",
    "LEVEL_OK",
    "LEVEL_WARNING",
    "MatrixObservatory",
    "MetricBaseline",
    "SidecarView",
    "RatedFractionDetector",
    "RobustZScoreDetector",
    "TrendDetector",
    "default_detectors",
    "level_name",
]
