"""API type tests (reference test model: api/v1alpha1/healthcheck_types_unit_test.go)."""

import datetime

import pytest
import yaml

from activemonitor_tpu.api import (
    HealthCheck,
    HealthCheckStatus,
    RemedyWorkflow,
    ResourceObject,
)

REFERENCE_STYLE_YAML = """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata:
  name: inline-hello
  namespace: health
spec:
  schedule:
    cron: "@every 1m"
  level: cluster
  workflow:
    generateName: inline-hello-
    resource:
      namespace: health
      serviceAccount: activemonitor-controller-sa
      source:
        inline: |
          apiVersion: argoproj.io/v1alpha1
          kind: Workflow
          spec:
            entrypoint: whalesay
"""

REMEDY_YAML = """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata:
  generateName: fail-healthcheck-
  namespace: health
spec:
  repeatAfterSec: 30
  level: cluster
  remedyRunsLimit: 2
  remedyResetInterval: 300
  workflow:
    generateName: randomfail-workflow-
    workflowtimeout: 20
    resource:
      namespace: health
      serviceAccount: activemonitor-controller-sa
      source:
        inline: "apiVersion: argoproj.io/v1alpha1"
  remedyworkflow:
    generateName: remedy-test-
    resource:
      namespace: health
      serviceAccount: activemonitor-remedy-sa
      source:
        inline: "apiVersion: argoproj.io/v1alpha1"
"""


def test_loads_reference_yaml_unchanged():
    hc = HealthCheck.from_yaml(REFERENCE_STYLE_YAML)
    assert hc.name == "inline-hello"
    assert hc.namespace == "health"
    assert hc.key == "health/inline-hello"
    assert hc.spec.schedule.cron == "@every 1m"
    assert hc.spec.level == "cluster"
    assert hc.spec.workflow.generate_name == "inline-hello-"
    assert hc.spec.workflow.resource.service_account == "activemonitor-controller-sa"
    assert "entrypoint: whalesay" in hc.spec.workflow.resource.source.inline
    assert hc.spec.remedy_workflow.is_empty()


def test_loads_remedy_yaml_with_gates():
    hc = HealthCheck.from_yaml(REMEDY_YAML)
    assert hc.spec.repeat_after_sec == 30
    assert hc.spec.remedy_runs_limit == 2
    assert hc.spec.remedy_reset_interval == 300
    assert hc.spec.workflow.timeout == 20  # json tag "workflowtimeout"
    assert not hc.spec.remedy_workflow.is_empty()
    assert hc.spec.remedy_workflow.resource.service_account == "activemonitor-remedy-sa"


def test_remedy_is_empty_semantics():
    # reference: healthcheck_types.go:104-106 (reflect.DeepEqual with zero value)
    assert RemedyWorkflow().is_empty()
    assert not RemedyWorkflow(generate_name="x-").is_empty()
    assert not RemedyWorkflow(resource=ResourceObject(namespace="health")).is_empty()


def test_round_trip_uses_json_aliases():
    hc = HealthCheck.from_yaml(REMEDY_YAML)
    d = hc.to_dict()
    assert d["spec"]["repeatAfterSec"] == 30
    assert d["spec"]["remedyRunsLimit"] == 2
    assert "remedyworkflow" in d["spec"]
    assert d["spec"]["workflow"]["generateName"] == "randomfail-workflow-"
    # round trip must be lossless
    assert HealthCheck.from_dict(d) == hc


def test_status_remedy_started_at_serializes_as_remedyTriggeredAt():
    # parity quirk: json tag is remedyTriggeredAt (healthcheck_types.go:53)
    st = HealthCheckStatus(
        remedy_started_at=datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
    )
    assert "remedyTriggeredAt" in st.to_json_dict()


def test_status_reset_remedy():
    st = HealthCheckStatus(
        remedy_total_runs=3,
        remedy_success_count=2,
        remedy_failed_count=1,
        remedy_started_at=datetime.datetime.now(datetime.timezone.utc),
        remedy_finished_at=datetime.datetime.now(datetime.timezone.utc),
        remedy_last_failed_at=datetime.datetime.now(datetime.timezone.utc),
    )
    st.reset_remedy("HealthCheck Passed so Remedy is reset")
    assert st.remedy_total_runs == 0
    assert st.remedy_success_count == 0
    assert st.remedy_failed_count == 0
    assert st.remedy_started_at is None
    assert st.remedy_finished_at is None
    assert st.remedy_last_failed_at is None
    assert st.remedy_status == "HealthCheck Passed so Remedy is reset"


def test_verify_cert_default_is_none():
    from activemonitor_tpu.api import URLArtifact

    u = URLArtifact(path="https://example.com/wf.yaml")
    assert u.verify_cert is None  # secure default handled by the reader


def test_printer_row_matches_reference_columns():
    hc = HealthCheck.from_yaml(REFERENCE_STYLE_YAML)
    hc.status.status = "Succeeded"
    hc.status.success_count = 7
    row = hc.printer_row()
    assert row["LATEST STATUS"] == "Succeeded"
    assert row["SUCCESS CNT"] == 7
    assert set(row) == {
        "NAME",
        "LATEST STATUS",
        "SUCCESS CNT",
        "FAIL CNT",
        "REMEDY SUCCESS CNT",
        "REMEDY FAIL CNT",
        "AGE",
    }


def test_deepcopy_is_independent():
    hc = HealthCheck.from_yaml(REMEDY_YAML)
    cp = hc.deepcopy()
    cp.status.success_count = 99
    cp.spec.workflow.resource.namespace = "other"
    assert hc.status.success_count == 0
    assert hc.spec.workflow.resource.namespace == "health"


def test_every_reference_example_parses():
    """All 12+ reference example HealthChecks must load unchanged."""
    import glob
    import os

    ref_examples = glob.glob("/root/reference/examples/**/*.yaml", recursive=True)
    if not ref_examples:
        pytest.skip("reference examples not mounted")
    loaded = 0
    for path in ref_examples:
        with open(path) as f:
            try:
                doc = yaml.safe_load(f)
            except yaml.YAMLError:
                continue
        if not isinstance(doc, dict) or doc.get("kind") != "HealthCheck":
            continue
        hc = HealthCheck.from_dict(doc)
        assert hc.spec.workflow is not None
        loaded += 1
    assert loaded >= 10
