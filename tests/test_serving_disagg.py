"""Disaggregated serving (ISSUE 20).

Covers the pool split's colocated fallback (bitwise the PR 14
scheduler — trace AND ledger), the α/B-priced migration channel, the
seeded interleaving property test for token-exact conservation across
the pool boundary, prefix-cache refcount safety (no live-block
eviction, no double-free), the per-tenant prefix ledger, the
tenant/prefix-mix generator's determinism, the KV refusal counters
(the small fix), the speculative-acceptance rated-fraction contract,
the `serving-disagg` matrix cells (topology variants + the structured
device-deficit skip), and the closed-loop acceptance probe: TTFT p99
improving for disaggregated+prefix-cache vs colocated under one
scripted cost model, with every ledger exact.
"""

import random

import pytest

from activemonitor_tpu.ops.kv_cache import KVBlockManager, PrefixCache
from activemonitor_tpu.scheduler.arrivals import TenantPrefixMix
from activemonitor_tpu.scheduler.pools import (
    DisaggregatedScheduler,
    MigrationChannel,
    MigrationModel,
    PoolTopology,
)
from activemonitor_tpu.scheduler.serving import (
    ContinuousBatchingScheduler,
    mixed_open_loop_requests,
    open_loop_requests,
)


# ---------------------------------------------------------------------
# drivers (pure policy — no jax, virtual clock)
# ---------------------------------------------------------------------


def _drive_colocated(sched, max_steps=500):
    """One deterministic engine-less loop over the colocated step
    protocol; works identically for the PR 14 scheduler and the
    pool-split fallback because the fallback IS delegation."""
    t = 0.0
    for _ in range(max_steps):
        if sched.done:
            return
        for seq in sched.admit(t):
            sched.record_first_token(seq, 100 + seq.req.rid, t)
        batch = sched.decode_batch()
        sched.record_decode_step(
            {s.slot: 200 + s.req.rid for s in batch}, t
        )
        t += 1.0
    raise AssertionError("colocated drive did not complete")


def _drive_disagg(sched, rng=None, max_steps=2000):
    """Drive the split lifecycle to completion. With an rng, the three
    pumps (admit, migrate, decode) run in a random order each tick and
    each is randomly skipped sometimes — the interleaving surface the
    conservation property test sweeps."""
    t = 0.0
    for _ in range(max_steps):
        if sched.done:
            return
        actions = ["admit", "migrate", "decode"]
        if rng is not None:
            rng.shuffle(actions)
        for action in actions:
            if rng is not None and rng.random() < 0.25:
                continue  # skipped pump: the boundary must still hold
            if action == "admit":
                for seq in sched.admit(t):
                    sched.record_first_token(seq, 100 + seq.req.rid, t)
            elif action == "migrate":
                sched.pump_migrations(t)
            else:
                batch = sched.decode_batch(t)
                sched.record_decode_step(
                    {s.slot: 200 + s.req.rid for s in batch}, t
                )
        assert sched.conservation()["ok"], "ledger broke mid-flight"
        assert sched.migration_ledger()["ok"], "boundary broke mid-flight"
        t += 1.0
    raise AssertionError("disagg drive did not complete")


def _disagg_sched(requests, *, prefill_slots=2, decode_slots=3,
                  prefill_blocks=24, decode_blocks=24, block_size=4,
                  prefix_cache=False, cross_slice=False):
    prefill_mgr = KVBlockManager(n_blocks=prefill_blocks, block_size=block_size)
    decode_mgr = KVBlockManager(n_blocks=decode_blocks, block_size=block_size)
    cache = PrefixCache(prefill_mgr) if prefix_cache else None
    return DisaggregatedScheduler(
        requests,
        PoolTopology.disaggregated(
            prefill_slots, decode_slots, cross_slice=cross_slice
        ),
        prefill_manager=prefill_mgr,
        decode_manager=decode_mgr,
        bytes_per_token=512.0,
        prefix_cache=cache,
    )


# ---------------------------------------------------------------------
# colocated fallback: bitwise the PR 14 scheduler
# ---------------------------------------------------------------------


def test_colocated_topology_is_bitwise_the_pr14_scheduler():
    """Same requests, same drive: the colocated pool topology must
    produce the PR 14 scheduler's trace and conservation dict EXACTLY
    (dict equality, not 'close') — the fallback is delegation, and
    this test is what keeps it that way."""
    requests = open_loop_requests(8, 50.0, seed=3)
    baseline = ContinuousBatchingScheduler(
        requests, KVBlockManager(n_blocks=16, block_size=4), max_batch=3
    )
    pooled = DisaggregatedScheduler(
        requests,
        PoolTopology.colocated(max_batch=3),
        manager=KVBlockManager(n_blocks=16, block_size=4),
    )
    _drive_colocated(baseline)
    _drive_colocated(pooled)
    assert pooled.trace == baseline.trace
    assert pooled.conservation() == baseline.conservation()
    assert pooled.conservation()["ok"]
    # the boundary ledger is trivially clean in colocated mode
    assert pooled.migration_ledger()["ok"]
    assert pooled.migration_ledger()["transfers"] == 0


def test_pool_topology_validation():
    with pytest.raises(ValueError):
        PoolTopology(mode="sharded")
    with pytest.raises(ValueError):
        PoolTopology.disaggregated(0, 4)
    requests = open_loop_requests(2, 50.0, seed=0)
    with pytest.raises(ValueError):  # colocated needs its manager
        DisaggregatedScheduler(requests, PoolTopology.colocated(2))
    with pytest.raises(ValueError):  # prefix cache rides the prefill pool
        mgr = KVBlockManager(n_blocks=8, block_size=4)
        DisaggregatedScheduler(
            requests,
            PoolTopology.colocated(2),
            manager=mgr,
            prefix_cache=PrefixCache(mgr),
        )
    with pytest.raises(ValueError):  # cache must index the PREFILL pool
        pre = KVBlockManager(n_blocks=8, block_size=4)
        dec = KVBlockManager(n_blocks=8, block_size=4)
        DisaggregatedScheduler(
            requests,
            PoolTopology.disaggregated(1, 1),
            prefill_manager=pre,
            decode_manager=dec,
            prefix_cache=PrefixCache(dec),
        )


def test_speculative_step_needs_the_disaggregated_pools():
    sched = DisaggregatedScheduler(
        open_loop_requests(2, 50.0, seed=0),
        PoolTopology.colocated(2),
        manager=KVBlockManager(n_blocks=8, block_size=4),
    )
    with pytest.raises(ValueError):
        sched.record_speculative_step({}, {}, {}, 0.0)


# ---------------------------------------------------------------------
# migration channel: the α/B price and the per-transfer receipts
# ---------------------------------------------------------------------


def test_migration_channel_alpha_b_pricing_exact():
    model = MigrationModel(
        alpha_s=1e-5, ici_gbps=40.0, dcn_gbps=20.0, ici_hops=1, dcn_hops=2
    )
    ici = MigrationChannel(model=model, cross_slice=False)
    rec = ici.transfer(7, n_tokens=100, bytes_per_token=512.0)
    assert rec["tier"] == "ici" and rec["hops"] == 1
    assert rec["bytes"] == 100 * 512.0
    assert rec["seconds"] == pytest.approx(1e-5 + 51200.0 / 40e9)
    dcn = MigrationChannel(model=model, cross_slice=True)
    rec = dcn.transfer(7, n_tokens=100, bytes_per_token=512.0)
    assert rec["tier"] == "dcn" and rec["hops"] == 2
    assert rec["seconds"] == pytest.approx(2e-5 + 51200.0 / 20e9)
    ledger = dcn.ledger()
    assert ledger["tokens_total"] == 100
    assert ledger["by_tier"]["dcn"]["transfers"] == 1
    assert ledger["by_tier"]["dcn"]["hops"] == 2


def test_cross_slice_topology_prices_on_dcn():
    requests = mixed_open_loop_requests(
        4, 1e6, seed=5, prefix_len=4, prompt_len_choices=(8, 12),
        output_choices=(2, 3), vocab=64,
    )
    sched = _disagg_sched(requests, cross_slice=True)
    _drive_disagg(sched)
    ledger = sched.migration_ledger()
    assert ledger["ok"] and ledger["transfers"] > 0
    assert set(ledger["by_tier"]) == {"dcn"}


# ---------------------------------------------------------------------
# the property test: token-exact conservation across the boundary
# under randomized admit/migrate/retire interleavings
# ---------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_boundary_conservation_under_seeded_interleavings(seed):
    """Whatever order the pumps run in — and whichever pumps a tick
    skips — every in-flight snapshot balances (admitted = completed +
    in-flight, per tenant, to the token) and the three boundary
    accounts (handed off / received / channel sum) agree exactly. Tight
    pools force every refusal path: prefill-slot and block deferrals,
    decode-slot and decode-block migration backpressure."""
    rng = random.Random(1000 + seed)
    requests = mixed_open_loop_requests(
        10, 200.0, seed=seed, prefix_len=4,
        prompt_len_choices=(8, 12), output_choices=(1, 2, 3), vocab=64,
    )
    use_cache = seed % 2 == 0
    sched = _disagg_sched(
        requests,
        prefill_slots=2,
        decode_slots=2,
        prefill_blocks=14 if use_cache else 8,
        decode_blocks=8,
        prefix_cache=use_cache,
    )
    _drive_disagg(sched, rng=rng)
    conservation = sched.conservation()
    assert conservation["ok"]
    assert conservation["completed"] == len(requests)
    ledger = sched.migration_ledger()
    assert ledger["ok"]
    assert ledger["handed_off_tokens"] == ledger["received_tokens"]
    # both pools drained; refusal counters stayed clean (every deferral
    # was a scheduler-level refusal, never a manager-level surprise)
    for mgr in (sched.prefill_manager, sched.decode_manager):
        stats = mgr.stats()
        assert stats["refusals"]["free_unknown_seq"] == 0
        assert stats["refusals"]["append_unknown_seq"] == 0
        assert stats["refusals"]["append_over_capacity"] == 0
    assert sched.decode_manager.stats()["sequences"] == 0
    if use_cache:
        cache_ledger = sched.prefix_cache.ledger()
        assert cache_ledger["ok"]
        assert cache_ledger["live_refs"] == 0  # every ref released
        # the only prefill-pool residents left are cached pseudo-owners
        assert (
            sched.prefill_manager.stats()["sequences"]
            == sched.prefix_cache.entries
        )


# ---------------------------------------------------------------------
# prefix-cache refcount safety
# ---------------------------------------------------------------------


def _bank_prompt(mgr, cache, rid, tenant, tokens):
    """Admission-shaped helper: acquire, allocate + bank the remainder,
    publish the full blocks."""
    _, hit = cache.acquire(rid, tenant, tokens)
    assert mgr.allocate(rid, len(tokens) - hit) is not None
    assert mgr.append(rid, len(tokens) - hit)
    cache.publish(rid, tenant, tokens)


def test_prefix_cache_never_evicts_a_live_shared_block():
    mgr = KVBlockManager(n_blocks=8, block_size=4)
    cache = PrefixCache(mgr)
    tokens = tuple(range(8))  # two full blocks
    _bank_prompt(mgr, cache, 1, "tenant-a", tokens)
    assert cache.entries == 2
    # rid 2 shares the prefix: refcount 2 on both blocks
    _, hit = cache.acquire(2, "tenant-a", tokens)
    assert hit == 8
    assert cache.refcount(tokens) == [2, 2]
    # eviction cannot touch live entries, however hard it is pressed
    assert cache.evict(blocks_needed=10) == 0
    assert cache.entries == 2
    cache.release(1)
    assert cache.refcount(tokens) == [1, 1]
    assert cache.evict(blocks_needed=10) == 0  # still held by rid 2
    cache.release(2)
    # refcount zero: now LRU reclaim may proceed
    freed = cache.evict(blocks_needed=10)
    assert freed == 2 and cache.entries == 0
    assert mgr.stats()["refusals"]["free_unknown_seq"] == 0


def test_prefix_cache_release_is_single_shot_and_eviction_frees_once():
    mgr = KVBlockManager(n_blocks=8, block_size=4)
    cache = PrefixCache(mgr)
    tokens = tuple(range(4))
    _bank_prompt(mgr, cache, 1, "tenant-a", tokens)
    assert cache.release(1) == 1
    # double release: counted no-op, refcounts untouched
    assert cache.release(1) == 0
    assert cache.refcount(tokens) == [0]
    before = mgr.free_blocks
    assert cache.evict() == 1
    assert mgr.free_blocks == before + 1
    # the entry is gone — a second eviction pass finds nothing and the
    # manager never sees a double-free
    assert cache.evict() == 0
    assert mgr.stats()["refusals"]["free_unknown_seq"] == 0


def test_prefix_ledger_exact_per_tenant():
    mgr = KVBlockManager(n_blocks=16, block_size=4)
    cache = PrefixCache(mgr)
    shared = tuple(range(8))
    _bank_prompt(mgr, cache, 1, "tenant-a", shared + (90, 91, 92))
    _bank_prompt(mgr, cache, 2, "tenant-b", shared + (80, 81))
    ledger = cache.ledger()
    assert ledger["ok"]
    a = ledger["tenants"]["tenant-a"]
    assert a["prompt_tokens"] == 11 == a["prefix_hits"] + a["prefill_tokens"]
    b = ledger["tenants"]["tenant-b"]
    assert b["prefix_hits"] == 8  # the shared blocks, never recomputed
    assert b["prompt_tokens"] == 10 == b["prefix_hits"] + b["prefill_tokens"]


# ---------------------------------------------------------------------
# the workload generator
# ---------------------------------------------------------------------


def test_tenant_prefix_mix_is_deterministic_and_resumable():
    kwargs = dict(prefix_len=4, hot_fraction=0.5, vocab=64,
                  prompt_len_choices=(8, 12))
    whole = TenantPrefixMix(50.0, seed=11, **kwargs).generate(8)
    split_gen = TenantPrefixMix(50.0, seed=11, **kwargs)
    split = split_gen.generate(4) + split_gen.generate(4)
    assert whole == split  # resumable: one schedule, however chunked
    again = TenantPrefixMix(50.0, seed=11, **kwargs).generate(8)
    assert whole == again  # same seed ⇒ byte-identical trace
    prefix = TenantPrefixMix(50.0, seed=11, **kwargs).prefix
    hot = [a for a in whole if a.hot]
    cold = [a for a in whole if not a.hot]
    assert hot and cold
    assert all(a.prompt_tokens[: len(prefix)] == prefix for a in hot)
    assert all(a.prompt_tokens[: len(prefix)] != prefix for a in cold)


def test_mixed_requests_leave_the_classic_generator_untouched():
    """The mixed generator must not perturb the classic seeded
    schedule: open_loop_requests draws stay byte-identical whether or
    not the mixed generator has consumed the same seed elsewhere."""
    before = open_loop_requests(6, 40.0, seed=7)
    mixed_open_loop_requests(6, 40.0, seed=7, prefix_len=4, vocab=64,
                             prompt_len_choices=(8, 12))
    after = open_loop_requests(6, 40.0, seed=7)
    assert before == after
    assert all(r.prompt_tokens is None for r in before)
    mixed = mixed_open_loop_requests(6, 40.0, seed=7, prefix_len=4,
                                     vocab=64, prompt_len_choices=(8, 12))
    assert all(r.prompt_tokens is not None for r in mixed)
    assert all(len(r.prompt_tokens) == r.prompt_len for r in mixed)


# ---------------------------------------------------------------------
# KV refusal counters (the ISSUE 20 small fix)
# ---------------------------------------------------------------------


def test_manager_refusals_are_counted_not_silent():
    mgr = KVBlockManager(n_blocks=4, block_size=2)
    assert mgr.free(99) == 0
    assert mgr.append(99, 1) is False
    assert mgr.allocate(1, 4) is not None
    assert mgr.append(1, 5) is False  # past the reservation
    stats = mgr.stats()["refusals"]
    assert stats == {
        "free_unknown_seq": 1,
        "append_unknown_seq": 1,
        "append_over_capacity": 1,
    }
    # refused operations must not half-apply
    assert mgr.length(1) == 0 and mgr.free_blocks == 2


# ---------------------------------------------------------------------
# speculative acceptance: the rated-fraction contract
# ---------------------------------------------------------------------


def test_spec_acceptance_is_a_rated_fraction_the_floors_and_why_cite():
    from activemonitor_tpu.analysis.detector import is_rated_fraction_metric
    from activemonitor_tpu.obs.attribution import subsystem_for_metric

    name = "serving-spec-accept-fraction-of-rated"
    assert is_rated_fraction_metric(name)
    # am-tpu why: acceptance is a scheduling-policy outcome (the
    # draft-depth knobs live there), migration bytes ride the wires
    assert subsystem_for_metric(name) == "scheduling"
    assert subsystem_for_metric("serving-kv-migration-bytes") == "ici"


def test_speculation_ledger_validates_and_starts_absent():
    requests = mixed_open_loop_requests(
        2, 1e6, seed=2, prefix_len=4, prompt_len_choices=(8, 12),
        output_choices=(3,), vocab=64,
    )
    sched = _disagg_sched(requests)
    assert sched.speculation()["acceptance"] is None  # absence, not 0.0
    for seq in sched.admit(1.0):
        sched.record_first_token(seq, 1, 1.0)
    sched.pump_migrations(1.0)
    batch = sched.decode_batch(2.0)
    assert batch
    slot = batch[0].slot
    with pytest.raises(ValueError):  # accepted > drafted is a caller bug
        sched.record_speculative_step({slot: [5]}, {slot: 1}, {slot: 2}, 2.0)
    sched.record_speculative_step({slot: [5, 6]}, {slot: 2}, {slot: 1}, 2.0)
    spec = sched.speculation()
    assert spec == {"drafted": 2, "accepted": 1, "acceptance": 0.5, "ok": True}


# ---------------------------------------------------------------------
# matrix cells + the acceptance probe (tiny jax model, scripted costs)
# ---------------------------------------------------------------------


def test_matrix_expands_topology_variants_and_skips_deficit_meshes():
    from activemonitor_tpu.analysis import matrix as matrix_mod

    spec = {
        "ops": ["serving-disagg"],
        "meshes": [{"model": 2}, {"model": 16}],
        "dtypes": ["float32"],
    }
    runnable, skipped = matrix_mod.expand(spec)
    ids = [c.cell_id for c in runnable]
    for variant in ("colo", "split", "split-prefix", "split-spec"):
        assert f"serving-disagg/model2/f32/{variant}" in ids
    assert not skipped
    # the op declares its variants — a spec cannot invent one
    assert matrix_mod.OPS["serving-disagg"].variants == (
        "colo", "split", "split-prefix", "split-spec",
    )
    # the deficit mesh executes to a structured skip, never a crash
    import time

    big = [c for c in runnable if dict(c.mesh)["model"] == 16][0]
    result = matrix_mod.execute_cell(big, iters=1, timer=time.monotonic)
    assert result.status == "skipped"
    assert "devices" in (result.reason or str(result.details))


def test_matrix_split_cell_executes_with_conserved_boundary():
    import time

    from activemonitor_tpu.analysis import matrix as matrix_mod

    runnable, _ = matrix_mod.expand(
        {"ops": ["serving-disagg"], "meshes": [{"model": 2}],
         "dtypes": ["float32"]}
    )
    cell = [c for c in runnable if c.variant == "split"][0]
    result = matrix_mod.execute_cell(cell, iters=1, timer=time.monotonic)
    assert result.status == "ok", result.reason
    block = result.details["serving_disagg"]
    assert block["mode"] == "disaggregated" and block["conserved"]
    assert block["migration_transfers"] > 0
    assert result.value > 0


def test_run_disagg_probe_improves_ttft_with_exact_ledgers():
    """The acceptance soak: colocated and disaggregated+prefix-cache
    under ONE scripted cost model — TTFT p99 must improve, emissions
    must be greedy-identical (the consistency gate), and every ledger
    (conservation, boundary, prefix, speculation) must balance exactly.
    Interpret-mode evidence, labeled (`cost_source: scripted`)."""
    from activemonitor_tpu.probes import serving as serving_probe

    result = serving_probe.run_disagg(
        tiny=True, n_requests=8, check_sequences=1, roofline=False
    )
    assert result.ok
    by_name = {m.name: m.value for m in result.metrics}
    assert by_name["serving-disagg-ttft-improvement"] > 0
    assert by_name["serving-disagg-consistency"] == 1.0
    assert by_name["serving-pool-prefill-ttft-p99-ms"] > 0
    assert by_name["serving-prefix-hit-ratio"] > 0
    block = result.details["serving_disagg"]
    assert block["cost_source"] == "scripted"
    assert block["disagg_ttft_p99_ms"] < block["colocated_ttft_p99_ms"]
    assert result.details["conservation"]["ok"]
    assert result.details["migration_ledger"]["ok"]
    assert result.details["prefix_ledger"]["ok"]
    assert result.details["speculation"]["ok"]
    if block["spec_acceptance"] is not None:
        assert (
            by_name["serving-spec-accept-fraction-of-rated"]
            == block["spec_acceptance"]
        )
    # the small fix, threaded through: both pools' refusal counters are
    # in the details and clean on a healthy run
    for pool in ("prefill", "decode"):
        refusals = result.details["kv_refusals"][pool]
        assert set(refusals) == {
            "free_unknown_seq", "append_unknown_seq", "append_over_capacity",
        }
        assert all(v == 0 for v in refusals.values())
