"""Probe library tests on the virtual 8-device CPU mesh (the
CRD-without-controller trick applied to hardware: SURVEY.md §4)."""

import json

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.models.probe_model import (
    forward,
    init_params,
    loss_fn,
    param_count,
    param_specs,
    tiny_config,
)
from activemonitor_tpu.parallel import (
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    best_2d_shape,
    make_1d_mesh,
    make_2d_mesh,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.probes import collectives as collectives_probe
from activemonitor_tpu.probes import devices as devices_probe
from activemonitor_tpu.probes import ici as ici_probe
from activemonitor_tpu.probes import compile_smoke, training_step
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.ops.stream import stream_scale_pallas, stream_scale_xla


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8
    assert jax.devices()[0].platform == "cpu"


def test_mesh_shapes():
    assert best_2d_shape(8) == (2, 4)
    assert best_2d_shape(16) == (4, 4)
    assert best_2d_shape(7) == (1, 7)
    assert make_1d_mesh().devices.size == 8
    assert dict(make_2d_mesh().shape) == {"data": 2, "model": 4}
    with pytest.raises(ValueError):
        make_2d_mesh(shape=(3, 2))


def test_collectives_run_and_report():
    mesh = make_1d_mesh()
    r = all_reduce_bandwidth(mesh, size_mb=1, iters=2)
    assert r.n_devices == 8
    assert r.algbw_gbps > 0
    assert r.busbw_gbps == pytest.approx(r.algbw_gbps * 2 * 7 / 8)
    g = all_gather_bandwidth(mesh, size_mb=0.5, iters=2)
    assert g.busbw_gbps > 0
    p = ppermute_ring_bandwidth(mesh, size_mb=0.5, iters=2)
    assert p.algbw_gbps > 0


def test_ppermute_bidir_chain_is_correct_and_reports():
    """The bidirectional hop body must actually move both halves in
    opposite directions (cw half arrives from the left neighbor, ccw
    half from the right) and report a bandwidth."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from activemonitor_tpu.parallel.collectives import ppermute_bidir_bandwidth
    from activemonitor_tpu.parallel.partition import shard_map

    mesh = make_1d_mesh()
    n = 8
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P("ici"), out_specs=P("ici"),
        check_vma=False,
    )
    def bidir(x):
        half = x.shape[0] // 2
        a = jax.lax.ppermute(x[:half], "ici", fwd)
        b = jax.lax.ppermute(x[half:], "ici", bwd)
        return jnp.concatenate([a, b], axis=0)

    # shard d holds rows [4d, 4d+4): first two rows ride cw, last two ccw
    x = jnp.arange(32.0)
    out = bidir(x)
    for d in range(n):
        rows = out[4 * d: 4 * d + 4]
        assert rows[0] == (4 * ((d - 1) % n)), (d, rows)  # from left
        assert rows[2] == (4 * ((d + 1) % n) + 2), (d, rows)  # from right
    r = ppermute_bidir_bandwidth(mesh, size_mb=0.5, iters=2)
    assert r.name == "ppermute_bidir"
    assert r.algbw_gbps > 0
    assert r.busbw_gbps == pytest.approx(r.algbw_gbps)  # hop convention


def test_reduce_scatter_and_all_to_all_report():
    mesh = make_1d_mesh()
    rs = reduce_scatter_bandwidth(mesh, size_mb=0.5, iters=2)
    assert rs.n_devices == 8
    assert rs.busbw_gbps == pytest.approx(rs.algbw_gbps * 7 / 8)
    a2a = all_to_all_bandwidth(mesh, size_mb=0.5, iters=2)
    assert a2a.busbw_gbps == pytest.approx(a2a.algbw_gbps * 7 / 8)
    assert a2a.algbw_gbps > 0


def test_all_to_all_chain_is_shape_preserving_and_correct():
    """One tiled all-to-all body round-trips shards correctly."""
    from functools import partial

    from activemonitor_tpu.parallel.partition import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_1d_mesh()

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P("ici"), out_specs=P("ici"), check_vma=False
    )
    def a2a(x):
        return jax.lax.all_to_all(x, "ici", split_axis=0, concat_axis=0, tiled=True)

    x = jnp.arange(64.0)
    out = a2a(x)
    assert out.shape == x.shape
    # tiled all-to-all over equal shards is a transpose of the
    # (device, slot) grid: applying it twice is the identity
    assert jnp.allclose(a2a(out), x)


def test_collectives_sweep_probe_on_cpu_mesh():
    r = collectives_probe.run(size_mb=0.5, iters=2)
    assert r.ok  # informational pass: no rated comparison on cpu
    names = {m.name for m in r.metrics}
    assert names == {
        "collective-allreduce-busbw-gbps",
        "collective-allgather-busbw-gbps",
        "collective-reducescatter-busbw-gbps",
        "collective-alltoall-busbw-gbps",
        "collective-ringhop-busbw-gbps",
        "collective-ringhop-bidir-busbw-gbps",
    }
    assert r.details["devices"] == 8
    # no name may collide with the north-star probe's gauges — a merged
    # battery contract must never carry duplicate metric names
    ici_names = {m.name for m in ici_probe.run(size_mb=0.5, iters=2).metrics}
    assert not names & ici_names


def test_collectives_sweep_case_subset_and_validation():
    r = collectives_probe.run(size_mb=0.5, iters=2, cases=("alltoall",))
    assert [m.name for m in r.metrics] == ["collective-alltoall-busbw-gbps"]
    with pytest.raises(ValueError, match="unknown collectives"):
        collectives_probe.run(cases=("bogus",))


def test_alltoall_rated_ceiling_is_bisection_bound():
    from activemonitor_tpu.probes.collectives import _rated_busbw

    # ring collectives: one bidirectional link pair; single hop: one link;
    # bidirectional hop: both directions of the link pair (full duplex)
    assert _rated_busbw("allreduce", 45.0, 8) == 90.0
    assert _rated_busbw("ringhop", 45.0, 8) == 45.0
    assert _rated_busbw("ringhop-bidir", 45.0, 8) == 90.0
    # all-to-all: bisection-bound, 8*B*(n-1)/n^2 < 2*B for every n >= 2
    a2a = _rated_busbw("alltoall", 45.0, 8)
    assert a2a == pytest.approx(8 * 45.0 * 7 / 64)
    assert a2a < 90.0


def test_zoo_schedule_ceilings_are_per_algorithm():
    """Each zoo schedule's rated ceiling reflects ITS wire volume and
    link usage, not the XLA bidir-ring model — the gauge that makes
    "losing to its own algorithm" distinguishable from a slow link."""
    from activemonitor_tpu.probes.collectives import _rated_busbw

    b, n = 45.0, 8
    # unidirectional ring rs+ag: one link direction, half the XLA 2x
    assert _rated_busbw("allreduce-rsag", b, n) == b
    # recursive doubling pays ring contention, not just rounds: round
    # s partners sit 2^s hops apart, so per-link time sums to
    # (p-1)·S/B — at n=8 the ceiling is 2(7/8)·B/7 = B/4, NOT B/3
    assert _rated_busbw("allreduce-recdouble", b, n) == pytest.approx(
        2 * 7 / 8 * b / 7
    )
    # non-pow2 adds the fold/unfold rounds: (4-1) + 2 = 5 at n=5
    assert _rated_busbw("allreduce-recdouble", b, 5) == pytest.approx(
        2 * 4 / 5 * b / 5
    )
    # tree: 2*ceil(log2 8) = 6 one-direction rounds
    assert _rated_busbw("allreduce-tree", b, n) == pytest.approx(
        2 * 7 / 8 * b / 6
    )
    # gather family: (n-1)/n of the payload each way -> one direction
    assert _rated_busbw("allgather-ring", b, n) == b
    assert _rated_busbw("allgather-recdouble", b, n) == b
    # every zoo ceiling sits at or below the XLA bidir-ring ceiling
    for case in (
        "allreduce-rsag", "allreduce-recdouble", "allreduce-tree",
        "allgather-ring", "allgather-recdouble",
    ):
        assert _rated_busbw(case, b, n) <= 2 * b


class _FakeSweepResult:
    def __init__(self, busbw_gbps, payload_bytes):
        self.busbw_gbps = busbw_gbps
        self.payload_bytes = payload_bytes


def _scripted_sweep_bench(_collective, schedule, mesh, axis, size_mb, _dt, _it):
    """alpha-beta regime script: recdouble wins small payloads, rsag
    wins large, XLA in between — deterministic crossovers."""
    n = mesh.shape[axis]
    payload = int(size_mb * 1e6)
    rounds, beta = {
        "xla": (14, 5.0),
        "rsag": (14, 10.0),
        "recdouble": (3, 1.0),
        "tree": (6, 0.5),
        "ring": (7, 8.0),
    }[schedule]
    seconds = 150e-6 * rounds + payload / (beta * 1e9)
    return _FakeSweepResult(payload / seconds / 1e9 * 2 * (n - 1) / n, payload)


def test_collectives_sweep_entrypoint_with_scripted_timings():
    """The sweep probe contract on a scripted regime: headline gauges,
    the serialized decision table, and a located crossover — without
    timing real collectives (tier-1 budget; the real-measurement path
    is the slow test below)."""
    from activemonitor_tpu.parallel import autotune

    autotune.clear()
    try:
        # a stale cell from an earlier tune in the same process must
        # NOT be serialized as this sweep's evidence
        autotune.record("allgather", 99, 2**30, jnp.float32, {"ring": 1.0})
        r = collectives_probe.sweep(
            sizes_mb=(0.01, 50.0),
            collectives=("allreduce",),
            bench=_scripted_sweep_bench,
        )
        assert not any("n99" in k for k in r.details["autotune_table"])
        assert r.ok
        names = [m.name for m in r.metrics]
        assert names == [
            "collective-sweep-zoo-best-win", "collective-sweep-crossovers",
        ]
        by_name = {m.name: m.value for m in r.metrics}
        # rsag beats xla 2x at the bandwidth end of the scripted regime
        assert by_name["collective-sweep-zoo-best-win"] > 1.0
        assert by_name["collective-sweep-crossovers"] >= 1.0
        flips = r.details["crossovers"]["allreduce"]
        assert flips and flips[0]["from"] == "recdouble"
        assert flips[0]["to"] == "rsag"
        # the headline win cell is the latency end: recdouble's 3
        # rounds vs the builtin's 14 dwarf rsag's 2x bandwidth edge
        assert r.details["zoo_best_cell"]["schedule"] == "recdouble"
        assert r.details["zoo_best_cell"]["size_mb"] == 0.01
        # the autotune table is serialized evidence, one entry per size
        assert len(r.details["autotune_table"]) == 2
        for entry in r.details["autotune_table"].values():
            assert set(entry) >= {"schedule", "busbw_gbps", "per_schedule_busbw_gbps"}
        # and the in-process table now serves the tuned decisions
        assert autotune.lookup("allreduce", 8, int(50e6), jnp.bfloat16) == "rsag"
    finally:
        autotune.clear()


@pytest.mark.slow  # real chain-delta measurements across 7 schedules
def test_collectives_sweep_quick_mode_measures_for_real():
    from activemonitor_tpu.parallel import autotune

    autotune.clear()
    try:
        r = collectives_probe.sweep(quick=True)
        assert r.ok
        assert r.details["quick"] is True
        assert len(r.details["sizes_mb"]) == 2
        assert r.details["autotune_table"]  # winners actually recorded
        # a losing zoo must not leave a "best cell" in the evidence
        if r.details["zoo_best_win"] <= 1.0:
            assert r.details["zoo_best_cell"] is None
        for by_size in r.details["results_busbw_gbps"].values():
            for busbw in by_size.values():
                assert all(bw > 0 for bw in busbw.values())
    finally:
        autotune.clear()


def test_collectives_run_accepts_zoo_cases():
    r = collectives_probe.run(size_mb=0.25, iters=2, cases=("allreduce-tree",))
    assert [m.name for m in r.metrics] == ["collective-allreduce-tree-busbw-gbps"]
    # the gauge is the unrounded value; the details copy rounds to
    # 2 decimals and can legitimately floor to 0.0 on a loaded CPU
    assert r.metrics[0].value > 0
    assert "allreduce_tree_busbw_gbps" in r.details


def test_collective_correctness():
    """The timing chain must still compute a correct mean-all-reduce."""
    from functools import partial

    from activemonitor_tpu.parallel.partition import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_1d_mesh()

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P("ici"), out_specs=P("ici"), check_vma=False
    )
    def mean_allreduce(x):
        return jax.lax.psum(x, "ici") / 8

    x = jnp.arange(16.0)
    out = mean_allreduce(x)
    assert out.shape == x.shape
    # shard i holds [2i, 2i+1]; the mean over shards replicates to every shard
    shard_means = x.reshape(8, 2).mean(axis=0)
    assert jnp.allclose(out, jnp.tile(shard_means, 8))


def test_devices_probe_pass_and_fail():
    ok = devices_probe.run(expect_devices=8)
    assert ok.ok
    bad = devices_probe.run(expect_devices=9)
    assert not bad.ok
    assert "expected 9" in bad.summary
    plat = devices_probe.run(require_platform="tpu")
    assert not plat.ok  # cpu test platform


def test_ici_probe_on_cpu_mesh():
    r = ici_probe.run(size_mb=1, iters=2)
    assert r.ok  # no rated comparison on cpu -> informational pass
    names = [m.name for m in r.metrics]
    assert "ici-allreduce-busbw-gbps" in names
    assert "ici-ring-hop-gbps" in names
    assert "ici-ring-hop-bidir-gbps" in names
    assert "ici-allreduce-fraction-of-rated" not in names  # unknown hardware
    assert "ici-ring-hop-fraction-of-rated" not in names
    assert "ici-ring-hop-bidir-fraction-of-rated" not in names


def test_compile_smoke_probe():
    r = compile_smoke.run(tiny=True, batch=2, seq=16)
    assert r.ok
    names = {m.name for m in r.metrics}
    assert names == {"xla-compile-seconds", "xla-exec-milliseconds"}


def test_training_step_probe_tiny():
    r = training_step.run(tiny=True, batch_per_device=2, seq=16, steps=2)
    assert r.ok
    assert r.details["mesh"] == {"data": 2, "model": 4}
    by_name = {m.name: m.value for m in r.metrics}
    assert by_name["train-tokens-per-second"] > 0
    # finite, sane loss for random data over 256 vocab (~ln 256 ≈ 5.5)
    assert 0 < r.details["loss_last"] < 10


def test_zero1_is_pure_layout():
    """ZeRO-1 changes WHERE optimizer state lives, never the math: the
    loss trajectory and final params are bitwise those of the plain
    step, while mu/nu actually carry the extra data-axis sharding."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.parallel.mesh import make_2d_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    cfg = tiny_config()
    mesh = make_2d_mesh()
    tokens = jax.random.randint(jax.random.key(1), (8, 17), 0, cfg.vocab_size)

    def run(**kw):
        step, params, opt, data_sh = build_sharded_train_step(cfg, mesh, **kw)
        t = jax.device_put(tokens, data_sh)
        losses = []
        for _ in range(2):
            params, opt, loss = step(params, opt, t)
            losses.append(float(loss))
        return losses, params, opt

    base_losses, base_params, _ = run()
    z1_losses, z1_params, z1_opt = run(zero1=True)
    assert base_losses == z1_losses
    drift = max(
        float(jnp.max(jnp.abs(a - b)))
        for a, b in zip(jax.tree.leaves(base_params), jax.tree.leaves(z1_params))
    )
    assert drift == 0.0
    mu_spec = z1_opt[0].mu["layers"][0]["w_up"].sharding.spec
    assert mu_spec == ("data", "model")
    # ln scales shard over dp too (leading dim free and divisible)
    assert z1_opt[0].mu["layers"][0]["ln1"]["scale"].sharding.spec == ("data",)


def test_remat_and_accum_match_plain_step():
    """remat is a pure recompute schedule (same losses to float noise);
    gradient accumulation consumes the same global batch in microbatch
    passes and lands within bf16 reordering tolerance."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.parallel.mesh import make_2d_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    cfg = tiny_config()
    mesh = make_2d_mesh()
    tokens = jax.random.randint(jax.random.key(2), (8, 17), 0, cfg.vocab_size)

    def losses(**kw):
        step, params, opt, data_sh = build_sharded_train_step(cfg, mesh, **kw)
        t = jax.device_put(tokens, data_sh)
        out = []
        for _ in range(2):
            params, opt, loss = step(params, opt, t)
            out.append(float(loss))
        return out

    base = losses()
    remat = losses(remat=True)
    accum = losses(accum_steps=4)
    assert all(abs(a - b) < 1e-3 for a, b in zip(base, remat))
    assert all(abs(a - b) < 5e-3 for a, b in zip(base, accum))
    with pytest.raises(ValueError, match="microbatches"):
        # batch 8 over 3 microbatches cannot split
        step, params, opt, data_sh = build_sharded_train_step(
            cfg, mesh, accum_steps=3
        )
        step(params, opt, jax.device_put(tokens, data_sh))


def test_memory_levers_compose_with_flash_attention():
    r = training_step.run(
        tiny=True, batch_per_device=2, seq=32, steps=1, attention="flash",
        zero1=True, remat=True, accum_steps=2,
    )
    assert r.ok
    assert r.details["zero1"] and r.details["remat"]
    assert r.details["accum_steps"] == 2
    assert 0 < r.details["loss_last"] < 10


def test_training_step_mfu_gate_enforces_bar(monkeypatch):
    """BASELINE.md single-chip bar: with a rated spec present, MFU
    below the threshold FAILS the verdict; without a threshold the MFU
    stays a gauge."""
    from activemonitor_tpu.probes.rated import RatedSpec

    absurd = RatedSpec(
        "v5e", bf16_tflops=1e9, hbm_gbps=819.0,
        ici_unidir_gbps=45.0, ici_links=4,
    )  # makes any real measurement a ~zero MFU
    monkeypatch.setattr(training_step, "rated_for", lambda kind: absurd)
    r = training_step.run(
        tiny=True, batch_per_device=2, seq=16, steps=1, mfu_threshold=0.5
    )
    assert not r.ok
    assert r.details["mfu_gate"].startswith("FAILED")
    assert r.details["mfu_threshold"] == 0.5
    assert any(m.name == "train-mfu" for m in r.metrics)
    # same chip, no threshold: gauge only, verdict unaffected
    r = training_step.run(tiny=True, batch_per_device=2, seq=16, steps=1)
    assert r.ok and "mfu_gate" not in r.details


def test_training_step_mfu_gate_skipped_without_rated_spec():
    """A threshold against hardware with no rated spec reports the gap
    instead of guessing a verdict (CPU mesh: rated_for is None)."""
    r = training_step.run(
        tiny=True, batch_per_device=2, seq=16, steps=1, mfu_threshold=0.5
    )
    assert r.ok
    assert "no rated spec" in r.details["mfu_gate"]


def test_training_step_ring_attention_builds_sp_mesh():
    """attention="ring" with no mesh auto-builds a dp×sp mesh and the
    differentiated ring step produces a finite loss."""
    r = training_step.run(
        tiny=True, batch_per_device=2, seq=32, steps=1, attention="ring"
    )
    assert r.ok
    assert r.details["mesh"]["sp"] == 2
    assert r.details["attention"] == "ring"
    assert 0 < r.details["loss_last"] < 10


@pytest.mark.slow  # interpret-mode probe re-run; tier-2 coverage
def test_flash_probe_fraction_gate_inert_off_tpu():
    """min_fraction gates only where the fraction is measurable — a CPU
    run stays a correctness check, never a bogus perf verdict."""
    from activemonitor_tpu.probes import flash

    r = flash.run(batch=1, seq=128, heads=2, head_dim=64, iters=2,
                  min_fraction=0.99)
    assert r.ok
    assert "fraction_gate" not in r.details


def test_probe_contract_line_parses():
    r = ProbeResult(
        ok=True,
        summary="x",
        metrics=[ProbeMetric("ici-bw-gbps", 123.4, help="h")],
    )
    doc = json.loads(r.contract_line())
    assert doc["metrics"][0]["name"] == "ici-bw-gbps"
    assert doc["metrics"][0]["value"] == 123.4
    assert doc["metrics"][0]["metrictype"] == "gauge"


def test_rated_table():
    v5e = rated_for("TPU v5 lite")
    assert v5e is not None and v5e.generation == "v5e"
    assert v5e.bf16_tflops == 197.0
    assert rated_for("TPU v4") is not None
    assert rated_for("cpu") is None
    assert rated_for("NVIDIA H100") is None


def test_rated_env_override(monkeypatch):
    monkeypatch.setenv("ACTIVEMONITOR_RATED_ICI_GBPS", "100")
    assert rated_for("TPU v5 lite").ici_unidir_gbps == 100.0


# -- model -------------------------------------------------------------


def test_probe_model_forward_shapes():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_probe_model_param_count_matches_tree():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == param_count(cfg)


def test_param_specs_tree_matches_params():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    specs = param_specs(cfg)
    from jax.sharding import PartitionSpec as P

    jax.tree.map(
        lambda p, s: None, params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )  # raises if structures mismatch


def test_loss_decreases_under_sgd():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, cfg.vocab_size)
    grad_fn = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg)))
    loss0, grads = grad_fn(params)
    for _ in range(5):
        loss, grads = grad_fn(params)
        params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
    loss_end, _ = grad_fn(params)
    assert float(loss_end) < float(loss0)


# -- ops ---------------------------------------------------------------


def test_pallas_stream_matches_xla():
    x = jax.random.normal(jax.random.key(0), (1024, 1024), jnp.float32)
    got = stream_scale_pallas(x, 2.0, block_rows=512)
    want = stream_scale_xla(x, 2.0)
    assert jnp.allclose(got, want)


def test_pallas_stream_rejects_ragged_blocks():
    x = jnp.ones((1000, 1024), jnp.float32)
    with pytest.raises(ValueError):
        stream_scale_pallas(x, 2.0, block_rows=512)


def test_pallas_stream_double_buffered_matches_xla():
    """The hand-scheduled DMA pipeline must be bit-identical to the
    reference expression, including the single-chunk edge (no second
    slot in flight) and multi-chunk drains."""
    from activemonitor_tpu.ops.stream import stream_scale_pallas_db

    for rows in (512, 1024, 2048):  # 1, 2 and 4 chunks
        x = jax.random.normal(jax.random.key(rows), (rows, 1024), jnp.float32)
        got = stream_scale_pallas_db(x, 1.5, block_rows=512)
        want = stream_scale_xla(x, 1.5)
        assert jnp.allclose(got, want), rows
    with pytest.raises(ValueError):
        stream_scale_pallas_db(jnp.ones((1000, 1024), jnp.float32), 2.0)


def test_suite_compile_cache_configured(tmp_path, monkeypatch):
    from activemonitor_tpu.probes.suite import enable_persistent_compile_cache

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cache = tmp_path / "xla-cache"
    monkeypatch.setenv("ACTIVEMONITOR_COMPILE_CACHE", str(cache))
    try:
        assert enable_persistent_compile_cache() == str(cache)
        assert cache.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(cache)
    finally:
        # global jax.config state must not leak into later tests
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


# -- CLI ---------------------------------------------------------------


def test_cli_devices(capsys):
    from activemonitor_tpu.probes.cli import main

    rc = main(["devices", "--expect", "8"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    doc = json.loads(out)
    assert doc["metrics"][0]["value"] == 8.0


def test_cli_failure_exit_code(capsys):
    from activemonitor_tpu.probes.cli import main

    rc = main(["devices", "--expect", "3"])
    assert rc == 1


# -- decode + memory probes --------------------------------------------


def test_decode_probe_consistency_and_latency():
    from activemonitor_tpu.probes import decode

    r = decode.run(tiny=True, batch=2, prompt_len=4, decode_tokens=4, iters=2)
    assert r.ok
    by_name = {m.name: m.value for m in r.metrics}
    assert by_name["decode-consistency"] == 1.0
    assert by_name["decode-step-milliseconds"] > 0
    assert by_name["decode-tokens-per-second"] > 0


def test_decode_step_matches_forward_logits():
    """The cached single-token path must produce the same logits as the
    batched forward at the corresponding position."""
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import (
        decode_step,
        forward,
        init_kv_cache,
        init_params,
        tiny_config,
    )

    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab_size)
    full_logits = forward(params, tokens, cfg)

    cache = init_kv_cache(cfg, 2, 8)
    for i in range(tokens.shape[1]):
        step_logits, cache = decode_step(
            params, cache, tokens[:, i], jnp.asarray(i), cfg
        )
        assert jnp.allclose(step_logits, full_logits[:, i], atol=2e-2), i


def test_memory_probe():
    from activemonitor_tpu.probes import memory

    r = memory.run(probe_gb=0.02)
    assert r.ok
    by_name = {m.name: m.value for m in r.metrics}
    assert by_name["hbm-headroom-probe-ok"] == 1.0


def test_runtime_histogram_observed():
    from activemonitor_tpu.metrics import MetricsCollector, WORKFLOW_LABEL_HEALTHCHECK

    c = MetricsCollector()
    c.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 107.0)
    c.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 140.0)
    count = c.sample_value(
        "healthcheck_runtime_histogram_seconds_count",
        {"healthcheck_name": "hc-a", "workflow": "healthCheck"},
    )
    assert count == 2
    # buckets are log-spaced 1s..30m (PR 2): the 7 s run lands in le=10,
    # the 40 s run doesn't
    le10 = c.sample_value(
        "healthcheck_runtime_histogram_seconds_bucket",
        {"healthcheck_name": "hc-a", "workflow": "healthCheck", "le": "10.0"},
    )
    assert le10 == 1  # only the 7s run


def test_chain_delta_recovers_per_op_time_under_constant_overhead():
    """The difference method must cancel constant dispatch overhead and
    survive one-sided noise (the tunnel hazard it exists for)."""
    import random
    import time as _time

    from activemonitor_tpu.utils.timing import chain_delta_seconds

    op = 0.002  # true per-op seconds
    rng = random.Random(0)

    def make_chain(k):
        def fn():
            # k ops + constant dispatch cost + one-sided noise
            _time.sleep(k * op + 0.005 + rng.random() * 0.001)
            return 0.0

        return fn

    sec = chain_delta_seconds(make_chain, k1=4, k2=12, iters=4)
    assert 0.0014 < sec < 0.0030, sec


def test_chain_delta_lengthens_chain_inside_noise_floor():
    """Ops far below the noise floor trigger the lengthen-and-remeasure
    policy instead of returning a garbage rate."""
    from activemonitor_tpu.utils.timing import chain_delta_seconds

    calls = []

    def make_chain(k):
        calls.append(k)
        return lambda: 0.0  # instantaneous: delta always in the noise

    sec = chain_delta_seconds(make_chain, k1=2, k2=6, iters=2)
    assert sec > 0
    assert max(calls) > 6  # the chain actually grew


def test_matmul_int8_mode_on_cpu():
    from activemonitor_tpu.probes import matmul

    r = matmul.run(dim=256, iters=2, dtype="int8")
    assert r.ok  # no rated comparison on cpu
    names = {m.name for m in r.metrics}
    assert "mxu-int8-matmul-tops" in names
    assert "mxu-matmul-tflops" not in names
    assert r.details["dtype"] == "int8"
    with pytest.raises(ValueError, match="dtype"):
        matmul.run(dim=128, dtype="fp8")


def test_rated_int8_tops():
    assert rated_for("TPU v5 lite").int8_tops == 394.0
    assert rated_for("TPU v4").int8_tops == 0.0  # no int8 MXU mode on v4


def test_collectives_per_axis_on_cpu_mesh():
    r = collectives_probe.run_per_axis(size_mb=0.5, iters=2)
    assert r.ok
    assert r.details["mesh"] == {"data": 2, "model": 4}
    names = {m.name for m in r.metrics}
    assert names == {
        "collective-allreduce-data-busbw-gbps",
        "collective-ringhop-data-busbw-gbps",
        "collective-allreduce-model-busbw-gbps",
        "collective-ringhop-model-busbw-gbps",
    }
    # each axis reports a positive number; no cross-axis name collision
    assert all(m.value > 0 for m in r.metrics)


def test_collectives_per_axis_threads_cases():
    """The per-axis sweep takes the same case vocabulary as the flat
    run — zoo schedules included — so a chosen schedule can be
    exercised along each torus direction (ISSUE-8 small fix)."""
    r = collectives_probe.run_per_axis(
        size_mb=0.25, iters=2, cases=("allreduce-recdouble",)
    )
    assert r.ok
    assert {m.name for m in r.metrics} == {
        "collective-allreduce-recdouble-data-busbw-gbps",
        "collective-allreduce-recdouble-model-busbw-gbps",
    }
    with pytest.raises(ValueError, match="unknown collectives"):
        collectives_probe.run_per_axis(cases=("bogus",))


def test_collectives_skip_details_carry_mesh_shape(monkeypatch):
    """Skip reasons must say what topology was absent: the per-axis
    skip records the 2D shape it would have used, the flat skip the
    1D ring size."""
    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:2])
    r = collectives_probe.run_per_axis(size_mb=0.25, iters=2)
    assert r.ok and r.details["skipped"]
    assert r.details["mesh"] == {"data": 1, "model": 2}
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:1])
    flat = collectives_probe.run(size_mb=0.25, iters=2)
    assert flat.ok and flat.details["skipped"]
    assert flat.details["mesh"] == {"ici": 1}
    swept = collectives_probe.sweep(quick=True)
    assert swept.ok and swept.details["skipped"]
    assert swept.details["mesh"] == {"ici": 1}


def test_ici_probe_rejects_unknown_schedules_cheaply():
    # validation precedes any measurement, so the error is instant
    with pytest.raises(ValueError, match="unknown all-reduce schedules"):
        ici_probe.run(schedules=("bogus",))


@pytest.mark.slow  # real chain-delta measurement of two zoo schedules
def test_ici_probe_zoo_schedule_gauges():
    """schedules=(...) adds per-algorithm busbw gauges (fractions are
    TPU-only, like every rated comparison)."""
    r = ici_probe.run(size_mb=0.25, iters=2, schedules=("tree", "recdouble"))
    names = {m.name for m in r.metrics}
    assert "ici-allreduce-tree-busbw-gbps" in names
    assert "ici-allreduce-recdouble-busbw-gbps" in names
    assert r.details["allreduce_tree_busbw_gbps"] > 0
    assert r.details["allreduce_recdouble_busbw_gbps"] > 0
    # no fraction gauges off-TPU — same rule as the north-star fraction
    assert not any("tree-fraction" in n for n in names)


@pytest.mark.slow  # full probe run under the profiler CLI; tier-2 coverage
def test_cli_profile_writes_a_trace(tmp_path, capsys):
    """--profile wraps the probe in jax.profiler.trace and must leave a
    trace artifact behind (the tracing/profiling aux subsystem,
    SURVEY.md §5.1) while the metrics contract still prints."""
    import json

    from activemonitor_tpu.probes.cli import main

    rc = main(["--profile", str(tmp_path / "trace"), "devices"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["metrics"][0]["name"] == "tpu-device-count"
    produced = list((tmp_path / "trace").rglob("*"))
    assert any(p.is_file() for p in produced), produced
    # the empty-dir sweep (ISSUE 17 satellite) only prunes HOLLOW
    # capture trees: a successful capture's directories all hold files
    # somewhere beneath them and must survive
    empties = [
        p
        for p in (tmp_path / "trace").rglob("*")
        if p.is_dir() and not any(p.iterdir())
    ]
    assert empties == []


def test_cli_profile_prunes_an_empty_capture_dir(tmp_path, capsys, monkeypatch):
    """A probe that dies before the first device event used to leave an
    empty capture tree behind (ISSUE 17 satellite): the operator — and
    the profile-on-anomaly size cap — then chases hollow captures. The
    CLI now sweeps empty directories after the profiler exits."""
    from activemonitor_tpu.probes import cli

    def boom(args):
        raise SystemExit(3)

    monkeypatch.setattr(cli, "_dispatch", boom)

    class FakeTrace:
        def __init__(self, path):
            # jax.profiler.trace creates the directory eagerly; the
            # crash then leaves it with no events written
            import os

            os.makedirs(path, exist_ok=True)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    import jax

    monkeypatch.setattr(jax.profiler, "trace", FakeTrace)
    target = tmp_path / "trace"
    with pytest.raises(SystemExit):
        cli.main(["--profile", str(target), "devices"])
    assert not target.exists()
