"""Test configuration.

Mirrors the reference's envtest trick (SURVEY.md §4): run everything on
CPU with a virtual 8-device platform so mesh/sharding code is exercised
without TPU hardware.
"""

import os
import sys
from pathlib import Path

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
