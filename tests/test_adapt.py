"""Closed-loop goodput control (ISSUE 18): the damp_factor composition
(flap × analysis × contention × burn through the ONE rule, with its
hard cap and floor), the owed-run math at reschedule time, the
AdaptiveController's four levers with their hysteresis, and the
acceptance chaos script — inject an ICI degradation under FakeEngine +
FakeClock, watch the cadence tighten, the bucket-targeted remedy fire
exactly once, the front door stretch freshness and shed the
low-priority tenant under a confirmed control-plane burn, then watch
every lever relax after recovery, asserted via /statusz, the pinned
gauges, ``am-tpu why``, and the flight-recorder bundles.
"""

import asyncio
import json

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.engine import FakeWorkflowEngine, succeed_after
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.frontdoor import AdmissionController, FrontDoor, TenantQuota
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs.history import ResultHistory
from activemonitor_tpu.resilience.adapt import (
    AdaptiveController,
    BURN_THRESHOLD,
    CONTENTION_DAMP,
    DECISION_LOG_CAPACITY,
    DEGRADED_FRESHNESS_FACTOR,
    ENGAGE_AFTER,
    RELEASE_AFTER,
    SHED_FACTOR,
    TIGHTEN_FACTOR,
)
from activemonitor_tpu.resilience.health import (
    MAX_COMPOSED_DAMP,
    MIN_BURN_DAMP,
    STATE_FLAPPING,
    CheckStateTracker,
)
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = (
    "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
)

ICI_METRIC = "ici-allreduce-fraction-of-rated"
KEY = "health/hc-x"


def make_hc(
    name="hc-x",
    repeat=60,
    slo=None,
    remedy=None,
    remedy_runs_limit=0,
    remedy_reset_interval=0,
):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if slo is not None:
        spec["slo"] = slo
    if remedy is not None:
        spec["remedyworkflow"] = remedy
    if remedy_runs_limit:
        spec["remedyRunsLimit"] = remedy_runs_limit
    if remedy_reset_interval:
        spec["remedyResetInterval"] = remedy_reset_interval
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


# ---------------------------------------------------------------------
# damp_factor composition: the ONE rule (resilience/health.py)
# ---------------------------------------------------------------------


def flap(tracker, key):
    """Drive a key into Flapping: alternating verdicts flip fast."""
    for ok in (True, False, True, False):
        tracker.note_verdict(key, ok)
    assert tracker.state(key) == STATE_FLAPPING


def test_slow_side_composes_strongest_wins():
    t = CheckStateTracker()
    assert t.damp_factor(KEY) == 1.0
    flap(t, KEY)
    assert t.damp_factor(KEY) == 2.0  # default flap damp
    t.set_analysis_damp(KEY, 8.0)
    assert t.damp_factor(KEY) == 8.0  # strongest wins, not product
    t.set_contention_damp(KEY, CONTENTION_DAMP)
    assert t.damp_factor(KEY) == 8.0  # 2.0 contention loses to 8.0
    t.set_analysis_damp(KEY, 1.0)  # <=1 clears the request
    assert t.damp_factor(KEY) == CONTENTION_DAMP
    t.set_contention_damp(KEY, 0.0)
    assert t.damp_factor(KEY) == 2.0  # flap containment remains


def test_composed_damp_caps_at_max():
    t = CheckStateTracker()
    t.set_analysis_damp(KEY, 50.0)
    assert t.damp_factor(KEY) == MAX_COMPOSED_DAMP
    # the burn tightener multiplies the CAPPED slow side
    t.set_burn_damp(KEY, TIGHTEN_FACTOR)
    assert t.damp_factor(KEY) == MAX_COMPOSED_DAMP * TIGHTEN_FACTOR


def test_burn_damp_clamps_and_clears():
    t = CheckStateTracker()
    t.set_burn_damp(KEY, 0.1)  # tighter than the floor: clamped
    assert t.damp_factor(KEY) == MIN_BURN_DAMP
    t.set_burn_damp(KEY, 0.5)
    assert t.damp_factor(KEY) == 0.5
    t.set_burn_damp(KEY, 1.0)  # >= 1 releases the request
    assert t.damp_factor(KEY) == 1.0
    # the composed result floors at MIN_BURN_DAMP too
    t.set_burn_damp(KEY, MIN_BURN_DAMP)
    assert t.damp_factor(KEY) == MIN_BURN_DAMP


def test_flap_times_burn_still_slows_down():
    # containment outranks urgency: a flapping AND burning check still
    # runs slower than spec cadence, never faster
    t = CheckStateTracker()
    flap(t, KEY)
    t.set_burn_damp(KEY, TIGHTEN_FACTOR)
    assert t.damp_factor(KEY) == 2.0 * TIGHTEN_FACTOR == 1.0


def test_forget_clears_every_damp_source():
    t = CheckStateTracker()
    flap(t, KEY)
    t.set_analysis_damp(KEY, 4.0)
    t.set_contention_damp(KEY, 2.0)
    t.set_burn_damp(KEY, 0.5)
    t.forget(KEY)
    assert t.damp_factor(KEY) == 1.0


# ---------------------------------------------------------------------
# owed-run math: reschedule-time interval (controller/reconciler.py)
# ---------------------------------------------------------------------


class Harness:
    def __init__(self, completer=None):
        self.clock = FakeClock()
        self.client = InMemoryHealthCheckClient()
        self.engine = FakeWorkflowEngine(completer)
        self.metrics = MetricsCollector()
        self.recorder = EventRecorder()
        self.reconciler = HealthCheckReconciler(
            client=self.client,
            engine=self.engine,
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=self.recorder,
            metrics=self.metrics,
            clock=self.clock,
        )

    async def apply_and_reconcile(self, hc):
        created = await self.client.apply(hc)
        await self.reconciler.reconcile(created.namespace, created.name)
        return created

    async def settle(self):
        for _ in range(50):
            await asyncio.sleep(0)


@pytest.mark.asyncio
async def test_effective_repeat_after_tightens_and_never_pauses():
    h = Harness()
    hc = make_hc(repeat=60)
    checks = h.reconciler.resilience.checks
    assert h.reconciler._effective_repeat_after(hc) == 60
    checks.set_burn_damp(hc.key, TIGHTEN_FACTOR)
    assert h.reconciler._effective_repeat_after(hc) == 30
    # a 1s check at the MIN_BURN_DAMP floor must still owe a run every
    # second — int(0.25) == 0 would read as "paused", silently stopping
    # the very check the adaptive loop wants to run MORE often
    short = make_hc(name="hc-short", repeat=1)
    checks.set_burn_damp(short.key, MIN_BURN_DAMP)
    assert h.reconciler._effective_repeat_after(short) == 1
    # slow side: the composed cap keeps a stacked containment finite
    checks.set_burn_damp(hc.key, 1.0)
    checks.set_analysis_damp(hc.key, 100.0)
    assert h.reconciler._effective_repeat_after(hc) == 60 * MAX_COMPOSED_DAMP


# ---------------------------------------------------------------------
# AdaptiveController units (resilience/adapt.py)
# ---------------------------------------------------------------------


def make_controller():
    clock = FakeClock()
    metrics = MetricsCollector()
    checks = CheckStateTracker()
    return AdaptiveController(clock, metrics, checks), clock, metrics, checks


def test_cadence_hysteresis_engages_and_releases():
    ctrl, _, metrics, checks = make_controller()
    hc = make_hc()
    # one burning run is a spike, not an episode
    ctrl.observe(hc, burn_rate=2.0, bucket="ici")
    assert ctrl.check_adapt(hc.key) is None
    assert checks.damp_factor(hc.key) == 1.0
    # the second consecutive one engages
    ctrl.observe(hc, burn_rate=2.0, bucket="ici")
    block = ctrl.check_adapt(hc.key)
    assert block["levers"] == ["cadence"]
    assert block["cadence_factor"] == TIGHTEN_FACTOR
    assert block["cause"] == "ici"
    assert checks.damp_factor(hc.key) == TIGHTEN_FACTOR
    assert (
        metrics.sample_value(
            "healthcheck_adaptive_cadence_factor",
            {"healthcheck_name": "hc-x", "namespace": "health"},
        )
        == TIGHTEN_FACTOR
    )
    # burn AT the threshold is calm (strictly greater engages)
    ctrl.observe(hc, burn_rate=BURN_THRESHOLD, bucket="")
    ctrl.observe(hc, burn_rate=0.5, bucket="")
    assert ctrl.check_adapt(hc.key) is not None  # 2 calm < RELEASE_AFTER
    ctrl.observe(hc, burn_rate=0.5, bucket="")
    assert ctrl.check_adapt(hc.key) is None
    assert checks.damp_factor(hc.key) == 1.0
    assert (
        metrics.sample_value(
            "healthcheck_adaptive_cadence_factor",
            {"healthcheck_name": "hc-x", "namespace": "health"},
        )
        is None
    )
    # a calm run in the middle of a hot streak resets the streak
    ctrl.observe(hc, burn_rate=2.0, bucket="ici")
    ctrl.observe(hc, burn_rate=0.2, bucket="")
    ctrl.observe(hc, burn_rate=2.0, bucket="ici")
    assert ctrl.check_adapt(hc.key) is None
    # a None burn rate (no SLO evaluation) is no observation at all
    ctrl.observe(hc, burn_rate=None, bucket="ici")
    assert ctrl.check_adapt(hc.key) is None


def test_first_real_attribution_adopted_as_cause():
    ctrl, _, _, _ = make_controller()
    hc = make_hc()
    for _ in range(ENGAGE_AFTER):
        ctrl.observe(hc, burn_rate=3.0, bucket="")
    assert ctrl.check_adapt(hc.key)["cause"] == "unknown"
    ctrl.observe(hc, burn_rate=3.0, bucket="hbm")
    assert ctrl.check_adapt(hc.key)["cause"] == "hbm"
    # the adopted cause is sticky — later buckets don't rewrite history
    ctrl.observe(hc, burn_rate=3.0, bucket="ici")
    assert ctrl.check_adapt(hc.key)["cause"] == "hbm"
    # the episode's burn tracks the latest observation
    assert ctrl.snapshot()["cadence"][hc.key]["burn"] == 3.0


class FakeCohorts:
    """The CohortIndex surface _sweep_placement consumes."""

    def __init__(self):
        self.scores = {}

    def cohorts(self):
        return ["pool-a"]

    def members(self, cohort):
        return list(self.scores)

    def worst_score(self, cohort, key):
        return self.scores.get(key)


def test_placement_sweep_parks_and_releases_contended_member():
    ctrl, _, _, checks = make_controller()
    ctrl.cohorts = FakeCohorts()
    ctrl.cohorts.scores[KEY] = -3.5  # |score| >= 3 sigmas: contended
    ctrl.sweep()
    assert checks.damp_factor(KEY) == CONTENTION_DAMP
    block = ctrl.check_adapt(KEY)
    assert block["levers"] == ["placement"]
    assert block["cohort"] == "pool-a"
    # a second sweep at the same score is idempotent (no new decision)
    decisions = len(ctrl.snapshot()["recent"])
    ctrl.sweep()
    assert len(ctrl.snapshot()["recent"]) == decisions
    ctrl.cohorts.scores[KEY] = 0.4  # back within the envelope
    ctrl.sweep()
    assert checks.damp_factor(KEY) == 1.0
    assert ctrl.check_adapt(KEY) is None


def make_door(clock, metrics, quotas=None):
    door = FrontDoor(
        ResultHistory(clock),
        AdmissionController(
            quotas,
            default_quota=TenantQuota(rate_per_minute=600.0),
            clock=clock,
        ),
        clock=clock,
        metrics=metrics,
        default_freshness=30.0,
        park_capacity=8,
    )
    door.bind(lambda ns, name: None)
    return door


def test_frontdoor_lever_follows_control_plane_episodes():
    ctrl, clock, metrics, _ = make_controller()
    door = make_door(clock, metrics)
    ctrl.frontdoor = door
    hc = make_hc()
    # an ici-caused episode does NOT touch the front door
    for _ in range(ENGAGE_AFTER):
        ctrl.observe(hc, burn_rate=2.0, bucket="ici")
    assert door.cache.freshness_ceiling() == 30.0
    assert ctrl.snapshot()["frontdoor"]["engaged"] is False
    # a control-plane episode on another check engages it
    cp = make_hc(name="hc-cp")
    for _ in range(ENGAGE_AFTER):
        ctrl.observe(cp, burn_rate=2.0, bucket="control_plane")
    fd = ctrl.snapshot()["frontdoor"]
    assert fd["engaged"] is True
    assert fd["freshness_ceiling"] == 30.0 * DEGRADED_FRESHNESS_FACTOR
    assert fd["shed_factor"] == SHED_FACTOR
    assert (
        metrics.sample_value(
            "healthcheck_adaptive_freshness_ceiling_seconds", {}
        )
        == 30.0 * DEGRADED_FRESHNESS_FACTOR
    )
    # releasing the control-plane episode releases the door
    for _ in range(RELEASE_AFTER):
        ctrl.observe(cp, burn_rate=0.1, bucket="")
    assert ctrl.snapshot()["frontdoor"]["engaged"] is False
    assert door.cache.freshness_ceiling() == 30.0
    assert door.admission.shed_factor is None


def test_forget_drops_episodes_and_releases_frontdoor():
    ctrl, clock, metrics, checks = make_controller()
    door = make_door(clock, metrics)
    ctrl.frontdoor = door
    cp = make_hc(name="hc-cp")
    for _ in range(ENGAGE_AFTER):
        ctrl.observe(cp, burn_rate=2.0, bucket="control_plane")
    ctrl.note_remedy_selected(cp.key, "control_plane")
    assert ctrl.snapshot()["frontdoor"]["engaged"] is True
    ctrl.forget(cp.key)
    assert ctrl.check_adapt(cp.key) is None
    assert ctrl.snapshot()["frontdoor"]["engaged"] is False
    assert door.cache.freshness_ceiling() == 30.0
    assert (
        metrics.sample_value(
            "healthcheck_adaptive_cadence_factor",
            {"healthcheck_name": "hc-cp", "namespace": "health"},
        )
        is None
    )


def test_decision_log_is_bounded():
    ctrl, _, _, _ = make_controller()
    for i in range(DECISION_LOG_CAPACITY + 10):
        ctrl.note_remedy_selected(f"health/hc-{i}", "ici")
    recent = ctrl.snapshot()["recent"]
    assert len(recent) == DECISION_LOG_CAPACITY
    # oldest entries fell off the front; the newest survives
    assert recent[-1]["key"] == f"health/hc-{DECISION_LOG_CAPACITY + 9}"


def test_snapshot_and_check_adapt_shapes():
    ctrl, _, _, _ = make_controller()
    snap = ctrl.snapshot()
    assert snap["engaged"] is False
    assert snap["levers"] == {
        "cadence": 0,
        "remedy": 0,
        "placement": 0,
        "frontdoor": 0,
    }
    assert snap["frontdoor"]["freshness_ceiling"] is None  # no door wired
    ctrl.note_remedy_selected(KEY, "ici")
    snap = ctrl.snapshot()
    assert snap["engaged"] is True
    assert snap["levers"]["remedy"] == 1
    block = ctrl.check_adapt(KEY)
    assert block["levers"] == ["remedy"]
    assert block["remedy_bucket"] == "ici"
    assert block["cadence_factor"] is None


# ---------------------------------------------------------------------
# acceptance: the closed loop end-to-end on a fake clock
# ---------------------------------------------------------------------


def contract(value):
    return json.dumps({"metrics": [{"name": ICI_METRIC, "value": value}]})


@pytest.mark.asyncio
async def test_closed_loop_chaos_burn_to_recovery():
    from activemonitor_tpu.__main__ import render_status_table, render_why

    h = Harness()
    mode = {"fail": True}

    def check_completer(_wf, _polls):
        if mode["fail"]:
            return {
                "phase": PHASE_FAILED,
                "message": "ici allreduce below rated floor",
                "outputs": {
                    "parameters": [
                        {"name": "metrics", "value": contract(0.4)}
                    ]
                },
            }
        return {
            "phase": PHASE_SUCCEEDED,
            "outputs": {
                "parameters": [{"name": "metrics", "value": contract(0.97)}]
            },
        }

    h.engine.on_prefix("hc-ici-", check_completer)
    h.engine.on_prefix("ici-remedy-", succeed_after(1))

    ici = make_hc(
        name="hc-ici",
        repeat=60,
        slo={"objective": 0.5, "windowSeconds": 3600},
        remedy={
            "generateName": "generic-remedy-",
            "resource": {
                "namespace": "health",
                "serviceAccount": "remedy-sa",
                "source": {"inline": WF_INLINE},
            },
            "byBucket": {
                "ici": {
                    "generateName": "ici-remedy-",
                    "resource": {
                        "namespace": "health",
                        "source": {"inline": WF_INLINE},
                    },
                }
            },
        },
        remedy_runs_limit=1,
        remedy_reset_interval=86400,  # both gates set => limit enforced
    )
    adapt = h.reconciler.adapt
    fleet = h.reconciler.fleet
    checks = h.reconciler.resilience.checks

    door = make_door(
        h.clock,
        h.metrics,
        quotas={
            "prod": TenantQuota(rate_per_minute=600.0),
            "batch": TenantQuota(rate_per_minute=4.0, priority="low"),
        },
    )
    adapt.frontdoor = door

    # -- inject: three failing runs with ici payload evidence ----------
    await h.apply_and_reconcile(ici)  # run 1 fires immediately
    await h.settle()
    await h.clock.advance(1.0)
    await h.settle()
    st = (await h.client.get("health", "hc-ici")).status
    assert st.failed_count == 1
    # run 1 is a spike: the remedy already targeted its bucket, but no
    # cadence episode yet — interval still 60s
    assert adapt.check_adapt(ici.key)["levers"] == ["remedy"]
    assert h.reconciler._effective_repeat_after(ici) == 60
    await h.clock.advance(61.0)  # run 2: engages, interval tightens
    await h.settle()
    await h.clock.advance(1.0)
    await h.settle()
    await h.clock.advance(31.0)  # run 3: already at the 30s cadence
    await h.settle()
    await h.clock.advance(1.0)
    await h.settle()
    st = (await h.client.get("health", "hc-ici")).status
    assert st.failed_count == 3

    # the cadence lever engaged on run 2 (burn 2.0 > 1.0 twice)
    block = adapt.check_adapt(ici.key)
    assert "cadence" in block["levers"]
    assert block["cause"] == "ici"
    assert checks.damp_factor(ici.key) == TIGHTEN_FACTOR
    assert h.reconciler._effective_repeat_after(ici) == 30
    assert (
        h.metrics.sample_value(
            "healthcheck_adaptive_cadence_factor",
            {"healthcheck_name": "hc-ici", "namespace": "health"},
        )
        == TIGHTEN_FACTOR
    )

    # the byBucket['ici'] remedy fired EXACTLY once (runs limit), and
    # the plain fallback never did
    names = [m["metadata"]["name"] for m in h.engine.submitted]
    assert sum(1 for n in names if n.startswith("ici-remedy-")) == 1
    assert sum(1 for n in names if n.startswith("generic-remedy-")) == 0
    assert block["remedy_bucket"] == "ici"

    # visible end-to-end: /statusz and am-tpu why/status
    doc = fleet.statusz([await h.client.get("health", "hc-ici")])
    assert doc["fleet"]["adaptive"]["engaged"] is True
    assert doc["fleet"]["adaptive"]["levers"]["cadence"] == 1
    [entry] = doc["checks"]
    why = render_why(entry)
    assert "adaptation:" in why
    assert "interval x0.5" in why
    table = render_status_table(doc)
    assert "ADAPT" in table and "cadence:0.5" in table

    # -- confirmed control-plane burn: breaker open + failing runs -----
    breaker = h.reconciler.resilience.breaker
    for _ in range(breaker.failure_threshold):
        breaker.record_failure()
    assert h.reconciler.resilience.degraded
    cp = make_hc(name="hc-cp", slo={"objective": 0.5, "windowSeconds": 3600})
    for i in range(3):
        fleet.record(cp, ok=False, latency=1.0, workflow=f"cp-w{i}")
    assert adapt.check_adapt(cp.key)["cause"] == "control_plane"

    # the front-door lever engaged: freshness ceiling stretched ...
    snap = door.snapshot()
    assert snap["freshness"]["widened"] is True
    assert snap["freshness"]["ceiling"] == 120.0
    assert (
        h.metrics.sample_value(
            "healthcheck_adaptive_freshness_ceiling_seconds", {}
        )
        == 120.0
    )
    # ... an over-asking request clamps AUDIBLY to the degraded ceiling
    ticket = door.submit("prod", "health/hc-ici", freshness=500.0)
    assert ticket.clamp["clamped"] is True
    assert ticket.clamp["mode"] == "degraded"
    assert ticket.clamp["window"] == 120.0
    assert (
        h.metrics.sample_value(
            "healthcheck_frontdoor_freshness_clamped_total",
            {"tenant": "prod", "mode": "degraded"},
        )
        == 1.0
    )
    # ... and the low-priority tenant is shed by quota re-pricing while
    # the healthy tenant is untouched
    batch = [door.submit("batch", f"health/b-{i}").outcome for i in range(3)]
    assert batch.count("refused") == 2  # re-priced to 1 token
    prod = [door.submit("prod", f"health/p-{i}").outcome for i in range(3)]
    assert prod.count("refused") == 0
    assert door.conservation()["ok"]  # every request still accounted

    # -- recovery: runs pass again, breaker probe closes the circuit ---
    mode["fail"] = False
    for _ in range(5):  # five passing ici runs at the tightened cadence
        await h.clock.advance(31.0)
        await h.settle()
        await h.clock.advance(1.0)
        await h.settle()
    st = (await h.client.get("health", "hc-ici")).status
    assert st.success_count == 5
    assert not h.reconciler.resilience.degraded  # probe closed it
    # burn 6/(3+k): calm at k=3,4,5 -> released on the fifth success
    assert adapt.check_adapt(ici.key)["levers"] == ["remedy"]  # sticky tag
    assert checks.damp_factor(ici.key) == 1.0
    assert h.reconciler._effective_repeat_after(ici) == 60
    assert (
        h.metrics.sample_value(
            "healthcheck_adaptive_cadence_factor",
            {"healthcheck_name": "hc-ici", "namespace": "health"},
        )
        is None
    )
    # the control-plane episode releases the same way
    for i in range(5):
        fleet.record(cp, ok=True, latency=1.0, workflow=f"cp-ok-{i}")
    assert adapt.check_adapt(cp.key) is None
    snap = adapt.snapshot()
    assert snap["levers"]["cadence"] == 0
    assert snap["levers"]["frontdoor"] == 0
    assert snap["frontdoor"]["engaged"] is False
    assert door.cache.freshness_ceiling() == 30.0
    assert door.admission.shed_factor is None
    assert (
        h.metrics.sample_value(
            "healthcheck_adaptive_freshness_ceiling_seconds", {}
        )
        == 30.0
    )
    for lever, want in (
        ("cadence", 0.0),
        ("frontdoor", 0.0),
        ("placement", 0.0),
        ("remedy", 1.0),  # the targeted-selection tag outlives release
    ):
        assert (
            h.metrics.sample_value(
                "healthcheck_adaptive_lever_active", {"lever": lever}
            )
            == want
        )

    # every engage has a matching release in the transition counters
    # AND one flight bundle each
    for lever, action, want in (
        ("cadence", "engage", 2.0),  # ici + cp episodes
        ("cadence", "release", 2.0),
        ("frontdoor", "engage", 1.0),
        ("frontdoor", "release", 1.0),
        ("remedy", "target", 1.0),
    ):
        assert (
            h.metrics.sample_value(
                "healthcheck_adaptive_transitions_total",
                {"lever": lever, "action": action},
            )
            == want
        ), (lever, action)
    bundles = h.reconciler.flightrec.bundles(kind="adaptive-lever")

    def count(lever, action):
        return sum(
            1
            for b in bundles
            if b["extra"]["lever"] == lever and b["extra"]["action"] == action
        )

    assert count("cadence", "engage") == 2
    assert count("cadence", "release") == 2
    assert count("frontdoor", "engage") == 1
    assert count("frontdoor", "release") == 1
    assert count("remedy", "target") == 1

    # the fleet doc and CLI read idle again (remedy tag aside)
    doc = fleet.statusz([await h.client.get("health", "hc-ici"), cp])
    assert doc["fleet"]["adaptive"]["levers"]["cadence"] == 0
    assert doc["fleet"]["adaptive"]["frontdoor"]["engaged"] is False
    entry = next(c for c in doc["checks"] if c["healthcheck"] == "hc-ici")
    assert "interval x0.5" not in render_why(entry)
