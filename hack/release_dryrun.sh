#!/usr/bin/env bash
# Execute every release.yml step that can run without a docker daemon,
# network egress, or GitHub credentials — the transcript that proves
# the release path works before any tag is pushed
# (docs/evidence/release-dryrun-*.md records a captured run).
#
# Usage: hack/release_dryrun.sh [expected-tag]   (default: v<pyproject version>)
set -euo pipefail
cd "$(dirname "$0")/.."

PKG_VERSION=$(python -c "import tomllib;print(tomllib.load(open('pyproject.toml','rb'))['project']['version'])")
TAG="${1:-v$PKG_VERSION}"

echo "== test job: version-tag gate =="
t="${TAG#v}"
if [ "$PKG_VERSION" = "$t" ]; then
  echo "tag $TAG matches pyproject version $PKG_VERSION"
else
  echo "pyproject version $PKG_VERSION != tag $t" >&2
  exit 1
fi

echo "== test job: lint =="
make lint

echo "== test job: full suite =="
python -m pytest tests/ -q

echo "== publish job: regenerate install artifacts + drift check =="
make crd
python hack/gen_deploy.py
git diff --exit-code config/ deploy/
echo "release artifacts match the tree"

echo "== image job: Dockerfile RUN steps, executed outside docker =="
STAGE=$(mktemp -d)
# the in-tree setuptools run leaves build/ + egg-info byproducts
# (both gitignored). Clean up ONLY what this run creates — a developer
# may have a pre-existing build/ or an editable-install egg-info that
# is not ours to delete.
PRE_BUILD=0; [ -e build ] && PRE_BUILD=1
PRE_EGG=0; compgen -G "./*.egg-info" > /dev/null && PRE_EGG=1
cleanup() {
  rm -rf "$STAGE"
  if [ "$PRE_BUILD" = 0 ]; then rm -rf build; fi
  if [ "$PRE_EGG" = 0 ]; then rm -rf ./*.egg-info; fi
}
trap cleanup EXIT
# Dockerfile: RUN pip install --no-cache-dir .
# Offline equivalent: deps come from the invoking environment at run
# time; what this proves is that THIS package installs cleanly and its
# entrypoints work from the installed copy, not the source checkout.
pip install --no-cache-dir --no-deps --no-build-isolation \
  --target "$STAGE" --quiet .
echo "installed: $(ls "$STAGE" | grep dist-info)"
# ENTRYPOINT ["python", "-m", "activemonitor_tpu"] + CMD ["run", "--help"]
(cd /tmp && JAX_PLATFORMS=cpu PYTHONPATH="$STAGE" \
  python -m activemonitor_tpu run --help >/dev/null)
echo "image entrypoint OK from installed copy"
# probe payload (what workflow templates exec inside probe pods)
(cd /tmp && JAX_PLATFORMS=cpu PYTHONPATH="$STAGE" \
  python -m activemonitor_tpu.probes devices >/dev/null)
echo "probe CLI OK from installed copy"

echo
echo "Dry run complete. Still needs real infrastructure: docker build"
echo "(multi-arch, nonroot runtime), JAX_VARIANT=jax[tpu] wheel pull,"
echo "GHCR push, and the GitHub release step."
