"""Cross-layer critical-path waterfall: where one request's time went.

Attribution (obs/attribution.py) says which SUBSYSTEM loses goodput and
the roofline (obs/roofline.py) says which hardware ceiling a probe
hits, but neither answers the operator's first question about a slow
check: *where did the milliseconds of this run go?* The evidence is
already recorded — the cycle's spans (obs/trace.py), the probe's
``PhaseTimings``, the front door's admission span, the serving
scheduler's token-exact stamps — it just lives in four places that
nothing joins. This module is that join, kept pure and wall-clock-free
(``hack/lint.py`` bans ``time.time()``/``time.monotonic()`` here; every
timestamp arrives inside a span or a scheduler stamp, so fake-clock
tests replay exact waterfalls):

- :func:`build_waterfall` — one trace's finished spans (+ the run's
  phase timings) folded into per-stage seconds over the fixed stage
  vocabulary :data:`STAGES`, with a computed ``dominant_stage`` and
  every second the spans do not cover booked honestly as ``untracked``
  — the per-stage seconds (``untracked`` included) sum to the trace's
  wall span exactly, the conservation the acceptance test pins to
  ±1e-9.
- :func:`queue_wait` / :func:`errored_span_names` — THE queue-wait and
  span-error definitions. Attribution's ``scheduling`` bucket
  (``FleetStatus._classify_inner``) and the waterfall's ``queue_wait``
  stage both read these, so the two surfaces can never disagree about
  how long a run sat in the workqueue.
- :func:`aggregate_waterfalls` — rolling p50/p95/p99 per stage over a
  check's recent waterfalls: the ``/statusz`` ``critical_path`` block
  and the ``healthcheck_critical_path_seconds{stage,quantile}`` gauges.
- :func:`merge_critical_path_blocks` / :func:`skew_block` — the
  multi-replica rollup (run-weighted, the goodput merge's convention);
  an old-binary replica that reports no block books its whole measured
  latency under ``untracked`` rather than vanishing from the fleet
  view.
- :func:`decompose_ttft` — the serving probe's TTFT split into
  queue-wait vs prefill vs first-decode, read off the PR 14
  scheduler's token-exact ``admitted_at`` / ``first_token_at`` /
  ``first_decode_at`` stamps.

Stage semantics (the vocabulary table in docs/observability.md):
``queue_wait`` is the workqueue's dequeue span, ``admission`` the
front door's submit-decision span, ``schedule`` the reconciler's
parse/decision span, ``submit``/``poll``/``status_write`` the engine
spans, ``probe_phase`` the probe's own ``PhaseTimings`` carved out of
the poll window it ran inside, and ``untracked`` everything the spans
leave uncovered — booked, never hidden.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

# ---------------------------------------------------------------------
# stage vocabulary — pinned by tests/test_lint.py across criticalpath,
# the metrics collector's stage-label validation, and the docs table.
# Path order: the order a healthy cycle traverses them.
# ---------------------------------------------------------------------

STAGE_QUEUE_WAIT = "queue_wait"
STAGE_ADMISSION = "admission"
STAGE_SCHEDULE = "schedule"
STAGE_SUBMIT = "submit"
STAGE_POLL = "poll"
STAGE_PROBE_PHASE = "probe_phase"
STAGE_STATUS_WRITE = "status_write"
STAGE_UNTRACKED = "untracked"

STAGES = (
    STAGE_QUEUE_WAIT,
    STAGE_ADMISSION,
    STAGE_SCHEDULE,
    STAGE_SUBMIT,
    STAGE_POLL,
    STAGE_PROBE_PHASE,
    STAGE_STATUS_WRITE,
    STAGE_UNTRACKED,
)

# span name -> stage. Root spans ("reconcile" from the workqueue path,
# "cycle" from the timer path) are deliberately unmapped: they cover
# the whole window, and the booked stages are their children.
SPAN_STAGES = {
    "dequeue": STAGE_QUEUE_WAIT,
    "admission": STAGE_ADMISSION,
    "parse": STAGE_SCHEDULE,
    "submit": STAGE_SUBMIT,
    "poll": STAGE_POLL,
    "status_write": STAGE_STATUS_WRITE,
}

QUANTILES = (0.50, 0.95, 0.99)
QUANTILE_KEYS = tuple(f"p{int(q * 100)}" for q in QUANTILES)


def _quantile(values: Sequence[float], q: float) -> float:
    """Nearest-rank quantile — ``sorted[ceil(q*n)-1]``, the SLO layer's
    exact convention (obs/slo.quantile; re-stated here rather than
    imported because slo.py imports THIS module for the classify-time
    queue-wait — the parity test in test_lint pins the two against each
    other). Callers guarantee a non-empty sample."""
    ordered = sorted(values)
    rank = min(len(ordered), max(1, math.ceil(q * len(ordered))))
    return float(ordered[rank - 1])


# ---------------------------------------------------------------------
# the one queue-wait / span-error definition (satellite of ISSUE 17:
# attribution's scheduling bucket and the waterfall read the same code)
# ---------------------------------------------------------------------


def queue_wait(spans) -> float:
    """Seconds the cycle sat in the workqueue: the longest finished
    ``dequeue`` span in the trace (the manager records exactly one per
    cycle; max is the defensive fold if a replay ever doubles it)."""
    wait = 0.0
    for span in spans:
        if getattr(span, "name", "") == "dequeue":
            duration = getattr(span, "duration", None)
            if duration:
                wait = max(wait, float(duration))
    return wait


def errored_span_names(spans) -> List[str]:
    """Names of spans an exception escaped — the control-plane evidence
    attribution feeds to ``classify_run(errored_spans=...)``."""
    return [
        span.name for span in spans if getattr(span, "error", "")
    ]


# ---------------------------------------------------------------------
# per-request waterfall
# ---------------------------------------------------------------------


def build_waterfall(
    spans, timings: Optional[dict] = None, trace_id: str = ""
) -> Optional[dict]:
    """Fold one trace's finished spans into a waterfall dict::

        {"trace_id", "wall_seconds", "stages": {stage: seconds},
         "dominant_stage", "segments": [{stage, offset_seconds, seconds}]}

    ``stages`` carries every name in :data:`STAGES` and sums to
    ``wall_seconds`` exactly (``untracked`` included). Booking is
    innermost-wins segmentation over the mapped spans: every elementary
    interval between span boundaries goes to the covering span that
    started LAST, so a nested span carves time out of its parent and
    cross-stage overlap can never double-book. The probe's
    ``PhaseTimings`` (durations without absolute placement) carve out
    of the ``poll`` stage they ran inside, capped at it. Returns None
    when the trace has no finished spans."""
    finished = [
        s for s in spans if getattr(s, "end", None) is not None
    ]
    if not finished:
        return None
    t0 = min(s.start for s in finished)
    t1 = max(s.end for s in finished)
    wall = max(0.0, t1 - t0)
    stages = {stage: 0.0 for stage in STAGES}
    mapped = [
        (s.start, s.end, SPAN_STAGES[s.name])
        for s in finished
        if s.name in SPAN_STAGES and s.end > s.start
    ]
    points = sorted({p for a, b, _stage in mapped for p in (a, b)})
    for a, b in zip(points, points[1:]):
        covering = [m for m in mapped if m[0] <= a and m[1] >= b]
        if not covering:
            continue
        stage = max(covering, key=lambda m: (m[0], -m[1]))[2]
        stages[stage] += b - a
    # probe phases: measured inside the probe process, so they subdivide
    # the poll window — never exceed it (a probe timing more work than
    # the controller polled for would un-conserve the sum)
    phase_total = 0.0
    for value in (timings or {}).values():
        try:
            phase_total += max(0.0, float(value))
        except (TypeError, ValueError):
            continue
    probe_phase = min(phase_total, stages[STAGE_POLL])
    stages[STAGE_POLL] -= probe_phase
    stages[STAGE_PROBE_PHASE] = probe_phase
    stages[STAGE_UNTRACKED] = max(
        0.0, wall - sum(stages[s] for s in STAGES if s != STAGE_UNTRACKED)
    )
    # earliest booked offset per stage, for the ASCII waterfall — the
    # probe phases inherit the poll window's start
    offsets: Dict[str, float] = {}
    for a, _b, stage in mapped:
        offsets[stage] = min(offsets.get(stage, a - t0), a - t0)
    if probe_phase > 0 and STAGE_POLL in offsets:
        offsets[STAGE_PROBE_PHASE] = offsets[STAGE_POLL]
    segments = [
        {
            "stage": stage,
            "offset_seconds": offsets.get(stage, 0.0),
            "seconds": stages[stage],
        }
        for stage in STAGES
        if stages[stage] > 0.0 and stage != STAGE_UNTRACKED
    ]
    segments.sort(key=lambda seg: (seg["offset_seconds"], STAGES.index(seg["stage"])))
    return {
        "trace_id": trace_id or getattr(finished[0], "trace_id", ""),
        "wall_seconds": wall,
        "stages": stages,
        "dominant_stage": dominant_stage(stages),
        "segments": segments,
    }


def dominant_stage(stages: Dict[str, float]) -> str:
    """The stage holding the most seconds; ties break in path order
    (:data:`STAGES`), so a deterministic answer on scripted clocks."""
    return max(STAGES, key=lambda s: float(stages.get(s) or 0.0))


# ---------------------------------------------------------------------
# rolling aggregation: the /statusz critical_path block
# ---------------------------------------------------------------------


def aggregate_waterfalls(waterfalls: Sequence[dict]) -> Optional[dict]:
    """p50/p95/p99 per stage over a window of waterfalls (oldest first;
    ``last`` is the newest run's full waterfall). ``dominant_stage`` is
    the stage with the largest p95 — the tail is what pages. Returns
    None over an empty window; ``skewed_runs`` is 0 here and non-zero
    only in :func:`skew_block` / the rollup merge."""
    if not waterfalls:
        return None
    walls = [float(w.get("wall_seconds") or 0.0) for w in waterfalls]
    stages = {}
    for stage in STAGES:
        values = [
            float((w.get("stages") or {}).get(stage) or 0.0)
            for w in waterfalls
        ]
        stages[stage] = {
            key: _quantile(values, q)
            for key, q in zip(QUANTILE_KEYS, QUANTILES)
        }
    return {
        "runs": len(waterfalls),
        "skewed_runs": 0,
        "wall": {
            key: _quantile(walls, q)
            for key, q in zip(QUANTILE_KEYS, QUANTILES)
        },
        "stages": stages,
        "dominant_stage": max(
            STAGES, key=lambda s: stages[s][QUANTILE_KEYS[1]]
        ),
        "last": waterfalls[-1],
    }


def skew_block(payload: dict) -> Optional[dict]:
    """Version-skew fallback for the rollup: an old-binary replica
    serves no ``critical_path`` block, but its per-check window
    quantiles still measure the path end to end — so its runs merge
    with their WHOLE latency booked under ``untracked`` (run-weighted
    mean of the per-check quantiles), never silently dropped. Returns
    None when the replica has no windowed runs either."""
    runs = 0
    weighted = {key: 0.0 for key in QUANTILE_KEYS}
    for entry in payload.get("checks") or []:
        window = entry.get("window") or {}
        n = int(window.get("results") or 0)
        if n <= 0:
            continue
        runs += n
        for key in QUANTILE_KEYS:
            weighted[key] += float(window.get(f"{key}_seconds") or 0.0) * n
    if runs == 0:
        return None
    untracked = {key: weighted[key] / runs for key in QUANTILE_KEYS}
    zero = {key: 0.0 for key in QUANTILE_KEYS}
    return {
        "runs": runs,
        "skewed_runs": runs,
        "wall": dict(untracked),
        "stages": {
            stage: (
                dict(untracked) if stage == STAGE_UNTRACKED else dict(zero)
            )
            for stage in STAGES
        },
        "dominant_stage": STAGE_UNTRACKED,
        "last": None,
    }


def merge_critical_path_blocks(
    blocks: Sequence[Optional[dict]],
) -> Optional[dict]:
    """Run-weighted merge of per-replica fleet blocks — the goodput
    merge's convention: each percentile value is the mean of the
    replicas' values weighted by their windowed runs (an approximation,
    same as the merged goodput ratio, and labelled as such in the
    docs). ``skewed_runs`` sums, so the fleet view says how much of the
    path is old-binary ``untracked`` rather than measured. ``last`` is
    first-seen-wins like the rollup's check dedupe."""
    real = [
        b for b in blocks
        if isinstance(b, dict) and int(b.get("runs") or 0) > 0
    ]
    if not real:
        return None
    total = sum(int(b["runs"]) for b in real)
    stages = {}
    for stage in STAGES:
        stages[stage] = {
            key: sum(
                float(
                    ((b.get("stages") or {}).get(stage) or {}).get(key)
                    or 0.0
                )
                * int(b["runs"])
                for b in real
            )
            / total
            for key in QUANTILE_KEYS
        }
    wall = {
        key: sum(
            float((b.get("wall") or {}).get(key) or 0.0) * int(b["runs"])
            for b in real
        )
        / total
        for key in QUANTILE_KEYS
    }
    last = next(
        (b["last"] for b in real if isinstance(b.get("last"), dict)), None
    )
    return {
        "runs": total,
        "skewed_runs": sum(int(b.get("skewed_runs") or 0) for b in real),
        "wall": wall,
        "stages": stages,
        "dominant_stage": max(
            STAGES, key=lambda s: stages[s][QUANTILE_KEYS[1]]
        ),
        "last": last,
    }


# ---------------------------------------------------------------------
# serving TTFT decomposition (scheduler/serving.py token-exact stamps)
# ---------------------------------------------------------------------


def decompose_ttft(sequences) -> Optional[dict]:
    """TTFT split per sequence from the continuous-batching scheduler's
    stamps: ``queue_wait`` (arrival → admission), ``prefill``
    (admission → first token; the two sum to TTFT exactly) and
    ``first_decode`` (first token → the first shared decode step's
    token; 0.0 for one-token requests). p50/p95/p99 over sequences that
    produced a first token; None when none did."""
    rows = []
    for seq in sequences:
        first_token = getattr(seq, "first_token_at", None)
        if first_token is None:
            continue
        arrival = seq.req.arrival
        admitted = seq.admitted_at
        first_decode = getattr(seq, "first_decode_at", None)
        rows.append(
            (
                max(0.0, admitted - arrival),
                max(0.0, first_token - admitted),
                (
                    max(0.0, first_decode - first_token)
                    if first_decode is not None
                    else 0.0
                ),
            )
        )
    if not rows:
        return None
    out = {"samples": len(rows)}
    for index, name in enumerate(("queue_wait", "prefill", "first_decode")):
        values = [row[index] for row in rows]
        out[name] = {
            key: _quantile(values, q)
            for key, q in zip(QUANTILE_KEYS, QUANTILES)
        }
    return out
