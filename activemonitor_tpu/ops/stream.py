"""HBM streaming kernel (Pallas) — the memory-bandwidth probe's hot op.

A blocked scale-copy: each grid step moves one (block, 1024) tile
HBM → VMEM, scales on the VPU, and writes back — 2 bytes moved per
payload byte, the STREAM "scale" pattern. A hand-set grid keeps each
tile within VMEM while the pipeline overlaps the next tile's DMA with
the current tile's compute (Pallas double-buffers automatically).

On non-TPU platforms the kernel runs in interpret mode (correct but
slow), so tests exercise the same code path on CPU; the probe falls
back to a plain jnp expression for *timing* there.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _scale_copy_kernel(in_ref, out_ref, *, scale):
    out_ref[:] = in_ref[:] * scale


def stream_scale_pallas(x: jax.Array, scale: float = 2.0, block_rows: int = 512):
    """Blocked scale-copy via Pallas; requires x.shape = (rows, 1024)
    with rows % block_rows == 0."""
    from jax.experimental import pallas as pl

    rows, cols = x.shape
    if rows % block_rows:
        raise ValueError(f"rows {rows} not divisible by block {block_rows}")
    interpret = jax.devices()[0].platform != "tpu"
    return pl.pallas_call(
        partial(_scale_copy_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        interpret=interpret,
    )(x)


def stream_scale_xla(x: jax.Array, scale: float = 2.0):
    """XLA fallback of the same op. The optimization barrier stops XLA
    from algebraically collapsing a chain of these into a single
    multiply (x * scale**k), which would fake k× the real bandwidth."""
    return jax.lax.optimization_barrier(x * scale)
