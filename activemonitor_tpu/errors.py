"""Shared exception types."""


class MissingDependencyError(RuntimeError):
    """An optional backend's package is not installed (e.g. cluster mode
    without ``kubernetes``). The CLI turns this into a usage error."""
