# Developer entry points (reference equivalent: kubebuilder-style Makefile).

PYTHON ?= python
IMG ?= ghcr.io/activemonitor-tpu/controller:latest

.PHONY: all test test-tpu bench bench-tpu bench-tpu-watch crd manifests run lint kind-e2e docker-build release-dryrun install help

all: test crd

test: ## run the suite on the virtual 8-device CPU mesh
	$(PYTHON) -m pytest tests/ -q

test-tpu: ## opt into real-hardware tests
	ACTIVEMONITOR_TEST_TPU=1 $(PYTHON) -m pytest tests/ -q

bench: ## one-line JSON benchmark (adaptive to hardware)
	$(PYTHON) bench.py

bench-tpu: ## one opportunistic TPU capture -> BENCH_TPU.json + SWEEP_TPU.md
	$(PYTHON) hack/tpu_evidence.py

bench-tpu-watch: ## poll for hours, capture whenever the tunnel is healthy
	$(PYTHON) hack/tpu_evidence.py --watch

crd: ## regenerate the CRD manifest from the pydantic models
	$(PYTHON) -m activemonitor_tpu crd > config/crd/activemonitor.keikoproj.io_healthchecks.yaml

deploy-manifest: ## regenerate the one-shot deploy file from config/
	$(PYTHON) hack/gen_deploy.py

manifests: crd deploy-manifest ## alias matching the reference's make target

run: ## run the controller locally (file store + local engine)
	$(PYTHON) -m activemonitor_tpu run --engine local --store ./healthchecks

lint: ## syntax + AST lint (undefined names, unused imports, bare except, ...)
	$(PYTHON) -m compileall -q activemonitor_tpu tests bench.py __graft_entry__.py
	$(PYTHON) hack/lint.py
	@for s in hack/*.sh deploy/*.sh; do bash -n "$$s" || exit 1; done; \
	  echo "shell syntax OK"

kind-e2e: ## real-cluster tier: kind + Argo + controller + a Succeeded check
	./hack/kind-e2e.sh

docker-build: ## build the controller+probes image
	docker build -t $(IMG) .

release-dryrun: ## every release.yml step that runs without docker/egress
	./hack/release_dryrun.sh

install: ## editable install
	$(PYTHON) -m pip install -e .

help:
	@grep -E '^[a-zA-Z_-]+:.*?## ' $(MAKEFILE_LIST) | awk 'BEGIN {FS = ":.*?## "}; {printf "  %-14s %s\n", $$1, $$2}'
