"""Probe model zoo."""

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    forward,
    init_params,
    loss_fn,
    param_count,
    param_specs,
    tiny_config,
)

__all__ = [
    "ProbeModelConfig",
    "forward",
    "init_params",
    "loss_fn",
    "param_count",
    "param_specs",
    "tiny_config",
]
