"""Goodput attribution + flight recorder (ISSUE 7): the bucket
taxonomy, the conservation property (per-subsystem lost ratios sum to
1 − goodput, per check, fleet-wide, and across a 3-replica sharded
rollup — including version skew), the flight-recorder triggers with
their /debug/traces joins, and the `am-tpu why` / `am-tpu goodput`
surfaces.
"""

import asyncio
import collections
import json

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import FleetStatus
from activemonitor_tpu.obs.attribution import (
    BUCKETS,
    classify_bench_round,
    classify_run,
    merge_goodput_blocks,
    subsystem_for_metric,
    summarize_results,
)
from activemonitor_tpu.obs.flightrec import FlightRecorder
from activemonitor_tpu.obs.slo import rollup_statusz
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"

ICI_METRIC = "ici-allreduce-fraction-of-rated"
HBM_METRIC = "hbm-fraction-of-rated"


def make_hc(name="hc-att", repeat=60, analysis=None, slo=None):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if analysis is not None:
        spec["analysis"] = analysis
    if slo is not None:
        spec["slo"] = slo
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


# ---------------------------------------------------------------------
# classification units
# ---------------------------------------------------------------------


def test_subsystem_vocabulary_mapping():
    assert subsystem_for_metric("ici-allreduce-fraction-of-rated") == "ici"
    assert subsystem_for_metric("ring-attention-busbw-gbps") == "ici"
    assert subsystem_for_metric("dcn-transfer-gbps") == "ici"  # first hit wins
    assert subsystem_for_metric("hbm-stream-gbps") == "hbm"
    assert subsystem_for_metric("compile-smoke-seconds") == "compile"
    # bench artifact spelling (underscores) maps identically
    assert subsystem_for_metric("ici_allreduce_fraction_of_rated") == "ici"
    # compute metrics have no subsystem — honest unknown, not a guess
    assert subsystem_for_metric("mxu-matmul-tflops") is None
    # token match, not substring: "pricing" must not read as ici
    assert subsystem_for_metric("pricing-total") is None


def test_classify_run_buckets_and_priority():
    # 1) payload evidence wins over everything, worst floor first
    got = classify_run(
        ok=False,
        metrics={ICI_METRIC: 0.41, HBM_METRIC: 0.6},
        degraded_controller=True,
    )
    assert got.bucket == "ici"
    assert ICI_METRIC in got.why
    # a passing-but-floored run is still classified (degraded evidence)
    assert classify_run(ok=True, metrics={HBM_METRIC: 0.5}).bucket == "hbm"
    # 2) confirmed anomaly verdict on a mapped metric
    got = classify_run(ok=False, anomalies={"ici-ring-hop-gbps": "degraded"})
    assert got.bucket == "ici"
    # an anomalous UNMAPPED metric is no subsystem evidence
    got = classify_run(ok=False, anomalies={"mxu-matmul-tflops": "degraded"})
    assert got.bucket == "unknown"
    # 3) compile-dominated timings
    got = classify_run(ok=False, timings={"compile": 30.0, "execute": 2.0})
    assert got.bucket == "compile"
    got = classify_run(ok=False, timings={"compile": 1.0, "execute": 30.0})
    assert got.bucket == "unknown"
    # 4) queue-wait dominated (late) runs
    got = classify_run(ok=False, queue_wait=45.0, interval=60.0)
    assert got.bucket == "scheduling"
    assert classify_run(ok=False, queue_wait=0.5, interval=60.0).bucket == "unknown"
    # 5) control plane: degraded controller / errored cycle spans
    assert classify_run(ok=False, degraded_controller=True).bucket == "control_plane"
    assert (
        classify_run(ok=False, errored_spans=["submit"]).bucket == "control_plane"
    )
    # unremarkable ok run: nothing to attribute
    assert classify_run(ok=True) is None
    # passing but confirmed-degraded on an unmapped metric: honest unknown
    got = classify_run(ok=True, anomaly_state="degraded")
    assert got.bucket == "unknown"


def test_summarize_results_conserves_per_check():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(hc, ok=True, latency=1.0, workflow="w1")
    fleet.record(
        hc, ok=False, latency=1.0, workflow="w2", metrics={ICI_METRIC: 0.4}
    )
    fleet.record(
        hc, ok=False, latency=1.0, workflow="w3", metrics={HBM_METRIC: 0.5}
    )
    fleet.record(hc, ok=False, latency=1.0, workflow="w4")
    [entry] = fleet.statusz([hc])["checks"]
    att = entry["attribution"]
    assert att["window_runs"] == 4
    assert att["lost_runs"] == 3
    assert att["buckets"]["ici"] == 0.25
    assert att["buckets"]["hbm"] == 0.25
    assert att["buckets"]["unknown"] == 0.25
    # conservation, per check: buckets sum to 1 - availability
    assert sum(att["buckets"].values()) == pytest.approx(
        1.0 - entry["window"]["availability"], abs=1e-9
    )
    assert att["top"] in ("ici", "hbm", "unknown")
    assert ICI_METRIC in entry["history"][1]["why"]
    assert summarize_results([]) is None


def test_classification_failure_never_drops_the_run():
    """Attribution is garnish on the SLO record: a classification bug
    (here: unfloatable timings from a caller outside the reconciler's
    parse path) must cost the bucket, never the run's availability."""
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(
        hc, ok=True, latency=1.0, workflow="w", timings={"init": "abc"}
    )
    [result] = fleet.history.results(hc.key)
    assert result.ok and result.bucket == ""
    [entry] = fleet.statusz([hc])["checks"]
    assert entry["window"]["availability"] == 1.0


def test_fleet_gauges_conserve_against_goodput_ratio():
    clock = FakeClock()
    metrics = MetricsCollector()
    fleet = FleetStatus(clock, metrics)
    a, b = make_hc("hc-a"), make_hc("hc-b")
    for _ in range(3):
        fleet.record(a, ok=True, latency=1.0, workflow="w")
    fleet.record(a, ok=False, latency=1.0, workflow="w", metrics={ICI_METRIC: 0.3})
    for _ in range(5):
        fleet.record(b, ok=True, latency=1.0, workflow="w")
    fleet.record(b, ok=False, latency=1.0, workflow="w")
    ratio = fleet.refresh_fleet_goodput()
    assert ratio == pytest.approx(8 / 10)
    lost = {
        bucket: metrics.sample_value(
            "healthcheck_goodput_lost_ratio", {"subsystem": bucket}
        )
        for bucket in BUCKETS
    }
    assert lost["ici"] == pytest.approx(1 / 10)
    assert lost["unknown"] == pytest.approx(1 / 10)
    # THE conservation property: per-subsystem lost ratios sum to
    # 1 - healthcheck_fleet_goodput_ratio
    assert sum(lost.values()) == pytest.approx(
        1.0 - metrics.sample_value("healthcheck_fleet_goodput_ratio", {}),
        abs=1e-9,
    )
    assert (
        metrics.sample_value(
            "healthcheck_goodput_attribution_info",
            {"version": "1", "top": lost["ici"] >= lost["unknown"] and "ici" or "unknown"},
        )
        == 1.0
    )


# ---------------------------------------------------------------------
# sharded rollup conservation + version skew
# ---------------------------------------------------------------------


def replica_payload(name, records):
    """One replica's /statusz payload (JSON round-tripped, like a real
    fetch) for a single check with the scripted (ok, metrics) runs."""
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc(name)
    for ok, metrics in records:
        fleet.record(hc, ok=ok, latency=1.0, workflow="w", metrics=metrics)
    return json.loads(json.dumps(fleet.statusz([hc])))


def test_rollup_conservation_across_three_replicas():
    payloads = [
        replica_payload(
            "hc-a", [(True, None)] * 3 + [(False, {ICI_METRIC: 0.4})]
        ),
        replica_payload(
            "hc-b", [(True, None)] * 2 + [(False, {ICI_METRIC: 0.3})] * 2
        ),
        replica_payload("hc-c", [(True, None)] * 2),
    ]
    rollup = rollup_statusz(payloads)
    fleet = rollup["fleet"]
    block = fleet["goodput"]
    # run-weighted: 10 runs, 3 lost, all ici
    assert fleet["goodput_ratio"] == pytest.approx(7 / 10)
    assert block["attribution"]["ici"] == pytest.approx(3 / 10)
    assert block["top"] == "ici"
    assert sum(block["attribution"].values()) == pytest.approx(
        1.0 - fleet["goodput_ratio"], abs=1e-9
    )


def test_rollup_version_skew_lands_in_unknown_and_still_conserves():
    """Satellite: a replica payload WITHOUT the goodput.attribution
    block (old binary mid rolling update) must not crash the rollup,
    and its lost share must surface as `unknown` — conservation holds
    because nothing vanishes."""
    payloads = [
        replica_payload(
            "hc-a", [(True, None)] * 3 + [(False, {ICI_METRIC: 0.4})]
        ),
        replica_payload(
            "hc-b", [(True, None)] * 2 + [(False, {ICI_METRIC: 0.3})] * 2
        ),
    ]
    # strip the new block from replica B, as an old binary would serve
    del payloads[1]["fleet"]["goodput"]
    rollup = rollup_statusz(payloads)
    fleet = rollup["fleet"]
    block = fleet["goodput"]
    assert fleet["goodput_ratio"] == pytest.approx(5 / 8)
    # replica A's loss keeps its bucket; replica B's is unattributable
    assert block["attribution"]["ici"] == pytest.approx(1 / 8)
    assert block["attribution"]["unknown"] == pytest.approx(2 / 8)
    assert block["top"] == "unknown"
    assert sum(block["attribution"].values()) == pytest.approx(
        1.0 - fleet["goodput_ratio"], abs=1e-9
    )
    # belt: a payload with NO fleet block at all doesn't crash either
    assert merge_goodput_blocks([{}])["ratio"] is None


# ---------------------------------------------------------------------
# acceptance: scripted FakeClock + FakeEngine fleet, end to end
# ---------------------------------------------------------------------

# (verdict, contract metrics): 7 clean passes, then one ici-floored
# failure, one hbm-floored failure, one bare failure → goodput 0.7,
# lost = ici 0.1 + hbm 0.1 + unknown 0.1
SCRIPT = (
    [(True, {ICI_METRIC: 0.97})] * 7
    + [
        (False, {ICI_METRIC: 0.41}),
        (False, {HBM_METRIC: 0.52}),
        (False, None),
    ]
)


def scripted_engine(script):
    engine = FakeWorkflowEngine()
    queue = collections.deque(script)
    assigned = {}

    def completer(wf, _count):
        name = wf["metadata"]["name"]
        if name not in assigned:
            if not queue:
                return None
            assigned[name] = queue.popleft()
        ok, metrics = assigned[name]
        status = {"phase": PHASE_SUCCEEDED if ok else PHASE_FAILED}
        if not ok:
            status["message"] = "scripted failure"
        if metrics is not None:
            contract = json.dumps(
                {
                    "metrics": [
                        {"name": name_, "value": value}
                        for name_, value in metrics.items()
                    ],
                    "timings": {"execute": 1.5},
                }
            )
            status["outputs"] = {
                "parameters": [{"name": "metrics", "value": contract}]
            }
        return status

    engine._default_completer = completer
    return engine


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


async def drive_runs(clock, count, interval=60.0, first=False):
    for i in range(count):
        if not first or i > 0:
            await clock.advance(interval)
        await settle()
        await clock.advance(1.0)
        await settle()


def build_controller(clock, client, engine):
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
    manager._health_addr = "127.0.0.1:0"
    return manager, reconciler, metrics


@pytest.mark.asyncio
async def test_acceptance_conservation_statusz_and_cli():
    import aiohttp

    from activemonitor_tpu.__main__ import render_goodput, render_why

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    manager, reconciler, metrics = build_controller(
        clock, client, scripted_engine(SCRIPT)
    )
    await manager.start()
    try:
        hc = make_hc("hc-ici")
        await client.apply(hc)
        await drive_runs(clock, len(SCRIPT), first=True)
        key = "health/hc-ici"
        results = reconciler.fleet.history.results(key)
        assert [r.ok for r in results] == [ok for ok, _m in SCRIPT]
        # record-time attribution landed on the ring
        assert results[7].bucket == "ici"
        assert results[8].bucket == "hbm"
        assert results[9].bucket == "unknown"
        assert ICI_METRIC in results[7].why
        # the contract timings rode into the ring too
        assert results[0].timings == {"execute": 1.5}

        # /statusz: fleet goodput block + per-check attribution
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port}/statusz") as r:
                assert r.status == 200
                payload = await r.json()
        fleet = payload["fleet"]
        assert fleet["goodput_ratio"] == pytest.approx(0.7)
        assert fleet["goodput"]["attribution"]["ici"] == pytest.approx(0.1)
        assert fleet["goodput"]["attribution"]["hbm"] == pytest.approx(0.1)
        assert fleet["goodput"]["attribution"]["unknown"] == pytest.approx(0.1)
        assert sum(fleet["goodput"]["attribution"].values()) == pytest.approx(
            1.0 - fleet["goodput_ratio"], abs=1e-9
        )
        [entry] = payload["checks"]
        att = entry["attribution"]
        assert sum(att["buckets"].values()) == pytest.approx(
            1.0 - entry["window"]["availability"], abs=1e-9
        )

        # the exact same numbers through the gauges (the acceptance
        # criterion): per-subsystem lost ratios sum to 1 - fleet ratio
        lost = {
            bucket: metrics.sample_value(
                "healthcheck_goodput_lost_ratio", {"subsystem": bucket}
            )
            for bucket in BUCKETS
        }
        fleet_ratio = metrics.sample_value(
            "healthcheck_fleet_goodput_ratio", {}
        )
        assert fleet_ratio == pytest.approx(0.7)
        assert sum(lost.values()) == pytest.approx(1.0 - fleet_ratio, abs=1e-9)
        assert lost["ici"] == pytest.approx(0.1)

        # ... and after a 3-replica sharded rollup (this replica's
        # payload + two synthetic peers), conservation still holds
        peers = [
            replica_payload(
                "hc-peer1", [(True, None)] * 4 + [(False, {ICI_METRIC: 0.2})]
            ),
            replica_payload("hc-peer2", [(True, None)] * 5),
        ]
        rollup = rollup_statusz([payload] + peers)
        rolled = rollup["fleet"]
        assert rolled["goodput_ratio"] == pytest.approx(16 / 20)
        assert sum(rolled["goodput"]["attribution"].values()) == pytest.approx(
            1.0 - rolled["goodput_ratio"], abs=1e-9
        )
        assert rolled["goodput"]["attribution"]["ici"] == pytest.approx(2 / 20)

        # CLI surfaces render from the same payload
        why_text = render_why(entry)
        assert "lost 30.0% of goodput" in why_text
        assert "ici" in why_text and "/debug/traces?trace_id=" in why_text
        goodput_text = render_goodput(payload)
        assert goodput_text.splitlines()[0].startswith("FLEET  goodput=70.0%")
        assert "TOP OFFENDERS" in goodput_text
        from activemonitor_tpu.__main__ import render_status_table

        table = render_status_table(payload)
        header, row = table.splitlines()[1], table.splitlines()[2]
        assert "WHY" in header.split()
        assert any(cell.endswith(":30%") for cell in row.split())

        # every lost run's trace joins back to /debug/traces?trace_id=
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces",
                params={"trace_id": results[7].trace_id},
            ) as r:
                traces = (await r.json())["traces"]
        assert traces and traces[0]["trace_id"] == results[7].trace_id
        # and the new ?check= filter narrows to this check's cycles
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces",
                params={"check": key},
            ) as r:
                by_check = (await r.json())["traces"]
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces",
                params={"check": "health/nope"},
            ) as r:
                none = (await r.json())["traces"]
        assert {t["trace_id"] for t in by_check} >= {
            r_.trace_id for r_ in results
        }
        assert none == []
    finally:
        await manager.stop()


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------

ANALYSIS_SPEC = {
    "warmupRuns": 5,
    "zThreshold": 3.0,
    "metrics": ["mxu-matmul-tflops"],
}


def analysis_engine_script(values):
    """FakeEngine whose Nth workflow succeeds immediately with the Nth
    scripted matmul sample (the test_analysis degradation walk)."""
    return scripted_engine(
        [(True, {"mxu-matmul-tflops": value}) for value in values]
    )


@pytest.mark.asyncio
async def test_forced_degradation_produces_exactly_one_joinable_bundle(tmp_path):
    """Acceptance: a forced ok→degraded transition produces exactly ONE
    flight bundle whose span/trace ids join back to
    /debug/traces?trace_id=, durable under --flight-dir."""
    import aiohttp

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=analysis_engine_script([100.0] * 5 + [70.0] * 4),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(
        client=client,
        reconciler=reconciler,
        max_parallel=2,
        flight_dir=str(tmp_path),
    )
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        hc = make_hc("hc-deg", analysis=ANALYSIS_SPEC)
        await client.apply(hc)
        key = "health/hc-deg"
        # 5 warmup runs at 100, then the 70s walk ok→warning→degraded
        await drive_runs(clock, 9, first=True)
        assert reconciler.analysis.state(key) == "degraded"
        bundles = reconciler.flightrec.bundles(kind="degraded-transition")
        assert len(bundles) == 1  # exactly one per confirmed episode
        [bundle] = bundles
        assert bundle["check"] == key
        assert bundle["trace_id"]
        assert bundle["spans"], "bundle carries the triggering cycle's spans"
        assert all(s["trace_id"] == bundle["trace_id"] for s in bundle["spans"])
        assert bundle["baselines"] is not None
        assert bundle["results"][-1]["metrics"] == {"mxu-matmul-tflops": 70.0}
        assert bundle["extra"]["transition"] == ["warning", "degraded"]

        # the bundle's trace joins back to /debug/traces?trace_id=
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces",
                params={"trace_id": bundle["trace_id"]},
            ) as r:
                traces = (await r.json())["traces"]
            assert traces and traces[0]["trace_id"] == bundle["trace_id"]
            span_ids = {s["span_id"] for s in traces[0]["spans"]}
            assert {s["span_id"] for s in bundle["spans"]} <= span_ids
            # served at /debug/flightrec with kind/check filters
            async with session.get(
                f"http://127.0.0.1:{port}/debug/flightrec",
                params={"kind": "degraded-transition", "check": key},
            ) as r:
                served = (await r.json())["bundles"]
            assert [b["id"] for b in served] == [bundle["id"]]
            async with session.get(
                f"http://127.0.0.1:{port}/debug/flightrec",
                params={"kind": "breaker-open"},
            ) as r:
                assert (await r.json())["bundles"] == []
        # durable: the same bundle landed as one JSONL line
        lines = list(
            FlightRecorder.read_jsonl(str(tmp_path / "flightrec.jsonl"))
        )
        assert [b["id"] for b in lines] == [bundle["id"]]
        # driving more degraded runs must NOT produce another bundle
        # (the transition already confirmed; no new episode)
    finally:
        await manager.stop()


def test_breaker_open_and_quarantine_trigger_bundles():
    from activemonitor_tpu.resilience import ResilienceCoordinator

    clock = FakeClock()
    coordinator = ResilienceCoordinator(clock, None)
    recorder = FlightRecorder(clock)
    recorder.resilience = coordinator
    coordinator.flightrec = recorder
    for _ in range(coordinator.breaker.failure_threshold):
        coordinator.breaker.record_failure()
    bundles = recorder.bundles(kind="breaker-open")
    assert len(bundles) == 1
    assert bundles[0]["resilience"]["breaker"]["state"] == "open"
    # a recorder failure must never raise into the transition path
    broken = FlightRecorder(clock)
    broken.tracer = object()  # no finished_spans attr -> internal error
    assert broken.record("breaker-open") is None
    assert len(broken) == 0


@pytest.mark.asyncio
async def test_quarantine_records_a_bundle():
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine()

    async def explode(_manifest):
        raise RuntimeError("boom")

    engine.submit = explode
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    hc = make_hc("hc-q")
    await client.apply(hc)
    threshold = reconciler.resilience.checks.quarantine_after
    for _ in range(threshold + 1):
        await reconciler.reconcile("health", "hc-q")
        await asyncio.sleep(0)
    bundles = reconciler.flightrec.bundles(kind="quarantine")
    assert len(bundles) == 1
    assert bundles[0]["check"] == "health/hc-q"


# ---------------------------------------------------------------------
# bench-round attribution (artifact-side, same taxonomy)
# ---------------------------------------------------------------------


def test_classify_bench_round():
    hang = classify_bench_round(
        {
            "fallback": True,
            "fallback_reason": "device probe hung past 120s on attempt 2/4 "
            "(wedged tunnel?)",
        }
    )
    assert hang == {
        "bucket": "control_plane",
        "why": "CPU fallback: device probe hang (device probe hung past "
        "120s on attempt 2/4 (wedged tunnel?))",
    }
    exited = classify_bench_round(
        {"fallback": True, "fallback_reason": "device probe exited with 1"}
    )
    assert exited["bucket"] == "control_plane"
    assert "exited with 1" in exited["why"]
    regression = classify_bench_round(
        {
            "metric": "ici_allreduce_fraction_of_rated",
            "value": 0.72,
            "vs_baseline": 0.8,
        }
    )
    assert regression["bucket"] == "ici"
    assert "real regression" in regression["why"]
    compute = classify_bench_round(
        {"metric": "mxu_bf16_fraction_of_rated", "vs_baseline": 0.9}
    )
    assert compute["bucket"] == "unknown"
    # a CPU-mesh round below its prior CPU artifact is host variance,
    # never an ici regression claim
    cpu_noise = classify_bench_round(
        {
            "metric": "allreduce_busbw_cpu_mesh",
            "platform": "cpu",
            "vs_baseline": 0.8,
        }
    )
    assert cpu_noise["bucket"] == "unknown"
    assert "host variance" in cpu_noise["why"]
    healthy = classify_bench_round(
        {"metric": "ici_allreduce_fraction_of_rated", "vs_baseline": 1.03}
    )
    assert healthy["bucket"] == "none"


def test_bench_stamps_attribution_next_to_fallback_reason():
    """The satellite wiring gate: bench.py calls classify_bench_round
    on every artifact (the stamp helper is importable and the call site
    exists), so BENCH_r*.json records WHY a round lost goodput."""
    from pathlib import Path

    src = (Path(__file__).resolve().parent.parent / "bench.py").read_text()
    assert "classify_bench_round" in src
    assert "goodput_attribution" in src


# ---------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------


def test_why_and_goodput_cli_flags_parse():
    from activemonitor_tpu.__main__ import build_parser

    args = build_parser().parse_args(["why", "hc-ici"])
    assert args.name == "hc-ici"
    assert args.namespace is None
    assert args.url is None
    assert args.output == "text"
    args = build_parser().parse_args(
        ["goodput", "--url", "http://x:1/statusz", "--url", "http://y:1/statusz",
         "-o", "json"]
    )
    assert len(args.url) == 2
    assert args.output == "json"
    args = build_parser().parse_args(["run", "--flight-dir", "/tmp/fl"])
    assert args.flight_dir == "/tmp/fl"


@pytest.mark.asyncio
async def test_why_cli_fetches_and_explains(capsys):
    from activemonitor_tpu.__main__ import _goodput, _why, build_parser

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    manager, reconciler, _metrics = build_controller(
        clock, client, scripted_engine([(False, {ICI_METRIC: 0.4})])
    )
    await manager.start()
    try:
        await client.apply(make_hc("hc-ici"))
        await drive_runs(clock, 1, first=True)
        port = manager._http_runners[0].addresses[0][1]
        url = f"http://127.0.0.1:{port}/statusz"
        args = build_parser().parse_args(["why", "hc-ici", "--url", url])
        assert await _why(args) == 0
        out = capsys.readouterr().out
        assert "health/hc-ici" in out
        assert "ici" in out and "below rated floor" in out
        args = build_parser().parse_args(["goodput", "--url", url])
        assert await _goodput(args) == 0
        out = capsys.readouterr().out
        assert out.startswith("FLEET  goodput=0.0%")
        assert "ici" in out
        # an unknown check name is a clean usage failure, not a traceback
        args = build_parser().parse_args(["why", "nope", "--url", url])
        assert await _why(args) == 1
    finally:
        await manager.stop()
