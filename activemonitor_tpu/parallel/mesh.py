"""Device mesh construction helpers.

Probes run over a `jax.sharding.Mesh` — 1D ("ici") for collective
bandwidth probes, 2D ("data", "model") for the sharded training-step
probe. The same code runs on a real TPU slice or on a virtual CPU
device set (``--xla_force_host_platform_device_count``), mirroring the
reference's envtest strategy (SURVEY.md §4): data model real, hardware
optional.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger("activemonitor.parallel")


def device_info() -> dict:
    """Inventory of visible devices (the devices-probe payload)."""
    devices = jax.devices()
    return {
        "platform": devices[0].platform if devices else "none",
        "device_kind": devices[0].device_kind if devices else "none",
        "count": len(devices),
        "process_count": jax.process_count(),
        "local_count": jax.local_device_count(),
    }


def make_1d_mesh(axis: str = "ici", devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def best_2d_shape(n: int) -> Tuple[int, int]:
    """Most-square factorization of n, favoring a larger second (model)
    axis so tensor-parallel collectives ride the shorter ICI hops."""
    best = (1, n)
    for a in range(1, int(np.sqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    return best


def make_2d_mesh(
    axes: Tuple[str, str] = ("data", "model"),
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    explicit_devices = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = best_2d_shape(len(devices))
    if shape[0] * shape[1] != len(devices):
        raise ValueError(f"mesh shape {shape} does not fit {len(devices)} devices")
    if not explicit_devices and devices and devices[0].platform == "tpu":
        # align logical axes with the physical torus: a naive id-order
        # reshape interleaves torus rows/columns, so each logical-axis
        # ring would traverse BOTH physical dimensions (and per-axis
        # bandwidth probes could not localize a sick link direction)
        try:
            from jax.experimental import mesh_utils

            return Mesh(mesh_utils.create_device_mesh(shape), axes)
        except Exception:  # unknown topology: fall back to id order
            log.debug("torus-aligned mesh unavailable", exc_info=True)
    return Mesh(np.array(devices).reshape(shape), axes)


def make_mesh(
    axes: Sequence[str],
    shape: Sequence[int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """N-dimensional mesh (the ≥3-axis composed case: dp×tp×pp). On
    real TPU, axes are aligned to the physical torus via
    ``mesh_utils.create_device_mesh`` like :func:`make_2d_mesh`."""
    explicit_devices = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} does not fit {len(devices)} devices")
    if not explicit_devices and devices and devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            return Mesh(mesh_utils.create_device_mesh(tuple(shape)), tuple(axes))
        except Exception:  # unknown topology: fall back to id order
            log.debug("torus-aligned mesh unavailable", exc_info=True)
    return Mesh(np.array(devices).reshape(tuple(shape)), tuple(axes))


def make_synthetic_two_tier_mesh(
    devices: Optional[Sequence] = None,
) -> Optional[Mesh]:
    """A single-process stand-in for a multislice topology: the flat
    device set re-meshed into (2, n/2) ("dcn", "ici") tiers — what the
    hierarchical collective cases/bench stamps measure when no real
    cross-host tier exists (probes/dcn.py owns the real one). Returns
    None when the set cannot form the shape (odd or < 4 devices), so
    callers surface a structured skip naming {"dcn": 2, "ici": n//2}
    instead of crashing — one rule, shared by every synthetic site."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n < 4 or n % 2:
        return None
    return Mesh(np.array(devices).reshape(2, n // 2), ("dcn", "ici"))


def make_multihost_mesh(axes: Tuple[str, str] = ("dcn", "ici")) -> Mesh:
    """Hierarchical mesh for multi-host runs: the outer axis spans
    processes (hosts — traffic rides DCN between slices/hosts), the
    inner axis spans each host's local devices (traffic rides ICI).
    Requires jax.distributed to be initialized so all hosts share one
    global device set. Devices are grouped by owning process so the
    outer axis really is the cross-host direction."""
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n_proc = jax.process_count()
    counts: dict = {}
    for d in devices:
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    if len(set(counts.values())) != 1:
        # unequal per-host device counts would silently mix intra- and
        # cross-host traffic on the "dcn" axis after the reshape
        raise ValueError(
            f"uneven devices per process ({counts}); cannot form a "
            "rectangular (dcn, ici) mesh"
        )
    local = len(devices) // n_proc
    return Mesh(np.array(devices).reshape(n_proc, local), axes)
