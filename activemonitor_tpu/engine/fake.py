"""Fake workflow engine — the data model is real, no executor runs.

Mirrors the reference's envtest strategy (SURVEY.md §4): the Workflow
CRD exists so objects can be created and polled, but nothing drives them
to completion unless the test scripts it. Default behavior is therefore
"never completes", which exercises the poll-timeout → synthesized-Failed
path exactly like the reference integration tests do
(reference: internal/controllers/healthcheck_controller_test.go:41-242).
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional

from activemonitor_tpu.engine.base import (
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    generate_name,
)

# completer(workflow, poll_count) -> status dict to set, or None to leave pending
Completer = Callable[[dict, int], Optional[dict]]


def succeed_after(polls: int, outputs: Optional[dict] = None) -> Completer:
    """Workflow reaches Succeeded on the Nth poll (1-based)."""

    def completer(_wf: dict, count: int) -> Optional[dict]:
        if count >= polls:
            status = {"phase": PHASE_SUCCEEDED}
            if outputs is not None:
                status["outputs"] = outputs
            return status
        return None

    return completer


def fail_after(polls: int, message: str = "probe failed") -> Completer:
    def completer(_wf: dict, count: int) -> Optional[dict]:
        if count >= polls:
            return {"phase": PHASE_FAILED, "message": message}
        return None

    return completer


def never_complete() -> Completer:
    return lambda wf, count: None


class FakeWorkflowEngine:
    name = "fake"  # engine label on submit/poll counters

    def __init__(self, completer: Completer | None = None):
        self._workflows: Dict[str, dict] = {}  # key: ns/name
        self._poll_counts: Dict[str, int] = {}
        self._default_completer = completer or never_complete()
        # per-generateName-prefix overrides, matched by startswith
        self._prefix_completers: List[tuple[str, Completer]] = []
        self.submitted: List[dict] = []  # submission log for assertions

    def on_prefix(self, prefix: str, completer: Completer) -> None:
        """Script behavior for workflows whose name starts with prefix."""
        self._prefix_completers.append((prefix, completer))

    def _completer_for(self, name: str) -> Completer:
        for prefix, completer in self._prefix_completers:
            if name.startswith(prefix):
                return completer
        return self._default_completer

    async def submit(self, manifest: dict) -> str:
        manifest = copy.deepcopy(manifest)
        meta = manifest.setdefault("metadata", {})
        name = meta.get("name") or generate_name(meta.get("generateName", "wf-"))
        meta["name"] = name
        namespace = meta.get("namespace", "default")
        self._workflows[f"{namespace}/{name}"] = manifest
        self._poll_counts[f"{namespace}/{name}"] = 0
        self.submitted.append(manifest)
        return name

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        key = f"{namespace}/{name}"
        wf = self._workflows.get(key)
        if wf is None:
            return None
        self._poll_counts[key] += 1
        if "status" not in wf or wf["status"].get("phase") not in (
            PHASE_SUCCEEDED,
            PHASE_FAILED,
        ):
            status = self._completer_for(name)(wf, self._poll_counts[key])
            if status is not None:
                wf["status"] = status
        return copy.deepcopy(wf)

    # test helpers -----------------------------------------------------
    def set_status(self, namespace: str, name: str, status: dict) -> None:
        self._workflows[f"{namespace}/{name}"]["status"] = status

    def delete(self, namespace: str, name: str) -> None:
        self._workflows.pop(f"{namespace}/{name}", None)

    def delete_owned_by(self, uid: str) -> int:
        """GC workflows owned by a HealthCheck UID (the ownerReference
        cascade the API server provides in the reference,
        healthcheck_controller.go:512-522)."""
        doomed = [
            k
            for k, wf in self._workflows.items()
            if any(
                ref.get("uid") == uid
                for ref in wf.get("metadata", {}).get("ownerReferences", [])
            )
        ]
        for k in doomed:
            del self._workflows[k]
        return len(doomed)

    @property
    def workflows(self) -> Dict[str, dict]:
        return self._workflows
