"""Timer wheel tests (reference behavior: healthcheck_controller.go:745-754
reschedule, :180-184 cancel-on-delete, :264-267 exists-for-dedupe)."""


import pytest

from activemonitor_tpu.scheduler import TimerWheel
from activemonitor_tpu.utils.clock import FakeClock


@pytest.mark.asyncio
async def test_fires_after_delay():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(clock.monotonic())

    wheel.schedule("hc-a", 30, cb)
    await clock.advance(29)
    assert fired == []
    await clock.advance(2)
    assert fired == [30.0]


@pytest.mark.asyncio
async def test_reschedule_replaces_pending_timer():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def mk(tag):
        async def cb():
            fired.append(tag)
        return cb

    wheel.schedule("hc-a", 30, await mk("first"))
    await clock.advance(10)
    wheel.schedule("hc-a", 30, await mk("second"))
    await clock.advance(100)
    assert fired == ["second"]


@pytest.mark.asyncio
async def test_stop_cancels_pending():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(1)

    wheel.schedule("hc-a", 30, cb)
    assert wheel.pending("hc-a")
    assert wheel.stop("hc-a") is True
    await clock.advance(100)
    assert fired == []
    assert not wheel.exists("hc-a")


@pytest.mark.asyncio
async def test_exists_after_firing_for_dedupe():
    clock = FakeClock()
    wheel = TimerWheel(clock)

    async def cb():
        pass

    wheel.schedule("hc-a", 1, cb)
    await clock.advance(5)
    assert wheel.exists("hc-a")  # fired entries remain (dedupe contract)
    assert not wheel.pending("hc-a")
    assert wheel.stop("hc-a") is False  # nothing pending to cancel


@pytest.mark.asyncio
async def test_callback_exception_does_not_kill_wheel(caplog):
    clock = FakeClock()
    wheel = TimerWheel(clock)

    async def boom():
        raise RuntimeError("probe exploded")

    async def ok():
        fired.append(1)

    fired = []
    wheel.schedule("hc-bad", 1, boom)
    wheel.schedule("hc-good", 2, ok)
    await clock.advance(5)
    assert fired == [1]


@pytest.mark.asyncio
async def test_shutdown_cancels_everything():
    clock = FakeClock()
    wheel = TimerWheel(clock)
    fired = []

    async def cb():
        fired.append(1)

    for i in range(5):
        wheel.schedule(f"hc-{i}", 10, cb)
    await wheel.shutdown()
    await clock.advance(100)
    assert fired == []
