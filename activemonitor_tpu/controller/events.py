"""Event recording.

The reference emits a Kubernetes Event on every significant transition
(~40 call sites; reference: healthcheck_controller.go:135 recorder,
SURVEY.md §5.5). Here events always land in structured logs and an
in-memory ring (queryable by tests and the CLI); a Kubernetes-backed
recorder can wrap this one in cluster mode.
"""

from __future__ import annotations

import collections
import datetime
import logging
from dataclasses import dataclass, field
from typing import Deque, List

from activemonitor_tpu.api.types import HealthCheck

log = logging.getLogger("activemonitor.events")

EVENT_NORMAL = "Normal"
EVENT_WARNING = "Warning"


@dataclass
class Event:
    type: str
    reason: str
    message: str
    namespace: str
    name: str
    timestamp: datetime.datetime = field(
        default_factory=lambda: datetime.datetime.now(datetime.timezone.utc)
    )


class EventRecorder:
    def __init__(self, capacity: int = 1000):
        self._events: Deque[Event] = collections.deque(maxlen=capacity)

    def event(self, hc: HealthCheck, type_: str, reason: str, message: str) -> None:
        ev = Event(
            type=type_,
            reason=reason,
            message=message,
            namespace=hc.metadata.namespace,
            name=hc.metadata.name,
        )
        self._events.append(ev)
        level = logging.WARNING if type_ == EVENT_WARNING else logging.INFO
        log.log(level, "%s/%s: %s: %s", ev.namespace, ev.name, reason, message)

    def events_for(self, namespace: str, name: str) -> List[Event]:
        return [e for e in self._events if e.namespace == namespace and e.name == name]

    @property
    def all(self) -> List[Event]:
        return list(self._events)
