"""Cluster registry: capability descriptors + movement-judged health.

Each member cluster is described by a :class:`ClusterDescriptor` —
generation and rated figures derived from the ``probes/rated.py``
tables (one source of truth with the probes' fraction-of-rated
denominators) plus the deployment facts no table can know: chip count,
mesh topology, the slices it owns, and a per-host ``dcn_gbps``
override for fleets that know their NICs.

Health is judged the way sharding's member leases are: by
LOCALLY-OBSERVED movement, never by the remote's own wall-clock
stamps. Every ``/statusz`` poll lands in :meth:`ClusterRegistry.
observe`; a payload whose ``fleet.generated_at`` differs from the last
one seen is movement, stamped on OUR monotonic clock. A cluster whose
payload stops moving for ``liveness_seconds`` is unhealthy —
a skewed remote clock can neither fake liveness nor fake death.

Transitions (join / leave / unhealthy / recovered) each fire exactly
ONE flight-recorder bundle (state-change gated, so a cluster that
stays dark does not re-fire every sweep) and one
``healthcheck_federation_transitions_total`` increment.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.probes.rated import capability_summary
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.federation")

STATE_HEALTHY = "healthy"
STATE_UNHEALTHY = "unhealthy"

# flight-bundle kinds (one bundle per transition, exactly once)
KIND_CLUSTER_JOIN = "cluster-join"
KIND_CLUSTER_LEAVE = "cluster-leave"
KIND_CLUSTER_UNHEALTHY = "cluster-unhealthy"
KIND_CLUSTER_RECOVERED = "cluster-recovered"

# a cluster whose /statusz stops moving for this long is unhealthy —
# deliberately longer than sharding's lease window (15 s): cross-
# cluster polls ride WAN links and the goodput-loop cadence (30 s)
DEFAULT_LIVENESS_SECONDS = 90.0


@dataclass(frozen=True)
class ClusterDescriptor:
    """One cluster's capability card, as the router and ``am-tpu
    clusters`` see it. ``capability`` carries the rated figures
    (:func:`~activemonitor_tpu.probes.rated.capability_summary`) for
    the declared ``device_kind``; empty for unknown hardware."""

    name: str
    url: str = ""  # /statusz endpoint; "" = in-process (tests, co-hosted)
    device_kind: str = ""  # jax device_kind string, e.g. "TPU v5p"
    generation: str = ""  # rated-table generation, e.g. "v5p"
    chips: int = 0
    topology: str = ""  # mesh shape, e.g. "4x4" / "2x2x2"
    slices: Tuple[str, ...] = ()
    dcn_gbps: float = 0.0  # per-host, one direction
    capability: dict = field(default_factory=dict)

    @classmethod
    def build(
        cls,
        name: str,
        *,
        url: str = "",
        device_kind: str = "",
        chips: int = 0,
        topology: str = "",
        slices=(),
        dcn_gbps: float = 0.0,
    ) -> "ClusterDescriptor":
        """Derive the capability card from the rated tables: generation
        and dcn tier come from ``capability_summary(device_kind)`` (env
        overrides flow through), with the explicit ``dcn_gbps`` winning
        when the deployment declares its own NIC provisioning."""
        cap = capability_summary(device_kind) or {}
        return cls(
            name=str(name),
            url=str(url),
            device_kind=str(device_kind),
            generation=str(cap.get("generation") or ""),
            chips=max(0, int(chips)),
            topology=str(topology),
            slices=tuple(str(s) for s in slices),
            dcn_gbps=(
                float(dcn_gbps)
                if float(dcn_gbps) > 0
                else float(cap.get("dcn_gbps") or 0.0)
            ),
            capability=cap,
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "url": self.url,
            "device_kind": self.device_kind,
            "generation": self.generation,
            "chips": self.chips,
            "topology": self.topology,
            "slices": list(self.slices),
            "dcn_gbps": self.dcn_gbps,
            "capability": dict(self.capability),
        }


class _Member:
    """One cluster's mutable liveness record."""

    __slots__ = (
        "descriptor",
        "state",
        "last_generated_at",
        "last_movement",
        "payload",
        "transitions",
    )

    def __init__(self, descriptor: ClusterDescriptor, joined_mono: float):
        self.descriptor = descriptor
        self.state = STATE_HEALTHY
        # the last fleet.generated_at seen — REMOTE data used only for
        # inequality (movement), never compared against our clock
        self.last_generated_at = ""
        # OUR monotonic stamp of the last observed movement; join time
        # seeds it so a fresh member gets a full liveness window before
        # the first poll can land
        self.last_movement = joined_mono
        self.payload: Optional[dict] = None  # latest observed /statusz
        self.transitions = 0


class ClusterRegistry:
    """The federation's membership + liveness table (single-owner on
    the event loop, like the manager's queue sets)."""

    def __init__(
        self,
        *,
        clock: Optional[Clock] = None,
        liveness_seconds: float = DEFAULT_LIVENESS_SECONDS,
        metrics=None,  # MetricsCollector (duck-typed; optional)
        flightrec=None,  # FlightRecorder (duck-typed; optional)
    ):
        self.clock = clock or Clock()
        self.liveness_seconds = max(1.0, float(liveness_seconds))
        self.metrics = metrics
        self.flightrec = flightrec
        self._members: Dict[str, _Member] = {}

    # -- membership ------------------------------------------------------
    def join(self, descriptor: ClusterDescriptor) -> None:
        """Register (or re-register) a cluster, healthy until its
        liveness window passes with no observed movement."""
        member = _Member(descriptor, self.clock.monotonic())
        self._members[descriptor.name] = member
        self._transition(member, KIND_CLUSTER_JOIN)

    def leave(self, name: str) -> None:
        """Drop a cluster from the federation (operator action — an
        unhealthy cluster stays listed so its absence is visible)."""
        member = self._members.pop(name, None)
        if member is None:
            return
        self._transition(member, KIND_CLUSTER_LEAVE)

    # -- liveness --------------------------------------------------------
    def observe(self, name: str, payload: dict) -> bool:
        """One ``/statusz`` poll landed for ``name``. Movement — a
        ``fleet.generated_at`` different from the last one seen — is
        stamped on the local monotonic clock and recovers an unhealthy
        cluster (firing one ``cluster-recovered`` bundle). Returns
        whether the poll counted as movement."""
        member = self._members.get(name)
        if member is None:
            return False
        member.payload = payload
        stamp = str(((payload or {}).get("fleet") or {}).get("generated_at") or "")
        if not stamp or stamp == member.last_generated_at:
            return False
        member.last_generated_at = stamp
        member.last_movement = self.clock.monotonic()
        if member.state == STATE_UNHEALTHY:
            member.state = STATE_HEALTHY
            self._transition(member, KIND_CLUSTER_RECOVERED)
        return True

    def sweep(self) -> List[Tuple[str, str]]:
        """Judge liveness: any healthy cluster whose observed movement
        is older than the liveness window transitions to unhealthy,
        firing exactly one ``cluster-unhealthy`` bundle (the state gate
        — not a cooldown — is what makes repeat sweeps quiet). Returns
        the ``(name, kind)`` transitions this sweep produced."""
        now = self.clock.monotonic()
        transitions: List[Tuple[str, str]] = []
        for member in self._members.values():
            if (
                member.state == STATE_HEALTHY
                and now - member.last_movement >= self.liveness_seconds
            ):
                member.state = STATE_UNHEALTHY
                self._transition(member, KIND_CLUSTER_UNHEALTHY)
                transitions.append((member.descriptor.name, KIND_CLUSTER_UNHEALTHY))
        return transitions

    # -- reading ---------------------------------------------------------
    def healthy(self) -> List[ClusterDescriptor]:
        """Healthy clusters, name-sorted (the router's candidate list —
        deterministic order so routing is reproducible)."""
        return [
            m.descriptor
            for _name, m in sorted(self._members.items())
            if m.state == STATE_HEALTHY
        ]

    def get(self, name: str) -> Optional[ClusterDescriptor]:
        member = self._members.get(name)
        return member.descriptor if member is not None else None

    def state(self, name: str) -> str:
        member = self._members.get(name)
        return member.state if member is not None else ""

    def names(self) -> List[str]:
        return sorted(self._members)

    def payloads(self) -> Dict[str, dict]:
        """Latest observed ``/statusz`` payload per cluster (unhealthy
        clusters included — their last evidence still merges into the
        federated rollup, flagged by the clusters block's state)."""
        return {
            name: m.payload
            for name, m in sorted(self._members.items())
            if m.payload is not None
        }

    def snapshot(self) -> dict:
        """The registry half of the ``/statusz`` federation block."""
        now = self.clock.monotonic()
        healthy = unhealthy = 0
        clusters = {}
        for name, member in sorted(self._members.items()):
            if member.state == STATE_HEALTHY:
                healthy += 1
            else:
                unhealthy += 1
            d = member.descriptor
            clusters[name] = {
                "state": member.state,
                "url": d.url,
                "generation": d.generation,
                "chips": d.chips,
                "topology": d.topology,
                "slices": list(d.slices),
                "dcn_gbps": d.dcn_gbps,
                "generated_at": member.last_generated_at,
                "movement_age_seconds": max(0.0, now - member.last_movement),
                "transitions": member.transitions,
            }
        return {
            "liveness_seconds": self.liveness_seconds,
            "healthy": healthy,
            "unhealthy": unhealthy,
            "clusters": clusters,
        }

    def export_metrics(self) -> None:
        """Refresh the registry gauges (cluster counts by state, the
        per-cluster health bit). Driven by the plane's sweep; a
        registry without a collector is a no-op."""
        if self.metrics is None:
            return
        snap = self.snapshot()
        self.metrics.set_federation_clusters(snap["healthy"], snap["unhealthy"])
        for name, row in snap["clusters"].items():
            self.metrics.set_federation_cluster_health(
                name, row["state"] == STATE_HEALTHY
            )

    # -- internals -------------------------------------------------------
    def _transition(self, member: _Member, kind: str) -> None:
        """Book one membership/health transition: counted, metered, and
        flight-recorded with the capability card and liveness evidence
        of the moment. Never raises into the sweep/poll that drove it
        (the recorder's own contract plus a guard for hostile ducks)."""
        member.transitions += 1
        name = member.descriptor.name
        log.warning("federation cluster %s: %s", name, kind)
        if self.metrics is not None:
            try:
                self.metrics.record_federation_transition(name, kind)
            except Exception:
                log.exception("federation transition metric failed")
        if self.flightrec is not None:
            try:
                self.flightrec.record(
                    kind,
                    cluster=name,
                    state=member.state,
                    descriptor=member.descriptor.to_dict(),
                    last_generated_at=member.last_generated_at,
                )
            except Exception:
                log.exception("federation flight bundle failed for %s", name)
