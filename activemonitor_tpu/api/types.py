"""HealthCheck API types.

Field-for-field capability match of the reference CRD schema
(reference: api/v1alpha1/healthcheck_types.go:32-151), expressed as
pydantic models so specs validate on load (the reference relies on the
generated OpenAPI schema in
config/crd/bases/activemonitor.keikoproj.io_healthchecks.yaml for this).

JSON field names (aliases) match the reference json tags exactly, so any
YAML written for the reference loads unchanged.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field

from activemonitor_tpu import API_VERSION, KIND

# Level values (reference: healthcheck_controller.go:62-63)
LEVEL_CLUSTER = "cluster"
LEVEL_NAMESPACE = "namespace"

# Workflow type discriminators (reference: healthcheck_controller.go:60-61)
WORKFLOW_TYPE_HEALTHCHECK = "healthCheck"
WORKFLOW_TYPE_REMEDY = "remedy"

# Terminal phases (reference: healthcheck_controller.go:58-59)
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"
STATUS_STOPPED = "Stopped"


class _Base(BaseModel):
    """Common config: accept both pythonic names and JSON aliases."""

    model_config = ConfigDict(populate_by_name=True, extra="ignore")

    def to_json_dict(self) -> dict:
        return self.model_dump(by_alias=True, exclude_none=True, exclude_defaults=True)


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class PolicyRule(_Base):
    """RBAC policy rule (mirror of rbacv1.PolicyRule as used by the
    reference rbacRules fields; reference: healthcheck_types.go:101,113)."""

    api_groups: List[str] = Field(default_factory=list, alias="apiGroups")
    resources: List[str] = Field(default_factory=list, alias="resources")
    verbs: List[str] = Field(default_factory=list, alias="verbs")
    resource_names: List[str] = Field(default_factory=list, alias="resourceNames")
    non_resource_urls: List[str] = Field(default_factory=list, alias="nonResourceURLs")


class FileArtifact(_Base):
    """Artifact on the local filesystem (reference: healthcheck_types.go:134-136).

    The reference declares this field but never implements a reader
    (store/store.go:15-21 returns "unknown artifact location"); this
    framework implements it for real (see store/file.py).
    """

    path: str = ""


class URLArtifact(_Base):
    """Artifact at an HTTP(S) endpoint (reference: healthcheck_types.go:139-145).

    verify_cert=None (omitted) or True verifies TLS certificates — the
    secure default; only an explicit False disables verification.
    """

    path: str = ""
    verify_cert: Optional[bool] = Field(default=None, alias="verifyCert")


class ArtifactLocation(_Base):
    """Source location of a workflow manifest (reference: healthcheck_types.go:127-131)."""

    inline: Optional[str] = None
    file: Optional[FileArtifact] = None
    url: Optional[URLArtifact] = None


class ResourceObject(_Base):
    """The workflow resource to create (reference: healthcheck_types.go:117-124)."""

    namespace: str = ""
    service_account: str = Field(default="", alias="serviceAccount")
    source: ArtifactLocation = Field(default_factory=ArtifactLocation)


class TPUPlacement(_Base):
    """TPU slice placement for the probe workload (extension; no
    counterpart in the reference — SURVEY.md §7.7: the controller
    injects TPU node selectors the way podGC is injected today).

    Maps onto the GKE TPU scheduling contract: nodeSelector
    ``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology`` and
    the ``google.com/tpu`` chip resource on probe containers.
    """

    accelerator: str = ""  # e.g. "tpu-v5-lite-podslice"
    topology: str = ""  # e.g. "2x4"
    chips: int = 0  # google.com/tpu resource per probe pod


class Workflow(_Base):
    """Describes the probe workflow (reference: healthcheck_types.go:109-114)."""

    generate_name: str = Field(default="", alias="generateName")
    resource: Optional[ResourceObject] = None
    timeout: int = Field(default=0, alias="workflowtimeout")
    rbac_rules: List[PolicyRule] = Field(default_factory=list, alias="rbacRules")
    tpu: Optional[TPUPlacement] = None


class RemedyWorkflow(Workflow):
    """Describes the self-healing workflow (reference: healthcheck_types.go:97-106).

    Same schema as Workflow, plus ``byBucket``: an optional map from
    attribution bucket (obs/attribution.py taxonomy: ``ici``, ``hbm``,
    ``compile``, ``scheduling``, ``control_plane``, ``unknown``) to a
    bucket-specific remedy workflow. When the failing run's attribution
    names a mapped bucket, that workflow runs INSTEAD of the plain
    remedy; otherwise the plain remedy is the fallback. Values are
    plain :class:`Workflow` (not ``RemedyWorkflow``) — nesting does not
    recurse, by construction and by CRD schema.
    """

    by_bucket: Dict[str, Workflow] = Field(
        default_factory=dict, alias="byBucket"
    )

    def is_empty(self) -> bool:
        """True when no remedy is configured (reference: healthcheck_types.go:104-106).

        A remedy carrying only ``byBucket`` entries is NOT empty: the
        targeted workflows are real remedies even without a fallback.
        """
        return self == RemedyWorkflow()

    def select_for_bucket(self, bucket: str) -> Optional[Workflow]:
        """The workflow to run for a failure attributed to ``bucket`` —
        the bucket-targeted entry when one exists, else this remedy
        itself (the documented fallback), else None when the remedy has
        ONLY unmatched ``byBucket`` entries and no fallback content.
        Callers detect targeting via ``selected is not remedy``."""
        selected = self.by_bucket.get(bucket or "")
        if selected is not None:
            return selected
        if self.model_copy(update={"by_bucket": {}}) == RemedyWorkflow():
            return None
        return self


class SLOSpec(_Base):
    """Per-check service-level objective (extension; no counterpart in
    the reference CRD — PAPERS.md: ML-productivity-goodput-style
    rolling-window availability).

    Declaring the block opts the check into error-budget accounting:
    the controller evaluates availability over the rolling window and
    exports ``healthcheck_slo_availability_ratio`` /
    ``healthcheck_error_budget_remaining`` for it, and ``/statusz``
    reports the budget state. Omitting the block (the default) changes
    nothing.
    """

    # target availability ratio over the window, exclusive bounds: 1.0
    # would allow a zero failure budget (division by zero in burn-rate)
    objective: float = Field(gt=0.0, lt=1.0)
    window_seconds: int = Field(default=3600, gt=0, alias="windowSeconds")


class AnalysisSpec(_Base):
    """Per-check baseline & anomaly detection (extension; no
    counterpart in the reference CRD — docs/analysis.md).

    Declaring the block opts the check into degradation verdicts
    orthogonal to pass/fail: the controller maintains per-metric
    rolling baselines over the run's custom-metric samples, detects
    robust-z / rated-fraction / trend anomalies with hysteresis, and
    exports ``healthcheck_anomaly_state`` plus baseline/z-score gauges
    for it. Omitting the block (the default) changes nothing.
    """

    # checks sharing a cohort label are compared against each other for
    # straggler ranking (e.g. all slices of one v5e pool); "" = none
    cohort: str = ""
    # runs before the statistical detectors may judge (the baseline
    # needs a population; the rated-fraction detector is exempt)
    warmup_runs: int = Field(default=5, ge=1, alias="warmupRuns")
    # robust-z warning threshold; degraded fires at twice this
    z_threshold: float = Field(default=3.0, gt=0.0, alias="zThreshold")
    # metric names (contract spelling, e.g. "mxu-matmul-tflops") to
    # analyze; empty = every numeric metric the probe emits
    metrics: List[str] = Field(default_factory=list)
    # a run that SUCCEEDS but is analysis-degraded triggers the remedy
    # workflow as if it had failed (per-check and fleet gates still apply)
    trigger_on_degraded: bool = Field(default=False, alias="triggerOnDegraded")


class RequiresSpec(_Base):
    """Capability requirement for multi-cluster routing (extension; no
    counterpart in the reference CRD — docs/operations.md "Federating
    clusters").

    Declaring the block tells the federation's capability router WHERE
    the check may run: the cluster owning ``slice``, or any healthy
    cluster matching the generation / mesh-shape / DCN-tier floors
    (tightest fit wins). No healthy cluster qualifying is a structured
    ``no_capable_cluster`` refusal, never a silent local run. Omitting
    the block (the default) routes by a stable hash over the healthy
    set — and changes nothing on an unfederated controller.
    """

    # rated-table generation the check needs (e.g. "v5p"); "" = any
    generation: str = ""
    # mesh shape the probe wants, e.g. "4x4" — its chip footprint
    # becomes the cluster-size floor
    topology: str = ""
    min_chips: int = Field(default=0, ge=0, alias="minChips")
    # per-host DCN tier floor (GB/s, one direction) for cross-slice
    # probes that need the fat NICs
    min_dcn_gbps: float = Field(default=0.0, ge=0.0, alias="minDcnGbps")
    # pin to the cluster owning this named slice (falls through to the
    # capability match while that cluster is unhealthy — the reroute)
    slice_name: str = Field(default="", alias="slice")


class ScheduleSpec(_Base):
    """Cron schedule (reference: healthcheck_types.go:148-151).

    Accepts robfig/cron standard expressions: 5-field cron,
    @hourly/@daily/@weekly/@monthly/@yearly descriptors, and
    "@every <duration>".
    """

    cron: str = ""


class HealthCheckSpec(_Base):
    """Desired state (reference: healthcheck_types.go:32-44).

    Either repeat_after_sec or schedule.cron must be set for the check
    to run; neither set ⇒ the check is paused ("Stopped").
    """

    repeat_after_sec: int = Field(default=0, alias="repeatAfterSec")
    description: str = ""
    workflow: Workflow = Field(default_factory=Workflow)
    level: str = ""  # "namespace" | "cluster"
    schedule: ScheduleSpec = Field(default_factory=ScheduleSpec)
    remedy_workflow: RemedyWorkflow = Field(
        default_factory=RemedyWorkflow, alias="remedyworkflow"
    )
    backoff_factor: str = Field(default="", alias="backoffFactor")
    backoff_max: int = Field(default=0, alias="backoffMax")
    backoff_min: int = Field(default=0, alias="backoffMin")
    remedy_runs_limit: int = Field(default=0, alias="remedyRunsLimit")
    remedy_reset_interval: int = Field(default=0, alias="remedyResetInterval")
    # optional SLO block — absent ⇒ no error-budget accounting
    slo: Optional[SLOSpec] = None
    # optional baseline/anomaly block — absent ⇒ no degradation verdicts
    analysis: Optional[AnalysisSpec] = None
    # optional capability requirement — absent ⇒ default routing on a
    # federated controller, ignored on a single-cluster one
    requires: Optional[RequiresSpec] = None


class HealthCheckStatus(_Base):
    """Observed state — the durable checkpoint of the framework
    (reference: healthcheck_types.go:47-66; checkpoint/resume semantics
    per SURVEY.md §5.4: all durable state lives here, in-memory timers
    are rebuilt idempotently from finished_at on boot)."""

    error_message: str = Field(default="", alias="errorMessage")
    remedy_error_message: str = Field(default="", alias="remedyErrorMessage")
    started_at: Optional[datetime.datetime] = Field(default=None, alias="startedAt")
    finished_at: Optional[datetime.datetime] = Field(default=None, alias="finishedAt")
    last_failed_at: Optional[datetime.datetime] = Field(default=None, alias="lastFailedAt")
    # NB: the reference serializes RemedyStartedAt under json tag
    # "remedyTriggeredAt" (healthcheck_types.go:53) — kept for parity.
    remedy_started_at: Optional[datetime.datetime] = Field(
        default=None, alias="remedyTriggeredAt"
    )
    remedy_finished_at: Optional[datetime.datetime] = Field(
        default=None, alias="remedyFinishedAt"
    )
    remedy_last_failed_at: Optional[datetime.datetime] = Field(
        default=None, alias="remedyLastFailedAt"
    )
    last_failed_workflow: str = Field(default="", alias="lastFailedWorkflow")
    last_successful_workflow: str = Field(default="", alias="lastSuccessfulWorkflow")
    success_count: int = Field(default=0, alias="successCount")
    failed_count: int = Field(default=0, alias="failedCount")
    remedy_success_count: int = Field(default=0, alias="remedySuccessCount")
    remedy_failed_count: int = Field(default=0, alias="remedyFailedCount")
    remedy_total_runs: int = Field(default=0, alias="remedyTotalRuns")
    total_healthcheck_runs: int = Field(default=0, alias="totalHealthCheckRuns")
    status: str = ""
    remedy_status: str = Field(default="", alias="remedyStatus")
    # resilience state machine (extension; resilience/health.py):
    # "" (healthy), "Flapping", or "Quarantined". Quarantined is the
    # explicit user-clearable mark — clear the field (set it to "") to
    # resume a quarantined check's schedule.
    state: str = ""
    # baseline & anomaly state (extension; analysis/engine.py): the
    # compact serialized per-metric baselines + hysteresis levels, so
    # learned baselines survive controller restarts through the same
    # merge-patch status write as everything else. Free-form by design
    # (the engine owns the schema and versions it with a "v" key) —
    # the CRD marks it x-kubernetes-preserve-unknown-fields so the
    # apiserver does not prune the metric sub-keys.
    analysis: Optional[dict] = Field(
        default=None,
        json_schema_extra={"x-kubernetes-preserve-unknown-fields": True},
    )

    def reset_remedy(self, reason: str) -> None:
        """Zero all remedy bookkeeping (reference: healthcheck_controller.go:649-660,695-703)."""
        self.remedy_total_runs = 0
        self.remedy_finished_at = None
        self.remedy_started_at = None
        self.remedy_failed_count = 0
        self.remedy_success_count = 0
        self.remedy_last_failed_at = None
        self.remedy_status = reason


class OwnerReference(_Base):
    """Owner reference enabling GC of workflows on HealthCheck delete
    (reference: healthcheck_controller.go:512-522)."""

    api_version: str = Field(default="", alias="apiVersion")
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None


class ObjectMeta(_Base):
    """Subset of k8s ObjectMeta used by the framework."""

    name: str = ""
    generate_name: str = Field(default="", alias="generateName")
    namespace: str = ""
    uid: str = ""
    resource_version: str = Field(default="", alias="resourceVersion")
    creation_timestamp: Optional[datetime.datetime] = Field(
        default=None, alias="creationTimestamp"
    )
    deletion_timestamp: Optional[datetime.datetime] = Field(
        default=None, alias="deletionTimestamp"
    )
    labels: dict = Field(default_factory=dict)
    annotations: dict = Field(default_factory=dict)
    owner_references: List[OwnerReference] = Field(
        default_factory=list, alias="ownerReferences"
    )


class HealthCheck(_Base):
    """The HealthCheck resource (reference: healthcheck_types.go:79-85).

    Printer-column equivalents (reference: healthcheck_types.go:71-76)
    are exposed via :meth:`printer_row`; short names ``hc``/``hcs``
    are honored by the CLI.
    """

    api_version: str = Field(default=API_VERSION, alias="apiVersion")
    kind: str = KIND
    metadata: ObjectMeta = Field(default_factory=ObjectMeta)
    spec: HealthCheckSpec = Field(default_factory=HealthCheckSpec)
    status: HealthCheckStatus = Field(default_factory=HealthCheckStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def key(self) -> str:
        """namespace/name key used by the work queue and timer wheel."""
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, data: dict) -> "HealthCheck":
        return cls.model_validate(data)

    @classmethod
    def from_yaml(cls, text: str) -> "HealthCheck":
        import yaml

        return cls.from_dict(yaml.safe_load(text))

    def to_dict(self) -> dict:
        # apiVersion/kind equal their defaults, so omitempty-style dumping
        # would drop them — but a manifest without them is not applyable.
        # They lead the dict, kubectl-style.
        d = {"apiVersion": self.api_version, "kind": self.kind}
        d.update(self.to_json_dict())
        return d

    def deepcopy(self) -> "HealthCheck":
        """Equivalent of the generated DeepCopy (reference: zz_generated.deepcopy.go)."""
        return self.model_copy(deep=True)

    def printer_row(self) -> dict:
        """Columns of `kubectl get hc` (reference: healthcheck_types.go:71-76)."""
        age: Any = ""
        if self.metadata.creation_timestamp is not None:
            created = self.metadata.creation_timestamp
            if created.tzinfo is None:
                created = created.replace(tzinfo=datetime.timezone.utc)
            age = _utcnow() - created
        return {
            "NAME": self.metadata.name,
            "LATEST STATUS": self.status.status,
            "SUCCESS CNT": self.status.success_count,
            "FAIL CNT": self.status.failed_count,
            "REMEDY SUCCESS CNT": self.status.remedy_success_count,
            "REMEDY FAIL CNT": self.status.remedy_failed_count,
            "AGE": age,
        }


class HealthCheckList(_Base):
    """List of HealthChecks (reference: healthcheck_types.go:90-94)."""

    api_version: str = Field(default=API_VERSION, alias="apiVersion")
    kind: str = "HealthCheckList"
    items: List[HealthCheck] = Field(default_factory=list)
