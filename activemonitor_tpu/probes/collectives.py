"""Collectives-sweep probe — the full XLA collective set over ICI.

The ici-allreduce probe answers the north-star question; this probe
characterizes the whole communication surface the parallelism code
relies on: all-reduce (dp gradient sync), all-gather (tp/weight
gather), reduce-scatter (ZeRO/psum_scatter), all-to-all (ep dispatch,
ops/moe.py) and single-hop ppermute (ring attention, ops/ring_attention
.py; pipeline, ops/pipeline.py). A degradation only one pattern hits —
e.g. a routing fault that halves the bisection but leaves neighbor
links intact — shows up here before it shows up as slow training.

Exports, per collective C in {allreduce, allgather, reducescatter,
alltoall, ringhop, ringhop-bidir} (prefix ``collective-``, distinct
from the north-star probe's ``ici-`` gauges so a merged battery
contract never carries duplicate names):

- ``collective-<C>-busbw-gbps`` — NCCL busbw convention
- ``collective-<C>-fraction-of-rated`` — busbw / rated ceiling (TPU)

Rated ceilings assume the same bidirectional-ring model as probes/ici:
2 x unidir link bw for the ring collectives AND for the bidirectional
hop (both directions of each link active at once — the ring-attention
variant="bidir" wire pattern), 1 x for a single unidirectional hop —
except all-to-all, which is bisection-bound on a ring: each half
exchanges n*S/4 bytes per direction across the cut's 2 links, capping
busbw at 8*B*(n-1)/n^2.

Verdict: every collective's fraction must clear ``threshold`` (rated
hardware, >1 device); otherwise informational-pass, like the other
bandwidth probes. No reference counterpart (the reference has no
communication backend at all, SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from activemonitor_tpu.parallel.collectives import (
    CollectiveResult,
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    ppermute_bidir_bandwidth,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh, make_2d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for

ALL_CASES = (
    "allreduce", "allgather", "reducescatter", "alltoall", "ringhop",
    "ringhop-bidir",
)

_BENCH: Dict[str, Callable] = {
    "allreduce": all_reduce_bandwidth,
    "allgather": all_gather_bandwidth,
    "reducescatter": reduce_scatter_bandwidth,
    "alltoall": all_to_all_bandwidth,
    "ringhop": ppermute_ring_bandwidth,
    "ringhop-bidir": ppermute_bidir_bandwidth,
}


def _rated_busbw(name: str, unidir_gbps: float, n: int) -> float:
    """Achievable-busbw ceiling on a bidirectional ring of n devices
    with per-direction link bandwidth ``unidir_gbps`` (see module doc)."""
    if name == "ringhop":
        return unidir_gbps
    if name == "ringhop-bidir":
        # both link directions active per hop — full-duplex ceiling,
        # the same 2x-unidir model as the ici probe's ring comparator
        return 2 * unidir_gbps
    if name == "alltoall":
        return 8 * unidir_gbps * (n - 1) / n**2
    return 2 * unidir_gbps


def _emit(
    entries: List[Tuple[str, str, int, CollectiveResult]],
    threshold: float,
    context: str,
    details: Dict,
) -> ProbeResult:
    """Shared emission scaffolding for the flat and per-axis sweeps.

    ``entries``: (label, base_case, ring_n, result) — the label is the
    metric suffix ("allreduce" or "allreduce-data"), the base case picks
    the rated comparator, ring_n its ring size. ``context`` names the
    measured surface in the summary."""
    devices = jax.devices()
    rated = rated_for(devices[0].device_kind)
    on_tpu = devices[0].platform == "tpu"
    metrics: List[ProbeMetric] = []
    fractions: Dict[str, float] = {}
    for label, base_case, ring_n, result in entries:
        key = label.replace("-", "_")
        metrics.append(
            ProbeMetric(
                f"collective-{label}-busbw-gbps",
                result.busbw_gbps,
                help=f"Measured {result.name} bus bandwidth (NCCL convention), GB/s",
            )
        )
        details[f"{key}_busbw_gbps"] = round(result.busbw_gbps, 2)
        if rated is not None and on_tpu:
            rated_busbw = _rated_busbw(base_case, rated.ici_unidir_gbps, ring_n)
            fraction = result.busbw_gbps / rated_busbw
            fractions[label] = fraction
            metrics.append(
                ProbeMetric(
                    f"collective-{label}-fraction-of-rated",
                    fraction,
                    help=f"{result.name} busbw / achievable ring ceiling",
                )
            )
            details[f"{key}_fraction_of_rated"] = round(fraction, 3)

    if fractions:
        worst = min(fractions, key=fractions.get)
        ok = fractions[worst] >= threshold
        summary = (
            f"{context}: worst {worst} at {fractions[worst]:.0%} of "
            f"rated {rated.generation}"
            + ("" if ok else f" (< {threshold:.0%} threshold)")
        )
    else:
        ok = True
        best = max(entries, key=lambda e: e[3].busbw_gbps)
        summary = (
            f"{context}: best {best[0]} {best[3].busbw_gbps:.1f} GB/s "
            "(no rated comparison)"
        )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)


def run_per_axis(
    size_mb: float = 64.0,
    iters: int = 5,
    threshold: float = 0.8,
) -> ProbeResult:
    """Per-axis variant over the 2D mesh: all-reduce and single-hop
    ppermute restricted to EACH mesh axis. The mesh is built with
    physical-topology alignment (parallel/mesh.make_2d_mesh uses
    mesh_utils.create_device_mesh on TPU), so on a real slice the two
    axes ride different torus dimensions and a degradation confined to
    one link direction shows up as one axis's fraction dropping while
    the other stays healthy — `collectives` alone can only say "slow",
    this says "slow WHERE"."""
    devices = jax.devices()
    n = len(devices)
    if n < 4:
        return ProbeResult(
            ok=True,
            summary=f"per-axis sweep skipped: {n} device(s), no 2D mesh",
            metrics=[],
            details={"devices": n, "skipped": True},
        )
    mesh = make_2d_mesh()
    entries = [
        (f"{name}-{axis}", name, mesh.shape[axis],
         bench(mesh, size_mb=size_mb, iters=iters, axis=axis))
        for axis in mesh.axis_names
        if mesh.shape[axis] >= 2  # nothing to move along a singleton axis
        for name, bench in (("allreduce", all_reduce_bandwidth),
                            ("ringhop", ppermute_ring_bandwidth))
    ]
    details = {
        "devices": n,
        "device_kind": devices[0].device_kind,
        "mesh": dict(mesh.shape),
    }
    return _emit(
        entries, threshold, f"per-axis sweep over mesh {dict(mesh.shape)}", details
    )


def run(
    size_mb: float = 64.0,
    iters: int = 5,
    threshold: float = 0.8,
    cases: Optional[Sequence[str]] = None,
) -> ProbeResult:
    cases = tuple(cases) if cases else ALL_CASES
    unknown = [c for c in cases if c not in _BENCH]
    if unknown:
        raise ValueError(f"unknown collectives {unknown}; pick from {ALL_CASES}")
    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return ProbeResult(
            ok=True,
            summary=f"collectives sweep skipped: {n} device(s), nothing to move",
            metrics=[],
            details={"devices": n, "skipped": True},
        )

    mesh = make_1d_mesh()
    entries = [
        (name, name, n, _BENCH[name](mesh, size_mb=size_mb, iters=iters))
        for name in cases
    ]
    details = {"devices": n, "device_kind": devices[0].device_kind}
    return _emit(
        entries, threshold, f"{len(entries)} collectives over {n} device(s)", details
    )
