#!/usr/bin/env python
"""In-repo AST linter — the `make lint` / CI gate.

The reference gates CI on golangci-lint
(/root/reference/.github/workflows/golangci-lint.yml). This
environment has no Python linter installed and installing one is not
an option, so the gate is implemented here: a small pyflakes-style
checker over the AST. Checks (lenient by construction — a false
positive that makes `make lint` cry wolf is worse than a miss):

- undefined-name: a Name load that no enclosing scope binds and
  builtins don't provide (pyflakes F821, the check that catches real
  bugs: typos, stale refactors, missing imports).
- unused-import: imported at module scope, never referenced anywhere
  in the file (F401). `__init__.py` re-exports are exempt.
- unused-local: a simple `x = ...` local never read afterwards (F841);
  only plain single-name targets, `_`-prefixed exempt.
- bare-except: `except:` swallowing KeyboardInterrupt/SystemExit (E722).
- mutable-default: list/dict/set literals as parameter defaults (B006).
- f-string-no-placeholder: f"..." with nothing interpolated (F541).
- duplicate-dict-key: literal dict with a repeated constant key (F601-ish).
- unawaited-coroutine: an expression statement calling a name that this
  file only ever defines as `async def` — the coroutine is created and
  dropped, the body never runs (asyncio's classic silent bug; RUF006 /
  ASYNC102 territory).
- shadowed-builtin: a module/function-level binding (assignment, def,
  or parameter) that reuses a builtin name like `list` or `id`
  (flake8-builtins A001-A002). Class bodies are exempt — field names
  mirroring builtins (`type:`, `id:`) are idiomatic in API models.
- redefined-test: the same scope defines `def test_x` twice — pytest
  collects only the last one, silently dropping the first (F811 for
  the case that actually loses coverage).
- unreachable-code: statements after a `return`/`raise`/`break`/
  `continue` in the same block never execute (pylint W0101) — usually
  a refactor left debris or an early-return was added above real work.
- unused-parameter: a parameter of an undecorated plain function that
  the body never mentions (ARG001), restricted hard against the
  false-positive swamp: methods (override signatures), decorated
  functions (callback contracts), `_`-prefixed names, `*args`/
  `**kwargs`, and stub bodies are all exempt.
- swallowed-exception: `except Exception:`/`except BaseException:`
  whose whole body is `pass`/`...` — the broad catch that silently
  eats errors (BLE001's harmful core). Handlers that log, re-raise,
  return, or otherwise DO something are fine.
- shard-map-outside-partition: a direct `shard_map` import (from
  `jax`, `jax.experimental.shard_map`, or the `utils/compat` vintage
  adapter) anywhere except `parallel/partition.py` and
  `utils/compat.py` — the one-sharding-surface invariant: every
  manual-collective region routes through partition.py's validated
  entry point, so the compat adapter keeps exactly one call site and a
  JAX API move is absorbed in one file pair. Import it from
  `activemonitor_tpu.parallel.partition` instead.
- wallclock-in-<unit>: `time.time()` / `time.monotonic()` calls in
  files under a `resilience/`, `analysis/`, `frontdoor/`, or
  `federation/` directory (the multi-cluster control plane's liveness
  judgment, routing, and global-door ledgers all run on the injectable
  Clock so the federation acceptance tests script entirely on a
  FakeClock), or in the clock-disciplined modules (`sharding.py`, `attribution.py`,
  `flightrec.py`, `roofline.py`, `arrivals.py`, `journal.py`,
  `replay.py`, `criticalpath.py`) — those units' whole
  contract is the injectable Clock (breaker open windows, token-bucket
  refill, baseline timestamps, shard lease expiry/fencing windows,
  attribution windows and flight-bundle timestamps, front-door quota
  refill / freshness-window / QPS math, and the adaptive controller's
  burn-streak hysteresis and episode `since` stamps — resilience/adapt.py
  rides the `resilience/` path key, so the closed-loop chaos tests can
  script engage→release purely on a FakeClock — must all be scriptable
  by fake-clock tests; roofline classification is pure math over seconds
  passed IN as arguments, and the seeded arrival schedules live on the
  caller's timeline); a bare wall-clock read there silently breaks
  determinism.
  The finding code carries the unit (`wallclock-in-resilience`,
  `wallclock-in-analysis`, `wallclock-in-sharding`,
  `wallclock-in-attribution`, `wallclock-in-flightrec`,
  `wallclock-in-roofline`, `wallclock-in-matrix` — the scenario
  matrix's verdict machinery runs on the Clock and its executor timer
  is injectable, wherever a matrix.py lands in the tree — and the
  serving runtime's `wallclock-in-serving` / `wallclock-in-kv_cache`:
  the admission scheduler takes every timestamp as an argument and the
  serving probe's soak runs on an injectable timer or the scripted
  StepCosts virtual clock, so the open-loop acceptance tests replay
  deterministically; the paged-cache manager is pure allocation
  arithmetic with no time in it at all; `wallclock-in-journal` /
  `wallclock-in-replay`: the durable telemetry journal stamps events
  and computes lag on the injected Clock, and trace replay lives on
  the recorded timeline driven by a FakeClock;
  `wallclock-in-criticalpath`: the waterfall decomposition is pure
  math over span monotonics and PhaseTimings passed IN — a wall-clock
  read there would desync the stage sums from the trace's own
  timeline).

Usage: python hack/lint.py [paths...]   (default: the package + tests
+ the root entry points). Exit 1 on any finding.
"""

from __future__ import annotations

import ast
import builtins
import sys
from pathlib import Path

DEFAULT_TARGETS = [
    "activemonitor_tpu",
    "tests",
    "hack",
    "bench.py",
    "__graft_entry__.py",
]

BUILTINS = set(dir(builtins)) | {
    "__file__",
    "__name__",
    "__doc__",
    "__package__",
    "__spec__",
    "__loader__",
    "__builtins__",
    "__debug__",
    "__annotations__",
    "__dict__",
    "__class__",
}


# names the shadowed-builtin check defends. Deliberately not all of
# dir(builtins): lowercase builtins people actually call, minus ones
# whose shadowing is idiomatic in this tree's domain (`input` for probe
# payloads, `format` for CLI flags, `compile` for XLA wrappers would
# all cry wolf — leniency rule from the module docstring).
_SHADOW_BUILTINS = {
    name
    for name in dir(builtins)
    if name.islower() and not name.startswith("_")
} - {"input", "format", "compile", "copyright", "credits", "license", "help"}


class Scope:
    __slots__ = (
        "node", "bound", "loads", "global_names", "parent", "is_class",
        "def_names", "params",
    )

    def __init__(self, node, parent=None, is_class=False):
        self.node = node
        self.parent = parent
        self.is_class = is_class
        self.bound: set[str] = set()
        self.loads: list[tuple[str, int, int]] = []
        self.global_names: set[str] = set()
        self.def_names: set[str] = set()  # function defs seen in this scope
        # (name, lineno) of parameters eligible for the
        # unused-parameter check (empty when the function is exempt)
        self.params: list[tuple[str, int]] = []


class Checker(ast.NodeVisitor):
    """One pass collecting bindings + loads per scope; resolution is
    deferred to the end so forward references (functions referring to
    later module-level names) never false-positive — the same two-phase
    shape pyflakes uses."""

    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.findings: list[tuple[int, str, str]] = []
        self.module_scope = Scope(tree)
        self.scopes = [self.module_scope]
        self.all_scopes = [self.module_scope]
        # import name -> (lineno, was it ever referenced anywhere)
        self.imports: dict[str, int] = {}
        self.referenced: set[str] = set()
        self.has_star_import = False
        self.is_init = path.endswith("__init__.py")
        self.source = source
        # the injectable-clock packages: bare wall-clock reads are banned
        parts = set(Path(path).parts)
        self.wallclock_pkg = next(
            (
                pkg
                for pkg in ("resilience", "analysis", "frontdoor", "federation")
                if pkg in parts
            ),
            None,
        )
        if self.wallclock_pkg is None and Path(path).name in (
            "sharding.py",  # lease expiry, fencing windows, shed cooldowns
            "attribution.py",  # goodput windows judged on result timestamps
            "flightrec.py",  # bundle timestamps ride scripted transitions
            "roofline.py",  # pure math over seconds passed in as args
            "matrix.py",  # verdicts on the Clock; executor timer injectable
            "serving.py",  # scheduler takes timestamps as args; probe
            # soak runs on an injectable timer / scripted StepCosts
            "kv_cache.py",  # pure allocation arithmetic — no time at all
            "arrivals.py",  # seeded schedules on the caller's timeline
            "pools.py",  # pool policy + priced migration: timestamps
            # are args, channel seconds are alpha/B MODEL outputs
            "journal.py",  # event timestamps + lag on the injected Clock
            "replay.py",  # recorded timelines + FakeClock drive harness
            "criticalpath.py",  # pure waterfall math over span monotonics
        ):
            # single-file modules carrying the same injectable-Clock
            # contract as the resilience/analysis packages
            self.wallclock_pkg = Path(path).stem
        self.ban_wallclock = self.wallclock_pkg is not None
        # the one-sharding-surface invariant: only these two files may
        # import shard_map directly (partition.py is the single call
        # site of the compat adapter; compat.py is the adapter itself)
        self.allow_shard_map = Path(path).name in ("partition.py", "compat.py")
        # names defined `async def` / plain `def` anywhere in the file
        # (functions AND methods) — the unawaited-coroutine check only
        # fires on names that are EXCLUSIVELY async, so a sync function
        # sharing a name anywhere silences it (lenient by construction)
        self.async_defs: set[str] = set()
        self.sync_defs: set[str] = set()
        # bare/attribute calls used as whole statements: (name, lineno)
        self.stmt_calls: list[tuple[str, int]] = []

    # -- scope plumbing -------------------------------------------------
    @property
    def scope(self) -> Scope:
        return self.scopes[-1]

    def bind(self, name: str) -> None:
        if name in self.scope.global_names:
            self.module_scope.bound.add(name)
        else:
            self.scope.bound.add(name)

    def push(self, node, is_class=False) -> None:
        scope = Scope(node, parent=self.scope, is_class=is_class)
        self.scopes.append(scope)
        self.all_scopes.append(scope)

    def pop(self) -> None:
        self.scopes.pop()

    # -- names ----------------------------------------------------------
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.scope.loads.append((node.id, node.lineno, node.col_offset))
            self.referenced.add(node.id)
        else:  # Store / Del
            if isinstance(node.ctx, ast.Store):
                self._check_shadow(node.id, node.lineno, "assignment to")
            self.bind(node.id)

    def visit_Expr(self, node: ast.Expr) -> None:
        # a call used as a whole statement: candidate for the
        # unawaited-coroutine check (resolved in finish() once every
        # def in the file has been seen)
        if isinstance(node.value, ast.Call):
            fn = node.value.func
            if isinstance(fn, ast.Name):
                self.stmt_calls.append((fn.id, node.lineno))
            elif isinstance(fn, ast.Attribute):
                self.stmt_calls.append((fn.attr, node.lineno))
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.scope.global_names.update(node.names)
        self.module_scope.bound.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        # lenient: treat as bound here and in the parent chain
        self.scope.bound.update(node.names)

    # -- imports --------------------------------------------------------
    def _record_import(self, alias: ast.alias, node) -> None:
        name = alias.asname or alias.name.split(".")[0]
        if alias.name == "*":
            self.has_star_import = True
            return
        self.bind(name)
        if self.scope is self.module_scope and not alias.name.startswith("__"):
            self.imports.setdefault(name, node.lineno)

    def _check_shard_map_import(self, node, module: str, name: str) -> None:
        """shard-map-outside-partition: direct shard_map imports are
        banned outside the two surface files. Banned sources: the
        legacy `jax.experimental.shard_map` home, the modern top-level
        `jax` export, and the in-tree `utils/compat` adapter (absolute
        or relative — any module path ending in `compat`). Importing
        from `activemonitor_tpu.parallel.partition` is the sanctioned
        spelling and stays quiet."""
        if self.allow_shard_map:
            return
        banned_module = (
            module in ("jax", "jax.experimental", "jax.experimental.shard_map")
            # the in-tree adapter, absolute or relative (`...utils.compat`,
            # `.compat`) — NOT any third-party module merely named *compat
            or module == "compat"
            or module.endswith(".compat")
        )
        if (name == "shard_map" and banned_module) or (
            module == "" and name == "jax.experimental.shard_map"
        ):
            self.findings.append(
                (
                    node.lineno,
                    "shard-map-outside-partition",
                    "direct shard_map import — route through "
                    "activemonitor_tpu.parallel.partition (the one "
                    "sharding surface)",
                )
            )

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self._check_shard_map_import(node, "", alias.name)
            self._record_import(alias, node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            for alias in node.names:
                self.bind(alias.asname or alias.name)
            return
        for alias in node.names:
            self._check_shard_map_import(node, node.module or "", alias.name)
            self._record_import(alias, node)

    def _check_shadow(self, name: str, lineno: int, what: str) -> None:
        """flake8-builtins-style A001/A002; class bodies exempt (API
        models legitimately declare fields like `type` / `id`)."""
        if self.scope.is_class:
            return
        if name in _SHADOW_BUILTINS:
            self.findings.append(
                (lineno, "shadowed-builtin", f"{what} `{name}` shadows a builtin")
            )

    # -- definitions ----------------------------------------------------
    def _visit_function(self, node) -> None:
        if (
            node.name.startswith("test_")
            and node.name in self.scope.def_names
        ):
            self.findings.append(
                (
                    node.lineno,
                    "redefined-test",
                    f"duplicate `def {node.name}` — pytest keeps only the "
                    "last definition, the first never runs",
                )
            )
        self.scope.def_names.add(node.name)
        if isinstance(node, ast.AsyncFunctionDef):
            self.async_defs.add(node.name)
        else:
            self.sync_defs.add(node.name)
        self._check_shadow(node.name, node.lineno, "function")
        self.bind(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for default in defaults:
            self.visit(default)
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(
                    (
                        default.lineno,
                        "mutable-default",
                        f"mutable default argument in {node.name}()",
                    )
                )
        for annotation in self._annotations(node):
            self.visit(annotation)
        in_class = self.scope.is_class
        self.push(node)
        args = node.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            self.scope.bound.add(a.arg)
            self._check_shadow(a.arg, a.lineno, "parameter")
        # unused-parameter eligibility (the narrow slice where a flag
        # means a bug, not a contract): plain undecorated functions
        # outside class bodies, with a real body; positional/keyword
        # params only, `_`-prefixed exempt
        if (
            not in_class
            and not node.decorator_list
            # pytest injects fixtures by PARAMETER NAME: a test's params
            # are requests, not inputs the body must read
            and not node.name.startswith("test_")
            and not self._is_stub_body(node.body)
            # docstring(s) followed by a trailing `raise` is the
            # canonical not-implemented stub: params are the contract
            and not (
                node.body
                and isinstance(node.body[-1], ast.Raise)
                and self._is_stub_body(node.body[:-1])
            )
        ):
            self.scope.params = [
                (a.arg, a.lineno)
                for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
                if not a.arg.startswith("_") and a.arg not in ("self", "cls")
            ]
        for stmt in node.body:
            self.visit(stmt)
        self.pop()

    @staticmethod
    def _annotations(node):
        args = node.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            if a.annotation is not None:
                yield a.annotation
        if node.returns is not None:
            yield node.returns

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            self.visit(default)
        self.push(node)
        args = node.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            self.scope.bound.add(a.arg)
        self.visit(node.body)
        self.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.bind(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        for base in node.bases + [kw.value for kw in node.keywords]:
            self.visit(base)
        self.push(node, is_class=True)
        self.scope.bound.add("__qualname__")
        self.scope.bound.add("__module__")
        for stmt in node.body:
            self.visit(stmt)
        self.pop()

    def _visit_comprehension(self, node) -> None:
        # first iterable evaluates in the enclosing scope
        self.visit(node.generators[0].iter)
        self.push(node)
        for gen in node.generators:
            self.visit(gen.target)
            for cond in gen.ifs:
                self.visit(cond)
        for gen in node.generators[1:]:
            self.visit(gen.iter)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.pop()

    visit_ListComp = _visit_comprehension
    visit_SetComp = _visit_comprehension
    visit_GeneratorExp = _visit_comprehension
    visit_DictComp = _visit_comprehension

    # -- other checks ---------------------------------------------------
    @staticmethod
    def _is_stub_body(body: list) -> bool:
        """Only docstrings, `pass`, and `...` — nothing executes."""
        for stmt in body:
            if isinstance(stmt, ast.Pass):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
                continue  # docstring or bare `...`
            return False
        return True

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.findings.append(
                (node.lineno, "bare-except", "bare `except:` (catches SystemExit)")
            )
        else:
            broad = {"Exception", "BaseException"}
            caught = (
                [node.type]
                if not isinstance(node.type, ast.Tuple)
                else list(node.type.elts)
            )
            if any(
                isinstance(t, ast.Name) and t.id in broad for t in caught
            ) and self._is_stub_body(node.body):
                self.findings.append(
                    (
                        node.lineno,
                        "swallowed-exception",
                        "broad `except Exception:` whose body is only "
                        "`pass` — errors vanish silently",
                    )
                )
        if node.name:
            self.bind(node.name)
        self.generic_visit(node)

    _TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)

    def _check_unreachable(self, body: list) -> None:
        for i, stmt in enumerate(body[:-1]):
            if isinstance(stmt, self._TERMINAL):
                self.findings.append(
                    (
                        body[i + 1].lineno,
                        "unreachable-code",
                        "statement can never execute (follows "
                        f"`{type(stmt).__name__.lower()}`)",
                    )
                )
                break  # one finding per block is enough

    def visit(self, node):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and len(block) > 1:
                self._check_unreachable(block)
        return super().visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.ban_wallclock:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr in ("time", "monotonic")
            ):
                self.findings.append(
                    (
                        node.lineno,
                        f"wallclock-in-{self.wallclock_pkg}",
                        f"`time.{fn.attr}()` in {self.wallclock_pkg}/ — "
                        "use the injectable Clock so fake-clock tests "
                        "stay deterministic",
                    )
                )
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # a format spec like `:.1e` parses as a placeholder-less
        # JoinedStr — visiting it through visit_JoinedStr would flag
        # every format spec in the file
        self.visit(node.value)
        if node.format_spec is not None:
            for part in node.format_spec.values:
                if isinstance(part, ast.FormattedValue):
                    self.visit(part)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.findings.append(
                (node.lineno, "f-string-no-placeholder", "f-string without placeholders")
            )
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        seen: set = set()
        for key in node.keys:
            if isinstance(key, ast.Constant):
                try:
                    hashable = key.value
                    if hashable in seen:
                        self.findings.append(
                            (
                                key.lineno,
                                "duplicate-dict-key",
                                f"duplicate dict key {key.value!r}",
                            )
                        )
                    seen.add(hashable)
                except TypeError:
                    pass
        self.generic_visit(node)

    # -- resolution -----------------------------------------------------
    def finish(self) -> None:
        for scope in self.all_scopes:
            for name, lineno, _col in scope.loads:
                if name in BUILTINS:
                    continue
                cursor = scope
                found = False
                while cursor is not None:
                    # class scopes are invisible to nested function
                    # scopes — but being lenient costs only misses
                    if name in cursor.bound:
                        found = True
                        break
                    cursor = cursor.parent
                if not found and not self.has_star_import:
                    self.findings.append(
                        (lineno, "undefined-name", f"undefined name `{name}`")
                    )
        if not self.is_init and not self.has_star_import:
            # a module-scope import only counts as used if the name is
            # loaded somewhere OR re-exported via __all__
            exported = self._all_exports()
            for name, lineno in self.imports.items():
                if name not in self.referenced and name not in exported:
                    self.findings.append(
                        (lineno, "unused-import", f"`{name}` imported but unused")
                    )
        for scope in self.all_scopes:
            if not scope.params:
                continue
            # every name mentioned in this scope OR any scope nested
            # inside it (closures legitimately consume parameters)
            mentioned = {name for name, _l, _c in scope.loads}
            for inner in self.all_scopes:
                cursor = inner.parent
                while cursor is not None:
                    if cursor is scope:
                        mentioned |= {n for n, _l, _c in inner.loads}
                        mentioned |= inner.bound
                        break
                    cursor = cursor.parent
            for name, lineno in scope.params:
                if name not in mentioned:
                    self.findings.append(
                        (
                            lineno,
                            "unused-parameter",
                            f"parameter `{name}` is never used in the body",
                        )
                    )
        for name, lineno in self.stmt_calls:
            if name in self.async_defs and name not in self.sync_defs:
                self.findings.append(
                    (
                        lineno,
                        "unawaited-coroutine",
                        f"`{name}(...)` creates a coroutine that is never "
                        "awaited — the body never runs",
                    )
                )
        self._unused_locals()

    def _all_exports(self) -> set:
        for node in self.module_scope.node.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                return {
                    elt.value
                    for elt in node.value.elts
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
                }
        return set()

    def _unused_locals(self) -> None:
        for scope in self.all_scopes:
            if scope is self.module_scope or scope.is_class:
                continue
            if not isinstance(scope.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            loads = {name for name, _l, _c in scope.loads}
            # nested scopes may close over these locals
            for inner in self.all_scopes:
                cursor = inner
                while cursor is not None:
                    if cursor is scope and inner is not scope:
                        loads |= {name for name, _l, _c in inner.loads}
                    cursor = cursor.parent
            for stmt in ast.walk(scope.node):
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    target = stmt.targets[0]
                    if (
                        isinstance(target, ast.Name)
                        and not target.id.startswith("_")
                        and target.id not in loads
                        and target.id not in scope.global_names
                        and self._owning_function(stmt, scope.node)
                    ):
                        self.findings.append(
                            (
                                stmt.lineno,
                                "unused-local",
                                f"local `{target.id}` assigned but never used",
                            )
                        )

    def _owning_function(self, stmt, func_node) -> bool:
        """True if stmt belongs to func_node directly (not to a nested
        function OR class body, which have their own scope entries —
        a `class X:` defined inside a function binds its body
        assignments as class attributes, not function locals)."""
        for node in ast.walk(func_node):
            if node is stmt:
                continue
            if (
                isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
                )
                and node is not func_node
                and any(n is stmt for n in ast.walk(node))
            ):
                return False
        return True


def lint_file(path: Path) -> list[str]:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax-error: {exc.msg}"]
    checker = Checker(str(path), tree, source)
    checker.visit(tree)
    checker.finish()
    return [
        f"{path}:{lineno}: {code}: {message}"
        for lineno, code, message in sorted(checker.findings)
    ]


def main(argv: list[str]) -> int:
    targets = argv or DEFAULT_TARGETS
    files: list[Path] = []
    for target in targets:
        p = Path(target)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    failures = 0
    for f in files:
        for line in lint_file(f):
            print(line)
            failures += 1
    if failures:
        print(f"\n{failures} lint finding(s)", file=sys.stderr)
        return 1
    print(f"lint OK ({len(files)} files)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
