"""Concurrency stress — the race-detection tier (SURVEY.md §5.2).

The reference relies on manual lock discipline and leaves one
documented race; here scheduler state is single-owner on the event
loop, so the invariants under load are: no cross-check contamination,
no lost or duplicated runs, no concurrent reconcile of one key.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller.client import NotFoundError
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine, fail_after, succeed_after
from activemonitor_tpu.metrics import MetricsCollector

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"

N_CHECKS = 40


def make_hc(i: int):
    # odd checks fail, even succeed — cross-contamination would show up
    # as wrong counters on either side
    return HealthCheck.from_dict(
        {
            "metadata": {"name": f"stress-{i:03d}", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 3600,
                "level": "cluster",
                "workflow": {
                    "generateName": f"stress-{i:03d}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": f"sa-{i:03d}",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


@pytest.mark.asyncio
async def test_many_checks_under_concurrent_reconciles():
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    for i in range(1, N_CHECKS, 2):
        engine.on_prefix(f"stress-{i:03d}-", fail_after(1, f"fail-{i:03d}"))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(capacity=100000),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        # apply all checks concurrently + storm duplicate events
        await asyncio.gather(*(client.apply(make_hc(i)) for i in range(N_CHECKS)))
        for _ in range(3):
            for i in range(N_CHECKS):
                manager.enqueue("health", f"stress-{i:03d}")
            await asyncio.sleep(0.01)

        async def settled():
            for _ in range(400):
                await asyncio.sleep(0.025)
                done = 0
                for i in range(N_CHECKS):
                    hc = await client.get("health", f"stress-{i:03d}")
                    if hc.status.total_healthcheck_runs >= 1:
                        done += 1
                if done == N_CHECKS:
                    return True
            return False

        assert await settled(), "not all checks completed a run"
        await reconciler.wait_watches()

        for i in range(N_CHECKS):
            hc = await client.get("health", f"stress-{i:03d}")
            if i % 2:
                assert hc.status.status == "Failed", i
                assert hc.status.failed_count == 1, (i, hc.status)
                assert hc.status.error_message == f"fail-{i:03d}", i
                assert hc.status.success_count == 0, i
            else:
                assert hc.status.status == "Succeeded", i
                assert hc.status.success_count == 1, (i, hc.status)
                assert hc.status.failed_count == 0, i
            # exactly one workflow per check despite the event storm
            prefix = f"stress-{i:03d}-"
            count = sum(
                1
                for wf in engine.submitted
                if wf["metadata"]["generateName"] == prefix
            )
            assert count == 1, (i, count)
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_interleaved_apply_delete_storm():
    """Rapid create/delete cycles must end clean: no timers or watches
    left for deleted checks, no crash."""
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        for cycle in range(5):
            await asyncio.gather(*(client.apply(make_hc(i)) for i in range(10)))
            await asyncio.sleep(0.05)
            for i in range(10):
                try:
                    await client.delete("health", f"stress-{i:03d}")
                except NotFoundError:
                    pass  # already gone in a previous churn round
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)
        await reconciler.wait_watches()
        # all deleted: no pending timers may survive
        for i in range(10):
            assert not reconciler.timers.pending(f"health/stress-{i:03d}")
    finally:
        await manager.stop()


# -- fake-clock soak tier ----------------------------------------------
#
# The reference's envtest runs minutes of wall-clock with a handful of
# CRs (suite_test.go); nothing there proves the controller's resource
# discipline over HOURS of schedule churn at fleet scale. This tier
# does: 210 HealthChecks (interval / storm-aligned cron / failing
# remedy), two simulated hours on the FakeClock with delete+re-apply
# churn in the middle, then QUANTIFIED invariants — run counts per
# cadence, remedy hysteresis bounds, watch-task and timer-wheel sizes,
# and stable metrics cardinality across the churn (a leak in any of
# those grows with simulated time and fails the bound).
#
# Scale margin: the same scenario was validated one-off at 630 checks
# over 4 simulated hours (~60 s wall) with every invariant scaled and
# holding — the committed size keeps the default suite fast, not the
# controller safe.

N_SOAK = 210  # divisible by 3: interval / cron / remedy thirds
SIM_SECONDS = 2 * 3600


def make_soak_hc(i: int):
    kind = i % 3
    spec = {
        "level": "cluster",
        "workflow": {
            "generateName": f"soak-{i:03d}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": f"soak-sa-{i:03d}",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if kind == 0:
        spec["repeatAfterSec"] = 600
    elif kind == 1:
        # every cron check shares the same fire minutes: a 70-check
        # thundering herd at :00/:15/:30/:45
        spec["schedule"] = {"cron": "*/15 * * * *"}
    else:
        spec["repeatAfterSec"] = 900
        spec["remedyRunsLimit"] = 2
        spec["remedyResetInterval"] = 1800
        spec["remedyworkflow"] = {
            "generateName": f"soak-fix-{i:03d}-",
            "resource": {
                "namespace": "health",
                "serviceAccount": f"soak-fix-sa-{i:03d}",
                "source": {"inline": WF_INLINE},
            },
        }
    return HealthCheck.from_dict(
        {
            "metadata": {"name": f"soak-{i:03d}", "namespace": "health"},
            "spec": spec,
        }
    )


# -- sharded-fleet soak (ISSUE 6 acceptance, full-scale tier) ----------
#
# ≥50k synthetic checks on the stub apiserver, 3 sharded controller
# replicas on one seeded FakeClock. One replica is hard-killed
# mid-cycle; the surviving owners adopt its shard and every owed run
# fires EXACTLY once fleet-wide — the tier-1 slice of this scenario
# (24 checks) lives in tests/test_chaos.py; this is the scale proof.

N_SHARD_SOAK = 50_000
OWED_BOOT = 900  # never ran: owed the moment the fleet boots
OWED_LATER = 600  # become owed at t≈120, AFTER the kill — the handoff's runs
SOAK_INTERVAL = 7200  # current checks never re-fire inside the window


def _soak_obj(i: int, epoch_iso: str, finished_iso) -> dict:
    from activemonitor_tpu import GROUP, VERSION

    doc = {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "HealthCheck",
        "metadata": {"name": f"s50-{i:05d}", "namespace": "health"},
        "spec": {
            "repeatAfterSec": SOAK_INTERVAL,
            "level": "cluster",
            "workflow": {
                "generateName": f"s50-{i:05d}-",
                "workflowtimeout": 300,
                "resource": {
                    "namespace": "health",
                    "serviceAccount": "s50-sa",
                    "source": {"inline": WF_INLINE},
                },
            },
        },
    }
    if finished_iso is not None:
        doc["status"] = {
            "status": "Succeeded",
            "startedAt": epoch_iso,
            "finishedAt": finished_iso,
            "successCount": 1,
            "totalHealthCheckRuns": 1,
        }
    return doc


@pytest.mark.slow
@pytest.mark.asyncio
async def test_shard_soak_50k_checks_survive_owner_kill_exactly_once():
    import datetime

    from activemonitor_tpu import GROUP, VERSION
    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )
    from activemonitor_tpu.controller.sharding import ShardCoordinator
    from activemonitor_tpu.engine.argo import (
        WF_GROUP,
        WF_PLURAL,
        WF_VERSION,
        ArgoWorkflowEngine,
    )
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.obs.slo import rollup_statusz
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import advance, drive_until, stub_env

    async with stub_env() as (server, api_a):
        clock = FakeClock()
        now = clock.now()

        def iso(dt):
            return dt.isoformat()

        # seed 50k checks WITHOUT watch broadcast (bulk fixture): 900
        # owed at boot (never ran), 600 owed at t≈120 (after the kill),
        # the rest current until far outside the window
        objs = []
        for i in range(N_SHARD_SOAK):
            if i < OWED_BOOT:
                finished = None
            elif i < OWED_BOOT + OWED_LATER:
                finished = iso(
                    now - datetime.timedelta(seconds=SOAK_INTERVAL - 120)
                )
            else:
                finished = iso(now - datetime.timedelta(seconds=60))
            objs.append(_soak_obj(i, iso(now), finished))

        apis = {
            "a": api_a,
            "b": KubeApi(KubeConfig(server=server.url)),
            "c": KubeApi(KubeConfig(server=server.url)),
        }
        player_api = KubeApi(KubeConfig(server=server.url))
        managers, coords, mets = {}, {}, {}
        for idx, tag in enumerate("abc"):
            metrics = MetricsCollector()
            coord = ShardCoordinator(
                api=apis[tag],
                namespace="health",
                shards=3,
                shard_id=idx,
                identity=f"replica-{tag}",
                clock=clock,
                metrics=metrics,
                lease_seconds=15.0,
                steal_threshold=10**9,  # adoption backlogs must not shed
            )
            client = KubernetesHealthCheckClient(apis[tag], owns=coord.owns_event)
            reconciler = HealthCheckReconciler(
                client=client,
                engine=ArgoWorkflowEngine(apis[tag]),
                rbac=RBACProvisioner(InMemoryRBACBackend()),
                recorder=EventRecorder(capacity=5000),
                metrics=metrics,
                clock=clock,
            )
            managers[tag] = Manager(
                client=client,
                reconciler=reconciler,
                max_parallel=24,
                shard_coordinator=coord,
                goodput_interval=600.0,  # 50k-list rollups stay off-path
            )
            coords[tag], mets[tag] = coord, metrics

        def argo_player():
            from activemonitor_tpu.kube import ApiError, api_path

            async def play():
                done = set()
                while True:
                    for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                        name = wf["metadata"]["name"]
                        if name in done:
                            continue
                        try:
                            await player_api.merge_patch(
                                api_path(
                                    WF_GROUP, WF_VERSION, WF_PLURAL,
                                    wf["metadata"]["namespace"], name, "status",
                                ),
                                {"status": {"phase": "Succeeded"}},
                            )
                            done.add(name)
                        except ApiError:
                            continue
                    await asyncio.sleep(0.05)

            return asyncio.create_task(play())

        def run_totals():
            """(total recorded runs, workflows created) from the stub's
            store directly — the exactly-once ledger, no HTTP."""
            runs = 0
            for hc in server.objs(GROUP, VERSION, "healthchecks"):
                runs += ((hc.get("status") or {}).get("totalHealthCheckRuns") or 0)
            return runs, len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))

        player = argo_player()
        try:
            # start the fleet FIRST (empty store: boot resync is a
            # no-op), then bulk-seed and resync by hand — the stub's
            # bulk path skips per-object broadcast, so 150k synthetic
            # watch events don't dominate the soak's wall clock
            await asyncio.gather(*(m.start() for m in managers.values()))
            server.bulk_seed(GROUP, VERSION, "healthchecks", objs)
            for manager in managers.values():
                for hc in await manager.client.list():
                    manager.enqueue(hc.metadata.namespace, hc.metadata.name)

            # drain the 50k-key resync (workers run in real time; only
            # the workflow polls need fake-clock pacing)
            for _ in range(2400):
                if all(m._queue.qsize() == 0 for m in managers.values()):
                    break
                await asyncio.sleep(0.25)
            assert all(m._queue.qsize() == 0 for m in managers.values())

            seeded_runs = N_SHARD_SOAK - OWED_BOOT  # pre-seeded history

            async def boot_batch_done():
                runs, workflows = run_totals()
                return (
                    runs >= seeded_runs + OWED_BOOT
                    and workflows >= OWED_BOOT
                )

            await drive_until(clock, boot_batch_done, max_seconds=90)
            runs, workflows = run_totals()
            # exactly once: every owed-at-boot check ran, nothing else did
            assert workflows == OWED_BOOT, workflows
            assert runs == seeded_runs + OWED_BOOT, runs

            # every replica owns exactly its home shard, and the fleet
            # rollup's per-shard counts sum to the 50k total
            for idx, tag in enumerate("abc"):
                assert coords[tag].owned_shards() == [idx]
            payloads = []
            for tag in "abc":
                manager = managers[tag]
                payloads.append(
                    manager.reconciler.fleet.statusz(await manager.client.list())
                )
            rollup = rollup_statusz(payloads)
            assert rollup["fleet"]["checks"] == N_SHARD_SOAK
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
                == N_SHARD_SOAK
            )

            # ---- hard-kill replica b mid-cycle (before the t=120 owed
            # batch; its lease rots unreleased) ------------------------
            from tests.kube_harness import hard_kill_shards

            victim = managers["b"]
            for task in list(victim._tasks) + list(victim._requeue_tasks):
                task.cancel()
            hard_kill_shards(coords["b"])
            await victim.reconciler.shutdown()

            await drive_until(
                clock,
                lambda: asyncio.sleep(
                    0, 1 in coords["a"].set.owned or 1 in coords["c"].set.owned
                ),
                max_seconds=120,
            )
            adopter = "a" if 1 in coords["a"].set.owned else "c"
            # adoption resync re-queues the dead shard's keys; drain it
            for _ in range(2400):
                if managers[adopter]._queue.qsize() == 0:
                    break
                await asyncio.sleep(0.25)

            # ---- the t≈120 owed batch fires on the SURVIVORS only ----
            async def later_batch_done():
                runs, workflows = run_totals()
                return workflows >= OWED_BOOT + OWED_LATER

            await drive_until(clock, later_batch_done, max_seconds=300)
            # let in-flight status writes land
            for _ in range(40):
                runs, workflows = run_totals()
                if runs >= seeded_runs + OWED_BOOT + OWED_LATER:
                    break
                await advance(clock, 2.5)
            runs, workflows = run_totals()
            # THE exactly-once ledger: one workflow per owed fire, one
            # recorded run per workflow, zero spurious fires across
            # 50k checks and a mid-cycle owner kill
            assert workflows == OWED_BOOT + OWED_LATER, workflows
            assert runs == seeded_runs + OWED_BOOT + OWED_LATER, runs
            for i in range(OWED_BOOT + OWED_LATER, OWED_BOOT + OWED_LATER + 50):
                hc = server.obj(GROUP, VERSION, "healthchecks", "health", f"s50-{i:05d}")
                assert (hc["status"].get("totalHealthCheckRuns") or 0) == 1

            # ---- the fenced old owner's late status write ------------
            fenced_name = next(
                f"s50-{i:05d}"
                for i in range(N_SHARD_SOAK)
                if coords["b"].shard_for(f"health/s50-{i:05d}") == 1
            )
            seeder = KubernetesHealthCheckClient(apis["a"])
            stale = await seeder.get("health", fenced_name)
            stale.status.error_message = "stale split-brain write"
            await victim.reconciler._update_status(stale)
            fresh = await seeder.get("health", fenced_name)
            assert fresh.status.error_message != "stale split-brain write"
            assert (
                mets["b"].sample_value(
                    "healthcheck_shard_fenced_writes_total", {"shard": "1"}
                )
                == 1.0
            )

            # ---- rollup after handoff: counts still sum to 50k -------
            payloads = []
            for tag in ("a", "c"):
                manager = managers[tag]
                payloads.append(
                    manager.reconciler.fleet.statusz(await manager.client.list())
                )
            rollup = rollup_statusz(payloads)
            assert rollup["fleet"]["checks"] == N_SHARD_SOAK
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
                == N_SHARD_SOAK
            )
            assert set(rollup["fleet"]["sharding"]["owners"]) == {"0", "1", "2"}
        finally:
            player.cancel()
            for manager in managers.values():
                await manager.stop()
            for tag in ("b", "c"):
                await apis[tag].close()
            await player_api.close()


def _series_count(metrics: MetricsCollector) -> int:
    return sum(
        1
        for line in metrics.exposition().decode().splitlines()
        if line and not line.startswith("#")
    )


@pytest.mark.asyncio
async def test_soak_two_simulated_hours_bounded_resources():
    from activemonitor_tpu.utils.clock import FakeClock

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    for i in range(2, N_SOAK, 3):  # remedy checks' health workflows fail
        engine.on_prefix(f"soak-{i:03d}-", fail_after(1, f"soak-fail-{i:03d}"))
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(capacity=5000),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()

    async def settle(rounds: int = 40) -> None:
        for _ in range(rounds):
            await asyncio.sleep(0)

    async def run_sim(seconds: int) -> None:
        for _ in range(seconds // 60):
            await clock.advance(60)
            await settle()

    churn = [f"soak-{i:03d}" for i in range(0, 60, 3)]  # 20 interval checks
    try:
        await asyncio.gather(*(client.apply(make_soak_hc(i)) for i in range(N_SOAK)))
        await settle(80)

        await run_sim(1800)
        mid_cardinality = _series_count(metrics)
        # churn: delete a slice, let half an hour pass, re-apply the
        # SAME names (bounded label space), run out the clock
        for name in churn:
            await client.delete("health", name)
        await settle(80)
        for name in churn:
            assert not reconciler.timers.pending(f"health/{name}"), name
        await run_sim(1800)
        await asyncio.gather(
            *(client.apply(make_soak_hc(int(n.split("-")[1]))) for n in churn)
        )
        await settle(80)
        await run_sim(SIM_SECONDS - 3600)
        # drain in-flight watches: a few extra minutes of fake time
        for _ in range(10):
            if not any(t for t in reconciler._watch_tasks.values() if not t.done()):
                break
            await clock.advance(60)
            await settle()
        await reconciler.wait_watches()

        # -- run-count invariants per cadence --------------------------
        for i in range(N_SOAK):
            name = f"soak-{i:03d}"
            hc = await client.get("health", name)
            runs = hc.status.total_healthcheck_runs
            kind = i % 3
            if kind == 0 and name not in churn:
                # 600 s cadence over 7200 s: one run per period, the
                # ±1-period slack covering start/drain edges
                assert 9 <= runs <= 14, (name, runs)
            elif kind == 0:
                assert 5 <= runs <= 14, (name, runs)  # churn gap allowed
            elif kind == 1:
                # */15 cron: 8 fires in two hours (storm-aligned)
                assert 7 <= runs <= 11, (name, runs)
                assert hc.status.status == "Succeeded", name
            else:
                assert 7 <= runs <= 11, (name, runs)
                assert hc.status.failed_count == runs, (name, hc.status)
                # hysteresis: the limit counter CYCLES (reset → rerun),
                # so the durable invariant is total submissions — at
                # most 2 per 1800 s reset window, never 1:1 with the
                # 900 s failure cadence
                fixes = sum(
                    1
                    for wf in engine.submitted
                    if wf["metadata"]["generateName"] == f"soak-fix-{i:03d}-"
                )
                assert 3 <= fixes <= 8, (name, fixes)
                assert fixes < runs, (name, fixes, runs)
                assert hc.status.remedy_total_runs <= 2, name

        # -- resource-discipline invariants ----------------------------
        alive_watches = sum(
            1 for t in reconciler._watch_tasks.values() if not t.done()
        )
        assert alive_watches == 0
        assert len(reconciler._watch_tasks) <= 2 * N_SOAK
        pending_timers = sum(
            1
            for i in range(N_SOAK)
            if reconciler.timers.pending(f"health/soak-{i:03d}")
        )
        # every live check keeps exactly one next-run timer
        assert pending_timers == N_SOAK
        assert len(reconciler.timers._timers) <= 2 * N_SOAK + 10
        # cardinality: the second hour (with churn + re-apply of the
        # same names) must not have grown the series space
        end_cardinality = _series_count(metrics)
        assert end_cardinality <= mid_cardinality + 5, (
            mid_cardinality,
            end_cardinality,
        )
        # per-check series budget: 5 scrape names + the runtime
        # histogram's buckets/sum/count (~22 series per check observed)
        assert end_cardinality <= 24 * N_SOAK + 200
        assert len(reconciler.recorder._events) <= 5000  # capacity holds
    finally:
        await manager.stop()
