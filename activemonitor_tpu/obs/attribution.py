"""Lost-goodput attribution: *why* is the fleet below 1.0 right now.

The SLO layer measures goodput (obs/slo.py ``fleet_goodput``); the ML
Productivity Goodput paper (PAPERS.md, arXiv:2502.06982) argues the
payoff of fleet telemetry is attribution — splitting lost goodput
across subsystems so remediation targets the right layer — and ReFrame
(arXiv:2404.10536) shows per-phase timings from inside the benchmark
are the raw material. This module is that decomposition: every
failed/degraded/late run is classified into exactly ONE bucket of a
fixed taxonomy, and the per-bucket lost ratios are **conservative by
construction** — each not-ok run lands in exactly one bucket, so the
bucket ratios sum to ``1 - goodput_ratio`` exactly (a contract test
pins it to ±1e-9, per check and across a sharded rollup).

Taxonomy (``BUCKETS``, docs/observability.md "Goodput attribution"):

- ``ici`` — interconnect evidence: a floored/anomalous metric on the
  ICI/DCN path (``ici-*``, ``*allreduce*``, ``*busbw*``, ``ring*``…).
- ``hbm`` — memory-path evidence (``hbm-*``, ``*stream*``,
  ``*transfer*``).
- ``compile`` — the run's phase timings are compile-dominated, or a
  compile-path metric is anomalous.
- ``scheduling`` — the cycle spent its time waiting in the workqueue
  (enqueue→dequeue lag dominated the cadence), not running.
- ``control_plane`` — the controller itself was degraded (breaker
  open/probing), the cycle's submit/poll/status-write spans errored,
  or the run was fenced during a shard handoff.
- ``unknown`` — a lost run with no attributable evidence. An honest
  bucket: it shrinking over time is the measure of this module.

Classification priority (first match wins, documented in the docs):
evidence from INSIDE the payload (rated-fraction floors, anomaly
verdicts, compile-heavy timings) outranks environment evidence
(queue wait, controller degradation) — a probe that ran and measured a
sick link is attributable to the link even if the controller was also
having a bad day.

Like every obs/ module: injectable-clock discipline (timestamps come
in as arguments; ``hack/lint.py`` bans wall-clock reads here), pure
functions over :class:`~activemonitor_tpu.obs.history.CheckResult`
sequences so fake-clock tests assert exact ratios, and nothing here
ever raises into the recording path (the callers guard).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from datetime import datetime
from typing import Dict, Iterable, Optional, Sequence

BUCKETS = (
    "ici",
    "hbm",
    "compile",
    "scheduling",
    "control_plane",
    "unknown",
)

# bumped when bucket semantics change; exported on
# healthcheck_goodput_attribution_info so dashboards can gate parsers
TAXONOMY_VERSION = 1

# rated-fraction evidence floor: mirrors the analysis layer's warning
# floor (analysis/detector.py) so a run the detectors would flag is
# attributable even for checks without a spec.analysis block.
# Roofline fractions (obs/roofline.py) carry the same floor: they are
# achieved-over-CEILING, so a floored one is stronger evidence still —
# the kernel is below what it could ever do here, no flat-peak excuse.
RATED_FLOOR = 0.85
RATED_SUFFIX = "-fraction-of-rated"
ROOFLINE_SUFFIX = "-roofline-fraction"

# queue wait above max(floor, fraction × cadence) reads as a scheduling
# loss: the run was late because it sat in the workqueue, not because
# the probe was slow
SCHEDULING_WAIT_FRACTION = 0.1
SCHEDULING_WAIT_FLOOR = 1.0

# compile-phase share of the payload's own timed seconds above which a
# lost run is a compile loss (a probe that spent its budget compiling
# never got to measure)
COMPILE_DOMINANCE = 0.5

# metric-name vocabulary → subsystem. Tokens, not substrings: the name
# is split on -_. so "ici-allreduce-busbw-gbps" yields clean tokens and
# "pricing" can never match "ici". Order matters (first hit wins).
_SUBSYSTEM_TOKENS = (
    (
        "ici",
        {
            "ici", "dcn", "allreduce", "allgather", "reducescatter",
            "busbw", "ring", "ringhop", "bidir", "permute", "ppermute",
            "collective", "collectives", "hop", "migration",
        },
    ),
    ("hbm", {"hbm", "stream", "memory", "transfer", "h2d", "d2h"}),
    ("compile", {"compile", "compilation", "jit", "lowering"}),
    # the serving scheduler's own knobs (ISSUE 20): speculative-decode
    # acceptance is a policy outcome, not a wire or memory property —
    # a low serving-spec-accept-fraction-of-rated attributes to
    # scheduling, where the draft depth/gamma knobs live
    ("scheduling", {"spec", "speculative", "accept", "acceptance"}),
)

_TOKEN_SPLIT = re.compile(r"[-_.]")


def roofline_entry_for(
    roofline: Optional[Dict[str, dict]], metric: str
) -> Optional[dict]:
    """The run's roofline verdict underlying ``metric``, if the payload
    shipped one (obs/roofline.py block, longest-prefix match)."""
    from activemonitor_tpu.obs import roofline as roofline_model

    return roofline_model.entry_for_metric(roofline, metric)


def roofline_citation(entry: dict) -> str:
    """The evidence phrase a why-line carries for a roofline verdict:
    '0.41 of memory-bound ceiling (xla cost model)'."""
    from activemonitor_tpu.obs import roofline as roofline_model

    return roofline_model.verdict_line(entry)


def subsystem_for_metric(name: str) -> Optional[str]:
    """The taxonomy bucket a metric name's vocabulary points at, or
    None for metrics with no subsystem mapping (e.g. ``mxu-*`` compute
    numbers — the taxonomy deliberately has no compute bucket, so those
    stay ``unknown`` rather than mislabeled)."""
    tokens = set(_TOKEN_SPLIT.split(str(name).lower()))
    for subsystem, vocabulary in _SUBSYSTEM_TOKENS:
        if tokens & vocabulary:
            return subsystem
    return None


@dataclass(frozen=True)
class Attribution:
    """One run's attribution verdict: the bucket and a one-line human
    ``why`` (the WHY column / ``am-tpu why`` evidence line)."""

    bucket: str
    why: str


def classify_run(
    *,
    ok: bool,
    metrics: Optional[Dict[str, float]] = None,
    timings: Optional[Dict[str, float]] = None,
    roofline: Optional[Dict[str, dict]] = None,
    anomalies: Optional[Dict[str, str]] = None,
    anomaly_state: str = "ok",
    queue_wait: float = 0.0,
    interval: float = 0.0,
    degraded_controller: bool = False,
    errored_spans: Iterable[str] = (),
) -> Optional[Attribution]:
    """Classify one finished run. Returns None for an unremarkable OK
    run (nothing to attribute); otherwise exactly one bucket.

    Inputs are all captured AT RECORD TIME by the caller (FleetStatus):
    the run's own contract ``metrics``/``timings``, the analysis
    layer's per-metric verdicts, the cycle's queue wait from its
    ``dequeue`` span, and the resilience coordinator's degraded bit —
    so classification never depends on state that has moved on by the
    time an operator asks.
    """
    # 1) payload evidence: a floored rated- or roofline-fraction metric
    #    names its subsystem directly — the WORST floor wins when
    #    several are low. When the run shipped a roofline verdict for
    #    the floored metric (obs/roofline.py), the evidence line cites
    #    it: "0.41 of memory-bound ceiling" distinguishes a kernel
    #    genuinely underperforming its ceiling from one merely far from
    #    the flat peak.
    worst: Optional[tuple] = None
    for name, value in (metrics or {}).items():
        if not name.endswith((RATED_SUFFIX, ROOFLINE_SUFFIX)):
            continue
        try:
            value = float(value)
        except (TypeError, ValueError):
            continue
        if value < RATED_FLOOR and (worst is None or value < worst[0]):
            worst = (value, name)
    if worst is not None:
        value, name = worst
        bucket = subsystem_for_metric(name) or "unknown"
        why = f"{name} {value:.3g} below rated floor {RATED_FLOOR:g}"
        entry = roofline_entry_for(roofline, name)
        if entry is not None:
            why += "; " + roofline_citation(entry)
        return Attribution(bucket, why)
    # 2) confirmed anomaly verdicts (analysis/engine.py hysteresis) on
    #    a metric whose name maps to a subsystem
    for name, state in sorted((anomalies or {}).items()):
        if state not in ("warning", "degraded"):
            continue
        bucket = subsystem_for_metric(name)
        if bucket is not None:
            return Attribution(
                bucket, f"{name} anomaly state {state} vs learned baseline"
            )
    # 3) compile-dominated payload timings — explains a LOST run only:
    #    a healthy compile-heavy run is just a probe with a warm-up
    #    cost, not lost goodput
    timed = {k: float(v) for k, v in (timings or {}).items() if v is not None}
    total = sum(v for v in timed.values() if v > 0)
    if not ok and total > 0:
        compile_seconds = sum(
            v
            for k, v in timed.items()
            if v > 0 and (subsystem_for_metric(k) == "compile" or k == "init")
        )
        if compile_seconds / total >= COMPILE_DOMINANCE:
            return Attribution(
                "compile",
                f"compile phases took {compile_seconds:.3g}s of "
                f"{total:.3g}s timed ({compile_seconds / total:.0%})",
            )
    # 4) the cycle sat in the workqueue — LATE runs are remarkable even
    #    when they pass (the cadence the SLO promises was not kept)
    wait_threshold = max(
        SCHEDULING_WAIT_FLOOR, SCHEDULING_WAIT_FRACTION * max(0.0, interval)
    )
    if queue_wait > wait_threshold:
        return Attribution(
            "scheduling",
            f"queue wait {queue_wait:.3g}s exceeded {wait_threshold:.3g}s "
            "(workqueue backlog)",
        )
    # 5) the control plane was the sick party (lost runs only — a run
    #    that SUCCEEDED under a degraded controller lost nothing)
    if not ok:
        errored = [s for s in errored_spans if s]
        if degraded_controller:
            return Attribution(
                "control_plane", "controller degraded (breaker open/probing)"
            )
        if errored:
            return Attribution(
                "control_plane",
                "cycle span(s) errored: " + ", ".join(sorted(set(errored))[:3]),
            )
        return Attribution("unknown", "run failed with no attributable evidence")
    if anomaly_state in ("warning", "degraded"):
        # passing but confirmed-degraded on an unmapped metric: still a
        # remarkable run, honestly unattributed
        return Attribution(
            "unknown", f"metrics {anomaly_state} from baseline (unmapped subsystem)"
        )
    return None


# ---------------------------------------------------------------------
# aggregation (conservation lives here)
# ---------------------------------------------------------------------


def _windowed(results: Sequence, now: datetime, window_seconds: float):
    """Same window rule as obs/slo.py ``window_results`` — exclusive on
    the left — re-stated locally because slo imports this module."""
    return [
        r for r in results if (now - r.ts).total_seconds() < window_seconds
    ]


def summarize_results(windowed: Sequence) -> Optional[dict]:
    """One check's attribution block over an already-windowed result
    list (None when the window is empty). Conservation: the per-bucket
    ratios sum to ``lost_ratio`` == ``1 - availability`` exactly —
    every not-ok run lands in exactly one bucket."""
    if not windowed:
        return None
    total = len(windowed)
    counts = {bucket: 0 for bucket in BUCKETS}
    for result in windowed:
        if result.ok:
            continue
        bucket = result.bucket if result.bucket in BUCKETS else "unknown"
        counts[bucket] += 1
    lost = sum(counts.values())
    why = next((r.why for r in reversed(windowed) if r.why), "")
    top = None
    if lost:
        top = max(BUCKETS, key=lambda b: counts[b])
    return {
        "window_runs": total,
        "lost_runs": lost,
        "lost_ratio": lost / total,
        "buckets": {bucket: counts[bucket] / total for bucket in BUCKETS},
        "counts": counts,
        "top": top,
        "why": why,
    }


def fleet_attribution(
    history, configs: Dict[str, object], now: datetime, default_window: float
) -> dict:
    """The fleet's goodput + attribution in ONE walk, so the ratio and
    its decomposition are computed over the very same windowed runs
    (the conservation contract: ``sum(attribution.values()) ==
    1 - ratio`` to float precision). Mirrors ``fleet_goodput``'s
    iteration exactly: each check contributes the runs inside ITS
    declared window (else ``default_window``), run-weighted."""
    total = good = 0
    counts = {bucket: 0 for bucket in BUCKETS}
    for key in history.checks():
        config = configs.get(key)
        window = getattr(config, "window_seconds", None) or default_window
        for result in _windowed(history.results(key), now, window):
            total += 1
            if result.ok:
                good += 1
            else:
                bucket = (
                    result.bucket if result.bucket in BUCKETS else "unknown"
                )
                counts[bucket] += 1
    ratio = (good / total) if total else None
    lost = total - good
    top = None
    if lost:
        top = max(BUCKETS, key=lambda b: counts[b])
    return {
        "ratio": ratio,
        "window_runs": total,
        "lost_ratio": (lost / total) if total else 0.0,
        "lost_runs": {bucket: counts[bucket] for bucket in BUCKETS},
        "attribution": {
            bucket: (counts[bucket] / total) if total else 0.0
            for bucket in BUCKETS
        },
        "top": top,
        "version": TAXONOMY_VERSION,
    }


def merge_goodput_blocks(payload_fleets: Sequence[dict]) -> dict:
    """Roll per-replica ``fleet.goodput`` blocks into one fleet block
    (obs/slo.py ``rollup_statusz`` calls this). Run-weighted like the
    goodput rollup itself. **Version skew is first-class**: a replica
    payload with NO goodput block (an old binary mid rolling-update)
    still conserves — its entire lost share lands in ``unknown`` rather
    than vanishing, so the rolled-up buckets keep summing to
    ``1 - rolled-up goodput``."""
    total_runs = 0.0
    good_runs = 0.0
    lost_weight = {bucket: 0.0 for bucket in BUCKETS}
    for fleet in payload_fleets:
        ratio = (fleet or {}).get("goodput_ratio")
        runs = int((fleet or {}).get("window_runs") or 0)
        if ratio is None or runs <= 0:
            continue
        total_runs += runs
        good_runs += ratio * runs
        block = (fleet or {}).get("goodput")
        buckets = (
            block.get("attribution") if isinstance(block, dict) else None
        )
        if isinstance(buckets, dict):
            for bucket, value in buckets.items():
                key = bucket if bucket in BUCKETS else "unknown"
                try:
                    lost_weight[key] += float(value) * runs
                except (TypeError, ValueError):
                    continue
        else:
            # old binary: it measured goodput but cannot explain it
            lost_weight["unknown"] += (1.0 - ratio) * runs
    top = None
    if total_runs and any(lost_weight.values()):
        top = max(BUCKETS, key=lambda b: lost_weight[b])
    return {
        "ratio": (good_runs / total_runs) if total_runs else None,
        "window_runs": int(total_runs),
        "lost_ratio": (
            sum(lost_weight.values()) / total_runs if total_runs else 0.0
        ),
        "lost_runs": {
            bucket: lost_weight[bucket] for bucket in BUCKETS
        },
        "attribution": {
            bucket: (lost_weight[bucket] / total_runs) if total_runs else 0.0
            for bucket in BUCKETS
        },
        "top": top,
        "version": TAXONOMY_VERSION,
    }


# ---------------------------------------------------------------------
# bench.py round attribution (same taxonomy, artifact-side)
# ---------------------------------------------------------------------


def classify_bench_round(doc: dict) -> dict:
    """Attribute ONE bench round's lost goodput, stamped into the
    BENCH_r*.json artifact next to ``fallback_reason`` — so a degraded
    round says WHY on the JSON line (CPU fallback vs probe hang vs real
    regression), not just that it degraded. Pure over the artifact
    dict; bucket ``none`` means the round lost nothing."""
    if doc.get("fallback"):
        reason = str(doc.get("fallback_reason") or "device unreachable")
        lowered = reason.lower()
        if "hung" in lowered or "wedged" in lowered or "timeout" in lowered:
            why = f"CPU fallback: device probe hang ({reason[:160]})"
        else:
            why = f"CPU fallback: {reason[:160]}"
        # a wedged tunnel / unreachable device is infrastructure between
        # the driver and the chip — the control plane's loss
        return {"bucket": "control_plane", "why": why}
    vs_baseline = doc.get("vs_baseline")
    metric = str(doc.get("metric") or "")
    if isinstance(vs_baseline, (int, float)) and vs_baseline < 1.0:
        if doc.get("platform") == "cpu" or "cpu" in metric:
            # a CPU-mesh round below its prior CPU artifact is host
            # noise, not a subsystem regression — never label it ici
            return {
                "bucket": "unknown",
                "why": (
                    f"{metric} at {vs_baseline:.3f}x of the prior CPU-mesh "
                    "round (host variance, not the TPU bar)"
                ),
            }
        bucket = subsystem_for_metric(metric) or "unknown"
        return {
            "bucket": bucket,
            "why": (
                f"{metric} at {vs_baseline:.3f}x of the target bar "
                "(real regression)"
            ),
        }
    return {"bucket": "none", "why": "round met its bar; no goodput lost"}
