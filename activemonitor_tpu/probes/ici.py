"""ICI all-reduce bandwidth probe — the north-star check.

Measures achieved all-reduce bus bandwidth over the chip mesh and
compares against the rated ICI link bandwidth (BASELINE.md: ≥90 % of
rated on a GKE v5e-8). Exports:

- ``ici-allreduce-busbw-gbps`` — measured bus bandwidth (NCCL convention)
- ``ici-allreduce-fraction-of-rated`` — measured / rated
- ``ici-ring-hop-gbps`` — single-hop ppermute bandwidth (one direction)
- ``ici-ring-hop-bidir-gbps`` — bidirectional hop (halves permuted
  clockwise/counter-clockwise at once — the ring-attention
  ``variant="bidir"`` wire pattern)
- ``ici-ring-hop-fraction-of-rated`` / ``ici-ring-hop-bidir-fraction-of-rated``
  — each hop flavor against its link-model ceiling (1x unidir for the
  single direction, 2x unidir full-duplex for bidirectional), the same
  model behind the all-reduce comparator below
"""

from __future__ import annotations

import jax

from activemonitor_tpu.parallel.collectives import (
    all_reduce_bandwidth,
    ppermute_bidir_bandwidth,
    ppermute_ring_bandwidth,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for


def run(
    size_mb: float = 64.0,
    iters: int = 10,
    threshold: float = 0.9,
    include_ring: bool = True,
) -> ProbeResult:
    devices = jax.devices()
    n = len(devices)
    mesh = make_1d_mesh()
    result = all_reduce_bandwidth(mesh, size_mb=size_mb, iters=iters)
    rated = rated_for(devices[0].device_kind)

    metrics = [
        ProbeMetric(
            "ici-allreduce-busbw-gbps",
            result.busbw_gbps,
            help="Measured all-reduce bus bandwidth (NCCL busbw convention), GB/s",
        ),
        ProbeMetric(
            "ici-allreduce-algbw-gbps",
            result.algbw_gbps,
            help="Measured all-reduce algorithm bandwidth, GB/s",
        ),
    ]
    details = {
        "devices": n,
        "device_kind": devices[0].device_kind,
        "payload_mb": result.payload_bytes / 1e6,
        "seconds_per_op": result.seconds_per_op,
        "busbw_gbps": round(result.busbw_gbps, 2),
    }

    ring = ring_bidir = None
    if include_ring and n > 1:
        ring = ppermute_ring_bandwidth(mesh, size_mb=size_mb, iters=iters)
        metrics.append(
            ProbeMetric(
                "ici-ring-hop-gbps",
                ring.algbw_gbps,
                help="Single-hop ppermute (ring neighbor shift) bandwidth, GB/s",
            )
        )
        details["ring_hop_gbps"] = round(ring.algbw_gbps, 2)
        ring_bidir = ppermute_bidir_bandwidth(mesh, size_mb=size_mb, iters=iters)
        metrics.append(
            ProbeMetric(
                "ici-ring-hop-bidir-gbps",
                ring_bidir.algbw_gbps,
                help="Bidirectional ring hop (cw+ccw halves per round) "
                "bandwidth, GB/s",
            )
        )
        details["ring_hop_bidir_gbps"] = round(ring_bidir.algbw_gbps, 2)

    ok = True
    if rated is not None and n > 1 and devices[0].platform == "tpu":
        # rated comparator: a 1D ring all-reduce is limited by one
        # bidirectional link pair per hop ⇒ 2 × unidirectional link bw
        rated_busbw = 2 * rated.ici_unidir_gbps
        fraction = result.busbw_gbps / rated_busbw
        metrics.append(
            ProbeMetric(
                "ici-allreduce-fraction-of-rated",
                fraction,
                help="Measured busbw / rated ring bandwidth (target ≥ 0.9)",
            )
        )
        details["rated_busbw_gbps"] = rated_busbw
        details["fraction_of_rated"] = round(fraction, 3)
        if ring is not None:
            # the hop flavors against the same link model: one direction
            # of one link, and both directions of one link (full duplex)
            metrics.append(
                ProbeMetric(
                    "ici-ring-hop-fraction-of-rated",
                    ring.algbw_gbps / rated.ici_unidir_gbps,
                    help="Single-hop bandwidth / rated unidirectional link",
                )
            )
            metrics.append(
                ProbeMetric(
                    "ici-ring-hop-bidir-fraction-of-rated",
                    ring_bidir.algbw_gbps / rated_busbw,
                    help="Bidirectional-hop bandwidth / 2x rated link "
                    "(full-duplex ceiling)",
                )
            )
            details["ring_hop_fraction_of_rated"] = round(
                ring.algbw_gbps / rated.ici_unidir_gbps, 3
            )
            details["ring_hop_bidir_fraction_of_rated"] = round(
                ring_bidir.algbw_gbps / rated_busbw, 3
            )
        ok = fraction >= threshold
        summary = (
            f"all-reduce busbw {result.busbw_gbps:.1f} GB/s = "
            f"{fraction:.0%} of rated {rated_busbw:.0f} GB/s over {n}x {rated.generation}"
        )
    else:
        summary = (
            f"all-reduce busbw {result.busbw_gbps:.1f} GB/s over {n} device(s)"
            " (no rated comparison: single device or unknown hardware)"
        )
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
