"""Probe CLI — ``python -m activemonitor_tpu.probes <probe> [options]``.

This is what workflow templates invoke (container command or script) in
every engine; stdout's final line is the custom-metrics contract, the
exit code is the verdict.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m activemonitor_tpu.probes",
        description="TPU health probe payloads",
    )
    parser.add_argument(
        "--profile",
        default="",
        metavar="DIR",
        help="capture a jax.profiler trace of the probe into DIR "
        "(view with TensorBoard / xprof)",
    )
    parser.add_argument(
        "--roofline",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="capture the roofline verdict under every numeric probe "
        "(XLA compile-time cost analysis on TPU, analytic model "
        "elsewhere — docs/observability.md \"Reading a roofline\"); "
        "--no-roofline drops the capture and records a structured "
        "skip in the details",
    )
    parser.add_argument(
        "--distributed",
        action="store_true",
        help="force jax.distributed.initialize (multi-host slices; "
        "auto-detected from TPU_WORKER_HOSTNAMES otherwise)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        metavar="HOST:PORT",
        help="explicit jax.distributed coordinator (implies --distributed)",
    )
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    sub = parser.add_subparsers(dest="probe", required=True)

    p = sub.add_parser("devices", help="device inventory check")
    p.add_argument("--expect", type=int, default=None, help="required device count")
    p.add_argument(
        "--require-platform", default="", help="required platform (e.g. tpu)"
    )

    p = sub.add_parser("ici-allreduce", help="ICI all-reduce bandwidth check")
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--threshold", type=float, default=0.9)
    p.add_argument("--no-ring", action="store_true")
    p.add_argument(
        "--schedules",
        default="",
        help="comma-separated zoo schedules (rsag,recdouble,tree) to "
        "also measure, each against its own algorithmic ceiling",
    )

    p = sub.add_parser(
        "collectives",
        help="full collective sweep: all-reduce/-gather, reduce-scatter, "
        "all-to-all, ring hop, plus the explicit-schedule zoo and the "
        "message-size autotune sweep (--sweep)",
    )
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--threshold", type=float, default=0.8)
    p.add_argument(
        "--per-axis",
        action="store_true",
        help="measure each 2D-mesh axis separately (localizes which "
        "torus direction is degraded)",
    )
    p.add_argument(
        "--cases",
        default="",
        help="comma-separated case subset (builtin cases and/or zoo "
        "schedules, e.g. allreduce,allreduce-rsag); works with "
        "--per-axis too",
    )
    p.add_argument(
        "--sweep",
        action="store_true",
        help="message-size autotune sweep: race every schedule across "
        "a log-spaced payload grid, report crossovers + the decision "
        "table",
    )
    p.add_argument(
        "--sweep-sizes-mb",
        default="",
        help="comma-separated payload grid for --sweep (default "
        "0.25..256 MB log-spaced)",
    )
    p.add_argument(
        "--quick",
        action="store_true",
        help="--sweep budget mode: 2 payload sizes, reduced iters",
    )

    p = sub.add_parser("compile-smoke", help="XLA compile smoke test")
    p.add_argument("--deadline", type=float, default=120.0)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--tiny", action="store_true")

    p = sub.add_parser("training-step", help="sharded train-step probe")
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--batch-per-device", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument(
        "--attention",
        choices=("dense", "flash", "ring"),
        default="dense",
        help="attention implementation: dense (XLA), the fused flash "
        "kernel (custom-VJP Pallas; shard_map over tp heads), or "
        "sequence-parallel ring attention (needs an 'sp' mesh axis)",
    )
    p.add_argument(
        "--mfu-threshold",
        type=float,
        default=None,
        help="fail the probe below this MFU (BASELINE.md single-chip "
        "bar; the battery applies rated.TRAIN_MFU_BAR)",
    )
    p.add_argument(
        "--zero1",
        action="store_true",
        help="ZeRO-1: shard AdamW mu/nu over the data axis too",
    )
    p.add_argument(
        "--remat",
        action="store_true",
        help="rematerialize block activations in the backward",
    )
    p.add_argument(
        "--accum-steps",
        type=int,
        default=1,
        help="gradient accumulation microbatches per step",
    )
    p.add_argument(
        "--grad-sync",
        choices=("implicit", "auto", "xla", "rsag", "recdouble", "tree"),
        default="auto",
        help="gradient-sync route: implicit (XLA-inserted reduction) "
        "or an explicit schedule through the tuned collective surface "
        "(auto consults the autotune decision table; dp-only meshes "
        "only — docs/training.md 'Partition rules')",
    )
    p.add_argument(
        "--tune-sync",
        action="store_true",
        help="race every all-reduce schedule at the gradient payload "
        "first, so --grad-sync auto dispatches a measured winner",
    )

    p = sub.add_parser("hbm", help="HBM bandwidth check")
    p.add_argument("--size-mb", type=float, default=256.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--threshold", type=float, default=0.6)
    p.add_argument("--no-pallas", action="store_true")

    p = sub.add_parser("matmul", help="MXU matmul throughput check")
    p.add_argument(
        "--dim",
        type=int,
        default=None,
        help="single dimension (default: sweep 4096/8192 and report best)",
    )
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--threshold", type=float, default=0.75)
    p.add_argument(
        "--dtype",
        choices=("bf16", "int8"),
        default="bf16",
        help="MXU throughput mode (int8 is rated 2x bf16 on v5e+)",
    )

    p = sub.add_parser(
        "ring-attention", help="sequence-parallel attention correctness + throughput"
    )
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--seq-per-device", type=int, default=1024)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument(
        "--flash",
        action="store_true",
        help="run each ring step's block compute through the fused "
        "Pallas kernel instead of XLA einsums",
    )
    p.add_argument(
        "--variant",
        choices=("overlap", "serial", "bidir"),
        default="overlap",
        help="K/V rotation schedule: double-buffered overlap (default), "
        "the serial baseline, or bidirectional halves over both ICI "
        "link directions",
    )
    p.add_argument(
        "--no-overlap-metrics",
        action="store_true",
        help="skip the serial-baseline timing pass (drops the "
        "ring-overlap-efficiency and busbw gauges)",
    )

    p = sub.add_parser(
        "flash-attention", help="fused attention kernel correctness + throughput"
    )
    p.add_argument("--batch", type=int, default=4)
    p.add_argument(
        "--seq",
        type=int,
        default=None,
        help="sequence length (default: 4096, or 2048 for --sweep)",
    )
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--no-causal", action="store_true")
    p.add_argument(
        "--tolerance",
        type=float,
        default=2e-2,
        help="forward max-abs-error gate; the gradient gate is a "
        "documented 2.5x of this",
    )
    p.add_argument(
        "--min-fraction",
        type=float,
        default=None,
        help="fail the probe below this fraction of rated bf16 peak "
        "(BASELINE.md single-chip bar; the battery applies "
        "rated.FLASH_FRACTION_BAR)",
    )
    p.add_argument(
        "--sweep",
        action="store_true",
        help="measure the (block_q, block_k) -> TFLOP/s tables the "
        "kernel defaults cite (forward grid + backward shapes) "
        "instead of the correctness/throughput probe",
    )
    p.add_argument(
        "--sweep-rounds",
        type=int,
        default=2,
        help="interleaved full passes over the sweep grid (per-config "
        "best kept; guards against contention bursts)",
    )

    p = sub.add_parser("decode", help="KV-cache decode-step latency + consistency")
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=16)
    p.add_argument("--decode-tokens", type=int, default=32)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument(
        "--flash",
        action="store_true",
        help="time the loop through the fused decode kernel "
        "(flash_decode: one blockwise HBM pass over the cache)",
    )

    p = sub.add_parser(
        "serving",
        help="continuous-batching serving loop: paged KV cache + "
        "in-flight admission under open-loop Poisson traffic "
        "(tokens/s, TTFT/inter-token tails, occupancy, KV "
        "fragmentation; gates on continuous-vs-static logits "
        "agreement and exact token conservation)",
    )
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--requests", type=int, default=10)
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--block-size", type=int, default=8)
    p.add_argument(
        "--rate-rps",
        type=float,
        default=None,
        help="open-loop arrival rate (default: calibrate to ~half the "
        "measured token capacity so admission churn is exercised)",
    )
    p.add_argument("--seed", type=int, default=0)

    p = sub.add_parser(
        "serving-disagg",
        help="disaggregated serving: prefill/decode pool split with "
        "priced KV migration, content-addressed prefix caching, and "
        "speculative decoding under a mixed hot-prefix workload "
        "(per-pool TTFT/tokens-per-s, colocated-vs-split comparison; "
        "gates on token-exact pool-boundary conservation, the "
        "per-tenant prefix ledger, and greedy-identical emissions)",
    )
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--prefill-slots", type=int, default=2)
    p.add_argument("--decode-slots", type=int, default=4)
    p.add_argument("--block-size", type=int, default=4)
    p.add_argument("--rate-rps", type=float, default=60.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--no-prefix-cache",
        action="store_true",
        help="disable the content-addressed prefix cache",
    )
    p.add_argument(
        "--speculate",
        type=int,
        default=2,
        help="draft tokens per speculative round (0 disables)",
    )
    p.add_argument(
        "--cross-slice",
        action="store_true",
        help="price KV migration at the DCN tier instead of ICI",
    )

    p = sub.add_parser("memory", help="HBM usage stats + headroom allocation smoke")
    p.add_argument("--probe-gb", type=float, default=1.0)

    p = sub.add_parser(
        "straggler", help="per-device timing/numerics spread — find the sick chip"
    )
    p.add_argument("--dim", type=int, default=0, help="matmul dim (0 = auto)")
    p.add_argument("--iters", type=int, default=5)
    p.add_argument(
        "--threshold",
        type=float,
        default=1.25,
        help="flag devices slower than this multiple of the median",
    )

    p = sub.add_parser("transfer", help="host<->device bandwidth (data-feed path)")
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument(
        "--min-gbps",
        type=float,
        default=0.0,
        help="fail below this bandwidth in either direction (0 = informational)",
    )

    p = sub.add_parser(
        "checkpoint", help="sharded orbax save/restore round-trip + bandwidth"
    )
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument(
        "--directory",
        default="",
        help="checkpoint under this directory (default: throwaway temp dir)",
    )

    p = sub.add_parser(
        "dcn-allreduce", help="cross-host all-reduce bandwidth + correctness"
    )
    p.add_argument("--size-mb", type=float, default=16.0)
    p.add_argument("--iters", type=int, default=4)

    p = sub.add_parser("all", help="run the whole probe battery in one payload")
    p.add_argument("--quick", action="store_true", help="smaller/faster variants")
    p.add_argument(
        "--skip", action="append", default=[], metavar="PROBE", help="probe to skip"
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    # a "CPU" battery run must not silently land on (and wedge against)
    # a site-plugin-registered remote device — shared rule, see
    # utils/platform.py (env-var trigger only: a stale XLA_FLAGS must
    # not silently downgrade a real-chip battery to interpret mode)
    from activemonitor_tpu.utils.platform import force_cpu_if_requested

    if force_cpu_if_requested() is False:
        print(
            "warning: JAX_PLATFORMS=cpu requested but the backend is "
            "already initialized on another platform",
            file=sys.stderr,
        )
    args = build_parser().parse_args(argv)
    from activemonitor_tpu.parallel.distributed import maybe_initialize_distributed

    if (
        args.num_processes is not None or args.process_id is not None
    ) and not (args.coordinator or args.distributed):
        print(
            "error: --num-processes/--process-id require --coordinator "
            "(or --distributed)",
            file=sys.stderr,
        )
        return 2
    maybe_initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        force=args.distributed,
    )

    if args.profile:
        import jax

        try:
            with jax.profiler.trace(args.profile):
                return _dispatch(args)
        finally:
            # a probe that dies before the first device event leaves an
            # empty capture tree behind — prune it so operators (and the
            # profile-on-anomaly size cap) never chase hollow captures
            from activemonitor_tpu.obs.journal import prune_empty_dirs

            prune_empty_dirs(args.profile)
    return _dispatch(args)


def _dispatch(args) -> int:
    if args.probe == "devices":
        from activemonitor_tpu.probes import devices

        result = devices.run(
            expect_devices=args.expect, require_platform=args.require_platform
        )
    elif args.probe == "ici-allreduce":
        from activemonitor_tpu.probes import ici

        result = ici.run(
            size_mb=args.size_mb,
            iters=args.iters,
            threshold=args.threshold,
            include_ring=not args.no_ring,
            schedules=tuple(s for s in args.schedules.split(",") if s),
            roofline=args.roofline,
        )
    elif args.probe == "collectives":
        from activemonitor_tpu.probes import collectives

        cases = tuple(c for c in args.cases.split(",") if c) or None
        if args.sweep:
            if cases or args.per_axis:
                # refuse rather than silently ignore: the sweep races
                # the full schedule set on the 1D mesh by design
                raise SystemExit(
                    "--sweep races the whole schedule zoo on the 1D mesh; "
                    "it does not combine with --cases/--per-axis"
                )
            sizes = tuple(
                float(s) for s in args.sweep_sizes_mb.split(",") if s
            ) or None
            result = collectives.sweep(
                sizes_mb=sizes, iters=args.iters, quick=args.quick
            )
        elif args.per_axis:
            result = collectives.run_per_axis(
                size_mb=args.size_mb,
                iters=args.iters,
                threshold=args.threshold,
                cases=cases,
                roofline=args.roofline,
            )
        else:
            result = collectives.run(
                size_mb=args.size_mb,
                iters=args.iters,
                threshold=args.threshold,
                cases=cases,
                roofline=args.roofline,
            )
    elif args.probe == "compile-smoke":
        from activemonitor_tpu.probes import compile_smoke

        result = compile_smoke.run(
            compile_deadline_seconds=args.deadline,
            batch=args.batch,
            seq=args.seq,
            tiny=args.tiny,
        )
    elif args.probe == "training-step":
        from activemonitor_tpu.probes import training_step

        result = training_step.run(
            tiny=args.tiny,
            batch_per_device=args.batch_per_device,
            seq=args.seq,
            steps=args.steps,
            attention=args.attention,
            mfu_threshold=args.mfu_threshold,
            zero1=args.zero1,
            remat=args.remat,
            accum_steps=args.accum_steps,
            roofline=args.roofline,
            grad_sync=args.grad_sync,
            tune_sync=args.tune_sync,
        )
    elif args.probe == "hbm":
        from activemonitor_tpu.probes import hbm

        result = hbm.run(
            size_mb=args.size_mb,
            iters=args.iters,
            threshold=args.threshold,
            use_pallas=not args.no_pallas,
            roofline=args.roofline,
        )
    elif args.probe == "matmul":
        from activemonitor_tpu.probes import matmul

        result = matmul.run(
            dim=args.dim, iters=args.iters, threshold=args.threshold,
            dtype=args.dtype, roofline=args.roofline,
        )
    elif args.probe == "ring-attention":
        from activemonitor_tpu.probes import ring

        result = ring.run(
            batch=args.batch,
            seq_per_device=args.seq_per_device,
            heads=args.heads,
            head_dim=args.head_dim,
            iters=args.iters,
            use_flash=args.flash,
            variant=args.variant,
            overlap_metrics=not args.no_overlap_metrics,
            roofline=args.roofline,
        )
    elif args.probe == "flash-attention":
        from activemonitor_tpu.probes import flash

        if args.sweep:
            result = flash.sweep(
                batch=args.batch,
                # None = per-mode default (clamped off-TPU); an explicit
                # --seq reaches the probe verbatim and always wins
                seq=args.seq,
                heads=args.heads,
                head_dim=args.head_dim,
                iters=args.iters,
                causal=not args.no_causal,
                rounds=args.sweep_rounds,
                min_fraction=args.min_fraction,
            )
        else:
            result = flash.run(
                batch=args.batch,
                seq=args.seq,
                heads=args.heads,
                head_dim=args.head_dim,
                iters=args.iters,
                causal=not args.no_causal,
                tolerance=args.tolerance,
                min_fraction=args.min_fraction,
                roofline=args.roofline,
            )
    elif args.probe == "decode":
        from activemonitor_tpu.probes import decode

        result = decode.run(
            tiny=args.tiny,
            batch=args.batch,
            prompt_len=args.prompt_len,
            decode_tokens=args.decode_tokens,
            iters=args.iters,
            use_flash=args.flash,
            roofline=args.roofline,
        )
    elif args.probe == "serving":
        from activemonitor_tpu.probes import serving

        result = serving.run(
            tiny=args.tiny,
            n_requests=args.requests,
            max_batch=args.max_batch,
            block_size=args.block_size,
            rate_rps=args.rate_rps,
            seed=args.seed,
            roofline=args.roofline,
        )
    elif args.probe == "serving-disagg":
        from activemonitor_tpu.probes import serving

        result = serving.run_disagg(
            tiny=args.tiny,
            n_requests=args.requests,
            prefill_slots=args.prefill_slots,
            decode_slots=args.decode_slots,
            block_size=args.block_size,
            rate_rps=args.rate_rps,
            seed=args.seed,
            prefix_cache=not args.no_prefix_cache,
            speculate=args.speculate,
            cross_slice=args.cross_slice,
        )
    elif args.probe == "memory":
        from activemonitor_tpu.probes import memory

        result = memory.run(probe_gb=args.probe_gb)
    elif args.probe == "straggler":
        from activemonitor_tpu.probes import straggler

        result = straggler.run(
            dim=args.dim, iters=args.iters, threshold=args.threshold
        )
    elif args.probe == "transfer":
        from activemonitor_tpu.probes import transfer

        result = transfer.run(
            size_mb=args.size_mb, iters=args.iters, min_gbps=args.min_gbps
        )
    elif args.probe == "checkpoint":
        from activemonitor_tpu.probes import checkpoint

        result = checkpoint.run(size_mb=args.size_mb, directory=args.directory)
    elif args.probe == "dcn-allreduce":
        from activemonitor_tpu.probes import dcn

        result = dcn.run(size_mb=args.size_mb, iters=args.iters)
    elif args.probe == "all":
        from activemonitor_tpu.probes import suite

        result = suite.run(
            quick=args.quick, skip=args.skip, roofline=args.roofline
        )
    else:  # pragma: no cover - argparse guards
        raise SystemExit(2)
    return result.emit()


if __name__ == "__main__":
    sys.exit(main())
