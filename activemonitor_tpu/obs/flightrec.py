"""Degradation flight recorder: every verdict ships its postmortem.

When a check confirms ok→degraded, the breaker opens, a check is
quarantined, or a shard hands off, the evidence an operator needs is
scattered across four ring buffers that will have wrapped by the time
anyone greps: the span ring (/debug/traces), the result history, the
learned baselines, and the breaker/shard state. The flight recorder
snapshots the CORRELATED slice of all of them at the moment of the
transition into one bundle — bounded in memory, optionally durable as
JSONL (``--flight-dir``), served at ``/debug/flightrec``.

Bundle contract (pinned by the statusz schema contract test):

- ``id``/``kind``/``check``/``ts`` — identity; kind is one of
  :data:`KINDS`.
- ``trace_id`` + ``spans`` — the triggering cycle's trace (the spans
  finished so far), joinable back to ``/debug/traces?trace_id=``.
- ``results`` — the check's result-ring tail (each entry carries its
  own trace_id, attribution bucket and why).
- ``baselines`` — the analysis layer's learned stats at trigger time.
- ``resilience``/``sharding`` — breaker + shard-ownership snapshots.
- ``attribution`` — the check's windowed lost-goodput decomposition.
- ``waterfall`` — the triggering trace's critical-path decomposition
  (obs/criticalpath.py: per-stage seconds summing to the wall span,
  gaps booked as ``untracked``); null when the trace has no finished
  spans.
- ``roofline`` — the check's latest roofline snapshot (obs/roofline.py:
  per-metric bound/intensity/fraction with its cost source) so a
  postmortem reader sees WHERE against the hardware ceilings the check
  sat when it degraded.
- ``extra`` — trigger-specific context (the transition, the shard id…).

Design constraints shared with the tracer/history (obs/trace.py):
injectable clock (``hack/lint.py`` bans wall-clock reads here), bounded
ring, and **never raises into the triggering path** — a recorder bug
must not fail the reconcile/transition that fed it. The durable sink is
append-only JSONL: one bundle per line, replayable with ``jq``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
from typing import Deque, List, Optional

from activemonitor_tpu.obs.trace import current_trace_id
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.flightrec")

KIND_DEGRADED = "degraded-transition"
KIND_BREAKER = "breaker-open"
KIND_QUARANTINE = "quarantine"
KIND_HANDOFF = "shard-handoff"
# a scenario-matrix cell's hysteresis verdict confirmed degraded
# (analysis/matrix.py): the bundle's extra carries both artifacts'
# evidence (the regressing round's cell entry, the prior round's, and
# the auto-bisect verdict)
KIND_MATRIX = "matrix-regression"
# a profile-on-anomaly capture landed (controller/manager.py
# ProfileOnAnomaly): the bundle's extra carries the capture directory
# path and the trigger reason, next to the profiled run's waterfall
KIND_PROFILE = "profile-capture"
# an adaptive-control lever engaged, released, or targeted a remedy
# (resilience/adapt.py): the bundle's extra carries the lever, action,
# attributed cause, and the human-readable decision detail — one bundle
# per engage/release, so an adaptation episode is bracketed in the
# flight log
KIND_ADAPTIVE = "adaptive-lever"
KINDS = (
    KIND_DEGRADED,
    KIND_BREAKER,
    KIND_QUARANTINE,
    KIND_HANDOFF,
    KIND_MATRIX,
    KIND_PROFILE,
    KIND_ADAPTIVE,
)

DEFAULT_CAPACITY = 256  # bundles retained in memory
SPAN_TAIL = 20  # fallback span excerpt when no trace is active
RESULT_TAIL = 10  # result-ring excerpt per bundle

FLIGHT_FILE = "flightrec.jsonl"
# size-capped rotation for the durable sink (same discipline as the
# telemetry journal's segments): the active file stays FLIGHT_FILE —
# what the tests and jq pipelines read — and aged content shifts to
# flightrec-1.jsonl, flightrec-2.jsonl, … with the oldest dropped
DEFAULT_MAX_BYTES = 4 << 20
DEFAULT_KEEP_ROTATIONS = 4


class FlightRecorder:
    """Owned by the reconciler like the tracer; evidence sources are
    wired post-construction (same shape as FleetStatus): ``tracer``,
    ``history``, ``fleet``, ``resilience``, ``analysis``, ``sharding``
    — any of them may stay None (standalone/unit-test recorders record
    null evidence for that source rather than failing)."""

    def __init__(
        self,
        clock: Optional[Clock] = None,
        capacity: int = DEFAULT_CAPACITY,
        flight_dir: str = "",
        max_bytes: int = DEFAULT_MAX_BYTES,
    ):
        self.clock = clock or Clock()
        self.flight_dir = flight_dir
        self.max_bytes = max(0, int(max_bytes))
        self._ring: Deque[dict] = collections.deque(maxlen=max(1, capacity))
        self._seq = 0
        self.tracer = None
        self.history = None
        self.fleet = None
        self.resilience = None
        self.analysis = None
        self.sharding = None

    # -- recording ------------------------------------------------------
    def record(self, kind: str, key: str = "", **extra) -> Optional[dict]:
        """Snapshot one transition's evidence bundle. Returns the bundle
        (or None on an internal failure — never raises into the
        transition that triggered it)."""
        try:
            return self._record(kind, key, extra)
        except Exception:
            log.exception("flight recording failed for %s/%s", kind, key)
            return None

    def _record(self, kind: str, key: str, extra: dict) -> dict:
        self._seq += 1
        trace_id = current_trace_id()
        if not trace_id and self.history is not None and key:
            # outside any span (e.g. a sweep-driven breaker trip): the
            # check's last recorded run is the best correlated trace
            last = self.history.last(key)
            trace_id = last.trace_id if last is not None else ""
        spans: List[dict] = []
        waterfall = None
        if self.tracer is not None:
            live_spans = (
                self.tracer.spans_for_trace(trace_id) if trace_id else []
            )
            if live_spans:
                # the waterfall must fold LIVE Span objects: to_dict()
                # deliberately drops the raw monotonic start/end floats
                # (wall timestamps only), so it cannot be rebuilt from
                # the serialized spans below
                from activemonitor_tpu.obs import criticalpath

                last = (
                    self.history.last(key)
                    if self.history is not None and key
                    else None
                )
                waterfall = criticalpath.build_waterfall(
                    live_spans,
                    timings=getattr(last, "timings", None),
                    trace_id=trace_id,
                )
                spans = [s.to_dict() for s in live_spans]
            if not spans:
                spans = [
                    s.to_dict()
                    for s in self.tracer.finished_spans[-SPAN_TAIL:]
                ]
        results: List[dict] = []
        if self.history is not None and key:
            results = [r.to_dict() for r in self.history.tail(key, RESULT_TAIL)]
        baselines = None
        if self.analysis is not None and key:
            baselines = self.analysis.baselines_snapshot(key)
        resilience = (
            self.resilience.snapshot() if self.resilience is not None else None
        )
        sharding = (
            self.sharding.snapshot() if self.sharding is not None else None
        )
        attribution = None
        roofline = None
        if self.fleet is not None and key:
            attribution = self.fleet.check_attribution(key)
            roofline = self.fleet.check_roofline(key)
        bundle = {
            "id": f"fr-{self._seq:06d}",
            "kind": kind,
            "check": key,
            "ts": self.clock.now().isoformat(),
            "trace_id": trace_id,
            "spans": spans,
            "results": results,
            "baselines": baselines,
            "resilience": resilience,
            "sharding": sharding,
            "attribution": attribution,
            "roofline": roofline,
            "waterfall": waterfall,
            # JSON round-trip now: the ring must hold exactly what the
            # JSONL sink and /debug/flightrec serve (tuples → lists,
            # exotic values stringified), not a Python-only shape
            "extra": json.loads(json.dumps(extra, default=str)),
        }
        self._ring.append(bundle)
        self._persist(bundle)
        log.warning(
            "flight bundle %s recorded (%s%s)",
            bundle["id"],
            kind,
            f" for {key}" if key else "",
        )
        return bundle

    def _persist(self, bundle: dict) -> None:
        """Append one JSONL line to ``flight_dir``; best-effort (an
        unwritable disk costs durability, never the transition). The
        sink is size-capped: at ``max_bytes`` the active file rotates
        (journal.rotate_capped) so a long-lived controller's flight
        directory is bounded like its in-memory ring."""
        if not self.flight_dir:
            return
        try:
            from activemonitor_tpu.obs.journal import rotate_capped

            os.makedirs(self.flight_dir, exist_ok=True)
            path = os.path.join(self.flight_dir, FLIGHT_FILE)
            rotate_capped(path, self.max_bytes, keep=DEFAULT_KEEP_ROTATIONS)
            with open(path, "a") as f:
                f.write(json.dumps(bundle, default=str) + "\n")
        except OSError:
            log.exception(
                "failed to persist flight bundle %s to %s",
                bundle.get("id"),
                self.flight_dir,
            )

    # -- reading --------------------------------------------------------
    def bundles(
        self, kind: Optional[str] = None, check: Optional[str] = None
    ) -> List[dict]:
        """Retained bundles, oldest first; ``kind``/``check`` narrow —
        the ``/debug/flightrec`` query parameters."""
        out = list(self._ring)
        if kind:
            out = [b for b in out if b["kind"] == kind]
        if check:
            out = [b for b in out if b["check"] == check]
        return out

    def __len__(self) -> int:
        return len(self._ring)

    @staticmethod
    def read_jsonl(path: str):
        """Parse a durable flight file back (tests, offline analysis)."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)
