"""Ring-attention (sequence-parallel) probe — the long-context canary.

Two verdicts in one probe:

1. correctness — sequence-parallel ring attention over the mesh must
   match single-device attention (a wrong answer here means broken
   collectives/permutes, the scariest failure mode for long-context
   training);
2. throughput — attended tokens/s for a sequence n× longer than one
   device could hold, exported as gauges.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.ops.ring_attention import reference_attention, ring_attention
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    batch: int = 1,
    seq_per_device: int = 1024,
    heads: int = 8,
    head_dim: int = 128,
    iters: int = 5,
    tolerance: float = 2e-2,
    use_flash: bool = False,
) -> ProbeResult:
    mesh = make_1d_mesh("sp")
    n = mesh.devices.size
    seq = seq_per_device * n
    dtype = jnp.bfloat16
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (
        jax.random.normal(kk, (batch, seq, heads, head_dim), dtype) for kk in keys
    )

    # correctness on a small slice (full reference attention is O(S^2)
    # on one device — keep it tractable)
    small = min(seq, 64 * n)
    got = ring_attention(
        q[:, :small], k[:, :small], v[:, :small], mesh, "sp", use_flash=use_flash
    )
    want = reference_attention(q[:, :small], k[:, :small], v[:, :small])
    max_err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )
    correct = max_err <= tolerance

    # throughput: chained ring attentions (output feeds next Q)
    def make_chain(kreps):
        @jax.jit
        def chain(q, k, v):
            x = q
            for _ in range(kreps):
                x = ring_attention(x, k, v, mesh, "sp", use_flash=use_flash)
            return x.astype(jnp.float32).sum()

        return chain

    seconds = chain_delta_seconds(make_chain, q, k, v, k1=1, k2=3, iters=iters)
    tokens_per_second = batch * seq / seconds
    # attention FLOPs: 2 matmuls of [S, S] x head_dim per head, causal halves it
    flops = 2 * 2 * batch * heads * seq * seq * head_dim / 2
    tflops = flops / seconds / 1e12

    metrics = [
        ProbeMetric(
            "ring-attention-max-error",
            max_err,
            help="Max abs error of sequence-parallel vs single-device attention",
        ),
        ProbeMetric(
            "ring-attention-tokens-per-second",
            tokens_per_second,
            help="Ring-attention throughput over the sequence-parallel mesh",
        ),
        ProbeMetric(
            "ring-attention-tflops", tflops, help="Achieved attention TFLOP/s"
        ),
    ]
    summary = (
        f"ring attention over {n} devices: err {max_err:.1e} "
        f"({'OK' if correct else 'MISMATCH'}), "
        f"{tokens_per_second:,.0f} tok/s @ seq {seq}"
    )
    return ProbeResult(
        ok=correct,
        metrics=metrics,
        summary=summary,
        details={
            "devices": n,
            "block_compute": "flash" if use_flash else "xla",
            "seq": seq,
            "seq_per_device": seq_per_device,
            "heads": heads,
            "head_dim": head_dim,
            "seconds_per_op": seconds,
            "max_error": max_err,
        },
    )
