"""Shared exception types."""


class MissingDependencyError(RuntimeError):
    """A required credential/backend is unavailable (e.g. cluster mode
    without any Kubernetes credentials). The CLI turns this into a
    usage error."""


class ConfigurationError(ValueError):
    """An invalid flag/option combination. Subclasses ValueError so
    library callers can catch broadly, while the CLI catches exactly
    this (not every internal ValueError) for its usage-error exit."""
