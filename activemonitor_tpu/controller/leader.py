"""Leader election — multi-replica controller safety.

The reference gets HA via controller-runtime's Lease-based leader
election (reference: cmd/main.go:87-88, election ID
"689451f8.keikoproj.io"). Equivalents here:

- :class:`FileLeaderElector` — flock-based, for multiple controller
  processes sharing a host/volume (the local deployment mode).
- :class:`KubernetesLeaseElector` — coordination.k8s.io/v1 Lease
  objects with renewal/takeover timing, import-gated on ``kubernetes``.
- :class:`AlwaysLeader` — single-replica default (election off, like
  the reference's default ``--leader-elect=false``).
"""

from __future__ import annotations

import asyncio
import os
from typing import Protocol

from activemonitor_tpu.errors import MissingDependencyError

ELECTION_ID = "689451f8.keikoproj.io"  # parity with the reference


class LeaderElector(Protocol):
    async def acquire(self) -> None:
        """Blocks until this process holds leadership."""
        ...

    def release(self) -> None: ...


class AlwaysLeader:
    async def acquire(self) -> None:
        return None

    def release(self) -> None:
        return None


class FileLeaderElector:
    """flock-based election for co-hosted replicas."""

    def __init__(self, path: str = "", poll_seconds: float = 1.0):
        self._path = path or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"activemonitor-{ELECTION_ID}.lock"
        )
        self._poll = poll_seconds
        self._fd = None

    async def acquire(self) -> None:
        import fcntl

        self._fd = open(self._path, "w")
        while True:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fd.write(str(os.getpid()))
                self._fd.flush()
                return
            except BlockingIOError:
                await asyncio.sleep(self._poll)

    def release(self) -> None:
        if self._fd is not None:
            import fcntl

            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                self._fd.close()
                self._fd = None


class KubernetesLeaseElector:  # pragma: no cover - needs a cluster
    """coordination.k8s.io Lease election (import-gated)."""

    def __init__(
        self,
        namespace: str = "health",
        name: str = ELECTION_ID,
        identity: str = "",
        lease_seconds: int = 15,
    ):
        try:
            from kubernetes import client  # type: ignore  # noqa: F401
        except ImportError as e:
            raise MissingDependencyError(
                "the 'kubernetes' package is required for KubernetesLeaseElector"
            ) from e
        import socket
        import uuid

        self._namespace = namespace
        self._name = name
        self._identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self._lease_seconds = lease_seconds
        self._stop = False

    async def acquire(self) -> None:
        import datetime

        from kubernetes import client  # type: ignore
        from kubernetes.client.rest import ApiException  # type: ignore

        api = client.CoordinationV1Api()
        while not self._stop:
            now = datetime.datetime.now(datetime.timezone.utc)
            body = client.V1Lease(
                metadata=client.V1ObjectMeta(name=self._name, namespace=self._namespace),
                spec=client.V1LeaseSpec(
                    holder_identity=self._identity,
                    lease_duration_seconds=self._lease_seconds,
                    renew_time=now,
                ),
            )
            try:
                existing = await asyncio.to_thread(
                    api.read_namespaced_lease, self._name, self._namespace
                )
                holder = existing.spec.holder_identity
                renew = existing.spec.renew_time
                expired = (
                    renew is None
                    or (now - renew).total_seconds() > self._lease_seconds
                )
                if holder == self._identity or expired:
                    existing.spec = body.spec
                    await asyncio.to_thread(
                        api.replace_namespaced_lease,
                        self._name,
                        self._namespace,
                        existing,
                    )
                    return
            except ApiException as e:
                if e.status == 404:
                    try:
                        await asyncio.to_thread(
                            api.create_namespaced_lease, self._namespace, body
                        )
                        return
                    except ApiException:
                        pass
            await asyncio.sleep(self._lease_seconds / 3)

    def release(self) -> None:
        self._stop = True
