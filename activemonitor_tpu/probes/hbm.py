"""HBM bandwidth probe.

Times a STREAM-scale pass (read + write = 2× payload bytes) and
compares achieved GB/s against the chip's rated HBM bandwidth. Uses the
Pallas kernel on TPU (ops/stream.py) and the fused XLA expression
elsewhere (interpret-mode Pallas is functionally identical but not
timeable).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.ops.stream import stream_scale_pallas, stream_scale_xla
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    size_mb: float = 256.0,
    iters: int = 10,
    threshold: float = 0.6,
    use_pallas: bool = True,
) -> ProbeResult:
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    dtype = jnp.bfloat16
    cols = 1024
    rows = max(512, int(size_mb * 1e6 / jnp.dtype(dtype).itemsize) // cols)
    rows -= rows % 512
    x = jnp.ones((rows, cols), dtype)
    payload = rows * cols * jnp.dtype(dtype).itemsize

    op = stream_scale_pallas if (on_tpu and use_pallas) else stream_scale_xla
    # bf16 scale factor chosen representable so chained values stay finite
    scale = 1.0078125

    def make_chain(k):
        @jax.jit
        def chain(x):
            for _ in range(k):  # data-dependent chain of full passes
                x = op(x, scale)
            # full reduction: a partial slice would let XLA dead-code
            # the untouched elements of every pass in the chain
            return x.astype(jnp.float32).sum()

        return chain

    # wide k spread: a single pass is sub-millisecond, so the delta must
    # tower over tunnel/dispatch jitter
    seconds = chain_delta_seconds(make_chain, x, k1=4, k2=28, iters=iters)
    gbps = 2 * payload / seconds / 1e9  # read + write per pass

    rated = rated_for(device.device_kind)
    metrics = [
        ProbeMetric("hbm-stream-gbps", gbps, help="Achieved STREAM-scale bandwidth, GB/s")
    ]
    details = {
        "payload_mb": payload / 1e6,
        "seconds_per_op": seconds,
        "kernel": "pallas" if (on_tpu and use_pallas) else "xla",
        "device_kind": device.device_kind,
    }
    ok = True
    if rated is not None and on_tpu:
        fraction = gbps / rated.hbm_gbps
        metrics.append(
            ProbeMetric(
                "hbm-fraction-of-rated",
                fraction,
                help="Achieved / rated HBM bandwidth",
            )
        )
        details["rated_gbps"] = rated.hbm_gbps
        details["fraction"] = round(fraction, 3)
        ok = fraction >= threshold
        summary = f"HBM {gbps:.0f} GB/s = {fraction:.0%} of rated {rated.hbm_gbps:.0f} GB/s"
    else:
        summary = f"memory bandwidth {gbps:.1f} GB/s on {device.platform} (no rated comparison)"
    return ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
