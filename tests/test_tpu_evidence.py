"""hack/tpu_evidence.py — the opportunistic TPU-evidence harness.

The device tunnel wedges for hours; the harness is the round's answer
(poll → capture → atomic artifacts). These tests drive its machinery
without hardware: probe timeout/failure handling, the capture
plumbing with a stubbed child, artifact atomicity, and the sweep
renderer — so the one tool that must work during a rare healthy
window cannot rot unnoticed.
"""

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "tpu_evidence", REPO / "hack" / "tpu_evidence.py"
)
te = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(te)


def test_probe_timeout_reads_as_unreachable(monkeypatch):
    monkeypatch.setattr(te, "_PROBE_SRC", "import time; time.sleep(60)")
    assert te.device_reachable(timeout=1.0) is False


def test_probe_failure_reads_as_unreachable(monkeypatch):
    monkeypatch.setattr(te, "_PROBE_SRC", "raise SystemExit(3)")
    assert te.device_reachable(timeout=30.0) is False


def test_probe_success_reads_as_reachable(monkeypatch):
    monkeypatch.setattr(te, "_PROBE_SRC", "print('ok')")
    assert te.device_reachable(timeout=30.0) is True


def _args(tmp_path, **over):
    defaults = dict(
        probe_timeout=30.0,
        capture_timeout=60.0,
        out=str(tmp_path / "BENCH_TPU.json"),
        sweep_out=str(tmp_path / "SWEEP_TPU.md"),
    )
    defaults.update(over)
    return type("Args", (), defaults)()


def test_capture_skipped_while_wedged(tmp_path, monkeypatch):
    monkeypatch.setattr(te, "device_reachable", lambda timeout: False)
    assert te.capture_once(_args(tmp_path)) is False
    assert not (tmp_path / "BENCH_TPU.json").exists()


def test_capture_writes_timestamped_artifacts(tmp_path, monkeypatch):
    """A healthy window produces BOTH artifacts atomically, with the
    capture timestamp and harness provenance stamped in."""
    doc = {
        "metric": "mxu_bf16_fraction_of_rated",
        "value": 0.93,
        "unit": "fraction",
        "vs_baseline": 1.03,
        "platform": "tpu",
        "n_devices": 1,
        "device_kind": "TPU v5e",
        "flash_sweep": {
            "summary": "best fwd 90 TFLOP/s (1024x1024)",
            "details": {
                "batch": 4, "seq": 2048, "heads": 8, "head_dim": 128,
                "causal": True,
                "forward_table_tflops": {"1024x1024": 90.1, "512x512": 71.0},
                "train_table_tflops": {"1024x256": 111.0},
            },
        },
    }
    monkeypatch.setattr(te, "device_reachable", lambda timeout: True)

    # stub the child capture: echo our doc instead of touching hardware
    def fake_run(cmd, **kw):
        assert "--child-capture" in cmd
        return te.subprocess.CompletedProcess(
            cmd, 0, stdout=(json.dumps(doc) + "\n").encode(), stderr=b""
        )

    monkeypatch.setattr(te.subprocess, "run", fake_run)
    assert te.capture_once(_args(tmp_path)) is True

    bench = json.loads((tmp_path / "BENCH_TPU.json").read_text())
    assert bench["value"] == 0.93
    assert bench["harness"] == "hack/tpu_evidence.py"
    assert "captured_at" in bench
    sweep = (tmp_path / "SWEEP_TPU.md").read_text()
    assert "| 1024x1024 | 90.1 |" in sweep
    assert "fwd+bwd" in sweep
    # no torn temp files left behind
    assert not list(tmp_path.glob("*.tmp"))

    # and bench.py's fallback embeds exactly this capture
    monkeypatch.syspath_prepend(str(REPO))
    import bench as bench_mod

    block = bench_mod._last_known_good_tpu(str(tmp_path / "BENCH_TPU.json"))
    assert block["value"] == 0.93
    assert block["captured_at"] == bench["captured_at"]
    assert block["flash_sweep_summary"] == doc["flash_sweep"]["summary"]


def test_capture_handles_garbage_child_output(tmp_path, monkeypatch):
    monkeypatch.setattr(te, "device_reachable", lambda timeout: True)
    monkeypatch.setattr(
        te.subprocess,
        "run",
        lambda cmd, **kw: te.subprocess.CompletedProcess(
            cmd, 0, stdout=b"not json\n", stderr=b""
        ),
    )
    assert te.capture_once(_args(tmp_path)) is False
    assert not (tmp_path / "BENCH_TPU.json").exists()
