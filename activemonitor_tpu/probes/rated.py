"""Rated hardware specs per TPU generation.

Denominators for the "fraction of rated" gauges the probes export
(BASELINE.md north star: ICI all-reduce ≥90 % of rated on a v5e-8).
Figures are the public per-chip numbers (cf. the "How to Scale Your
Model" rooflines); every value can be overridden via environment
variables for new silicon or corrected ratings:

    ACTIVEMONITOR_RATED_BF16_TFLOPS
    ACTIVEMONITOR_RATED_INT8_TOPS
    ACTIVEMONITOR_RATED_HBM_GBPS
    ACTIVEMONITOR_RATED_ICI_GBPS   (per-link, one direction)
    ACTIVEMONITOR_RATED_DCN_GBPS   (cross-slice, per host, one direction)
    ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE   (roofline ridge point)

The DCN figures are per-HOST egress for the multislice data-center
network tier (the slow axis of a ("dcn", "ici") mesh) — approximate
public numbers, deliberately overridable per fleet: unlike ICI, DCN
provisioning varies by deployment, so the env override is the
expected path for a fleet that knows its NICs.

The bf16 peak and HBM bandwidth together define the chip's roofline
(obs/roofline.py): the ridge point — peak FLOP/s over HBM byte/s, in
FLOPs per byte — is where the memory-bandwidth ceiling meets the
compute ceiling. :func:`ridge_point` derives it from the (already
override-validated) table figures, with its own validated override for
silicon whose effective ridge diverges from the paper numbers.
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger("activemonitor.probes")


@dataclass(frozen=True)
class RatedSpec:
    generation: str
    bf16_tflops: float  # peak dense bf16 matmul TFLOP/s per chip
    hbm_gbps: float  # HBM bandwidth GB/s per chip
    ici_unidir_gbps: float  # ICI bandwidth per link, one direction, GB/s
    ici_links: int  # ICI links per chip
    int8_tops: float = 0.0  # peak dense int8 matmul TOP/s per chip (0 = n/a)
    # cross-slice DCN egress per host, one direction, GB/s (0 = n/a —
    # single-slice hardware or unknown provisioning); approximate and
    # meant to be overridden via ACTIVEMONITOR_RATED_DCN_GBPS
    dcn_gbps: float = 0.0

    @property
    def ridge_flops_per_byte(self) -> float:
        """Roofline ridge point: rated peak FLOP/s / rated HBM byte/s.
        Below this arithmetic intensity a kernel is memory-bound (its
        ceiling is intensity x bandwidth); above it, compute-bound
        (the ceiling is the flat bf16 peak). Derived, so the validated
        bf16/HBM overrides flow through; :func:`ridge_point` adds the
        direct override."""
        return self.bf16_tflops * 1e12 / (self.hbm_gbps * 1e9)


# device_kind substrings -> rated spec (DCN: ~200 Gbps/host NICs on
# the v5/v6 multislice generations, ~100 Gbps on v4 — per-host one
# direction, the denominator of dcn-xslice-fraction-of-rated)
_RATED = [
    ("v6", RatedSpec("v6e", bf16_tflops=918.0, hbm_gbps=1640.0, ici_unidir_gbps=90.0, ici_links=4, int8_tops=1836.0, dcn_gbps=25.0)),
    ("v5p", RatedSpec("v5p", bf16_tflops=459.0, hbm_gbps=2765.0, ici_unidir_gbps=90.0, ici_links=6, int8_tops=918.0, dcn_gbps=25.0)),
    ("v5 lite", RatedSpec("v5e", bf16_tflops=197.0, hbm_gbps=819.0, ici_unidir_gbps=45.0, ici_links=4, int8_tops=394.0, dcn_gbps=25.0)),
    ("v5e", RatedSpec("v5e", bf16_tflops=197.0, hbm_gbps=819.0, ici_unidir_gbps=45.0, ici_links=4, int8_tops=394.0, dcn_gbps=25.0)),
    # v4 has no int8 MXU mode (int8 ships with v5)
    ("v4", RatedSpec("v4", bf16_tflops=275.0, hbm_gbps=1228.0, ici_unidir_gbps=45.0, ici_links=6, dcn_gbps=12.5)),
]


def _override(value: float, env: str) -> float:
    """An env-supplied rated figure, validated: it is the DENOMINATOR
    of every fraction-of-rated gauge and verdict, so a malformed or
    non-positive override must fall back to the table value with a
    warning — never crash the probe, never divide by zero or flip the
    fraction's sign."""
    raw = os.environ.get(env)
    if raw is None or not raw.strip():
        return value  # unset/empty: the table value stands
    try:
        parsed = float(raw)
    except ValueError:
        log.warning(
            "ignoring %s=%r: not a number; using rated %s", env, raw, value
        )
        return value
    if not math.isfinite(parsed) or parsed <= 0:
        log.warning(
            "ignoring %s=%r: rated figures must be positive and finite; "
            "using rated %s",
            env,
            raw,
            value,
        )
        return value
    return parsed


# Single-chip performance bars (BASELINE.md § single-chip bar): the
# battery enforces these on real TPU hardware so an underperforming
# chip FAILS its HealthCheck instead of merely reporting low gauges.
# - flash fwd ≥0.40 of rated bf16 peak: measured ~0.46 on a healthy
#   v5e (ops/flash_attention.py block-sweep tables; re-captured into
#   SWEEP_TPU.md by hack/tpu_evidence.py) — 0.40 leaves headroom for
#   shared-chip contention without passing a sick MXU/Mosaic path.
# - training-step ≥0.15 MFU: PROVISIONAL floor for the probe
#   transformer (small-model steps are overhead-bound well below the
#   large-model 40-50% regime); raise once hack/tpu_evidence.py commits
#   a measured train_mfu to BENCH_TPU.json. Overridable per run via
#   --mfu-threshold / --min-fraction.
TRAIN_MFU_BAR = float(os.environ.get("ACTIVEMONITOR_TRAIN_MFU_BAR", "0.15"))
FLASH_FRACTION_BAR = float(
    os.environ.get("ACTIVEMONITOR_FLASH_FRACTION_BAR", "0.40")
)


def ridge_point(spec: RatedSpec) -> float:
    """The spec's roofline ridge point (FLOPs/byte), env-overridable
    through the same validation as every other rated figure: it is the
    DENOMINATOR-side pivot of every bound classification, so a
    malformed or non-positive override falls back to the derived value
    with a warning — it must never flip a healthy memory-bound kernel
    into a "badly underperforming compute-bound" verdict."""
    return _override(
        spec.ridge_flops_per_byte, "ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE"
    )


def rated_for(device_kind: str) -> Optional[RatedSpec]:
    """Spec for a jax device_kind string (e.g. "TPU v5 lite"), or None
    for unknown/non-TPU hardware."""
    kind = device_kind.lower()
    for needle, spec in _RATED:
        if needle in kind:
            return RatedSpec(
                generation=spec.generation,
                bf16_tflops=_override(spec.bf16_tflops, "ACTIVEMONITOR_RATED_BF16_TFLOPS"),
                hbm_gbps=_override(spec.hbm_gbps, "ACTIVEMONITOR_RATED_HBM_GBPS"),
                ici_unidir_gbps=_override(spec.ici_unidir_gbps, "ACTIVEMONITOR_RATED_ICI_GBPS"),
                ici_links=spec.ici_links,
                int8_tops=_override(spec.int8_tops, "ACTIVEMONITOR_RATED_INT8_TOPS"),
                dcn_gbps=_override(spec.dcn_gbps, "ACTIVEMONITOR_RATED_DCN_GBPS"),
            )
    return None


def capability_summary(device_kind: str) -> Optional[dict]:
    """The generation's rated figures as one plain dict — the single
    source of truth behind the federation's cluster capability cards
    and the ``am-tpu clusters`` table, so they can never drift from the
    probes' fraction-of-rated denominators. Env overrides flow through
    (same :func:`_override` validation: malformed / non-positive values
    warn and fall back). Returns None for unknown/non-TPU hardware."""
    spec = rated_for(device_kind)
    if spec is None:
        return None
    return {
        "generation": spec.generation,
        "bf16_tflops": spec.bf16_tflops,
        "int8_tops": spec.int8_tops,
        "hbm_gbps": spec.hbm_gbps,
        "ici_unidir_gbps": spec.ici_unidir_gbps,
        "ici_links": spec.ici_links,
        "dcn_gbps": spec.dcn_gbps,
        "ridge_flops_per_byte": ridge_point(spec),
    }
