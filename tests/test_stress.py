"""Concurrency stress — the race-detection tier (SURVEY.md §5.2).

The reference relies on manual lock discipline and leaves one
documented race; here scheduler state is single-owner on the event
loop, so the invariants under load are: no cross-check contamination,
no lost or duplicated runs, no concurrent reconcile of one key.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller.client import NotFoundError
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine, fail_after, succeed_after
from activemonitor_tpu.metrics import MetricsCollector

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"

N_CHECKS = 40


def make_hc(i: int):
    # odd checks fail, even succeed — cross-contamination would show up
    # as wrong counters on either side
    return HealthCheck.from_dict(
        {
            "metadata": {"name": f"stress-{i:03d}", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 3600,
                "level": "cluster",
                "workflow": {
                    "generateName": f"stress-{i:03d}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": f"sa-{i:03d}",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


@pytest.mark.asyncio
async def test_many_checks_under_concurrent_reconciles():
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    for i in range(1, N_CHECKS, 2):
        engine.on_prefix(f"stress-{i:03d}-", fail_after(1, f"fail-{i:03d}"))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(capacity=100000),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        # apply all checks concurrently + storm duplicate events
        await asyncio.gather(*(client.apply(make_hc(i)) for i in range(N_CHECKS)))
        for _ in range(3):
            for i in range(N_CHECKS):
                manager.enqueue("health", f"stress-{i:03d}")
            await asyncio.sleep(0.01)

        async def settled():
            for _ in range(400):
                await asyncio.sleep(0.025)
                done = 0
                for i in range(N_CHECKS):
                    hc = await client.get("health", f"stress-{i:03d}")
                    if hc.status.total_healthcheck_runs >= 1:
                        done += 1
                if done == N_CHECKS:
                    return True
            return False

        assert await settled(), "not all checks completed a run"
        await reconciler.wait_watches()

        for i in range(N_CHECKS):
            hc = await client.get("health", f"stress-{i:03d}")
            if i % 2:
                assert hc.status.status == "Failed", i
                assert hc.status.failed_count == 1, (i, hc.status)
                assert hc.status.error_message == f"fail-{i:03d}", i
                assert hc.status.success_count == 0, i
            else:
                assert hc.status.status == "Succeeded", i
                assert hc.status.success_count == 1, (i, hc.status)
                assert hc.status.failed_count == 0, i
            # exactly one workflow per check despite the event storm
            prefix = f"stress-{i:03d}-"
            count = sum(
                1
                for wf in engine.submitted
                if wf["metadata"]["generateName"] == prefix
            )
            assert count == 1, (i, count)
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_interleaved_apply_delete_storm():
    """Rapid create/delete cycles must end clean: no timers or watches
    left for deleted checks, no crash."""
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()
    try:
        for cycle in range(5):
            await asyncio.gather(*(client.apply(make_hc(i)) for i in range(10)))
            await asyncio.sleep(0.05)
            for i in range(10):
                try:
                    await client.delete("health", f"stress-{i:03d}")
                except NotFoundError:
                    pass  # already gone in a previous churn round
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.3)
        await reconciler.wait_watches()
        # all deleted: no pending timers may survive
        for i in range(10):
            assert not reconciler.timers.pending(f"health/stress-{i:03d}")
    finally:
        await manager.stop()


# -- fake-clock soak tier ----------------------------------------------
#
# The reference's envtest runs minutes of wall-clock with a handful of
# CRs (suite_test.go); nothing there proves the controller's resource
# discipline over HOURS of schedule churn at fleet scale. This tier
# does: 210 HealthChecks (interval / storm-aligned cron / failing
# remedy), two simulated hours on the FakeClock with delete+re-apply
# churn in the middle, then QUANTIFIED invariants — run counts per
# cadence, remedy hysteresis bounds, watch-task and timer-wheel sizes,
# and stable metrics cardinality across the churn (a leak in any of
# those grows with simulated time and fails the bound).
#
# Scale margin: the same scenario was validated one-off at 630 checks
# over 4 simulated hours (~60 s wall) with every invariant scaled and
# holding — the committed size keeps the default suite fast, not the
# controller safe.

N_SOAK = 210  # divisible by 3: interval / cron / remedy thirds
SIM_SECONDS = 2 * 3600


def make_soak_hc(i: int):
    kind = i % 3
    spec = {
        "level": "cluster",
        "workflow": {
            "generateName": f"soak-{i:03d}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": f"soak-sa-{i:03d}",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if kind == 0:
        spec["repeatAfterSec"] = 600
    elif kind == 1:
        # every cron check shares the same fire minutes: a 70-check
        # thundering herd at :00/:15/:30/:45
        spec["schedule"] = {"cron": "*/15 * * * *"}
    else:
        spec["repeatAfterSec"] = 900
        spec["remedyRunsLimit"] = 2
        spec["remedyResetInterval"] = 1800
        spec["remedyworkflow"] = {
            "generateName": f"soak-fix-{i:03d}-",
            "resource": {
                "namespace": "health",
                "serviceAccount": f"soak-fix-sa-{i:03d}",
                "source": {"inline": WF_INLINE},
            },
        }
    return HealthCheck.from_dict(
        {
            "metadata": {"name": f"soak-{i:03d}", "namespace": "health"},
            "spec": spec,
        }
    )


# -- sharded-fleet soak (ISSUE 6 acceptance, full-scale tier) ----------
#
# ≥50k synthetic checks on the stub apiserver, 3 sharded controller
# replicas on one seeded FakeClock. One replica is hard-killed
# mid-cycle; the surviving owners adopt its shard and every owed run
# fires EXACTLY once fleet-wide — the tier-1 slice of this scenario
# (24 checks) lives in tests/test_chaos.py; this is the scale proof.

N_SHARD_SOAK = 50_000
OWED_BOOT = 900  # never ran: owed the moment the fleet boots
OWED_LATER = 600  # become owed at t≈120, AFTER the kill — the handoff's runs
SOAK_INTERVAL = 7200  # current checks never re-fire inside the window


def _soak_obj(i: int, epoch_iso: str, finished_iso) -> dict:
    from activemonitor_tpu import GROUP, VERSION

    doc = {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "HealthCheck",
        "metadata": {"name": f"s50-{i:05d}", "namespace": "health"},
        "spec": {
            "repeatAfterSec": SOAK_INTERVAL,
            "level": "cluster",
            "workflow": {
                "generateName": f"s50-{i:05d}-",
                "workflowtimeout": 300,
                "resource": {
                    "namespace": "health",
                    "serviceAccount": "s50-sa",
                    "source": {"inline": WF_INLINE},
                },
            },
        },
    }
    if finished_iso is not None:
        doc["status"] = {
            "status": "Succeeded",
            "startedAt": epoch_iso,
            "finishedAt": finished_iso,
            "successCount": 1,
            "totalHealthCheckRuns": 1,
        }
    return doc


@pytest.mark.slow
@pytest.mark.asyncio
async def test_shard_soak_50k_checks_survive_owner_kill_exactly_once():
    import datetime

    from activemonitor_tpu import GROUP, VERSION
    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )
    from activemonitor_tpu.controller.sharding import ShardCoordinator
    from activemonitor_tpu.engine.argo import (
        WF_GROUP,
        WF_PLURAL,
        WF_VERSION,
        ArgoWorkflowEngine,
    )
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.obs.slo import rollup_statusz
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import advance, drive_until, stub_env

    async with stub_env() as (server, api_a):
        clock = FakeClock()
        now = clock.now()

        def iso(dt):
            return dt.isoformat()

        # seed 50k checks WITHOUT watch broadcast (bulk fixture): 900
        # owed at boot (never ran), 600 owed at t≈120 (after the kill),
        # the rest current until far outside the window
        objs = []
        for i in range(N_SHARD_SOAK):
            if i < OWED_BOOT:
                finished = None
            elif i < OWED_BOOT + OWED_LATER:
                finished = iso(
                    now - datetime.timedelta(seconds=SOAK_INTERVAL - 120)
                )
            else:
                finished = iso(now - datetime.timedelta(seconds=60))
            objs.append(_soak_obj(i, iso(now), finished))

        apis = {
            "a": api_a,
            "b": KubeApi(KubeConfig(server=server.url)),
            "c": KubeApi(KubeConfig(server=server.url)),
        }
        player_api = KubeApi(KubeConfig(server=server.url))
        managers, coords, mets = {}, {}, {}
        for idx, tag in enumerate("abc"):
            metrics = MetricsCollector()
            coord = ShardCoordinator(
                api=apis[tag],
                namespace="health",
                shards=3,
                shard_id=idx,
                identity=f"replica-{tag}",
                clock=clock,
                metrics=metrics,
                lease_seconds=15.0,
                steal_threshold=10**9,  # adoption backlogs must not shed
            )
            client = KubernetesHealthCheckClient(apis[tag], owns=coord.owns_event)
            reconciler = HealthCheckReconciler(
                client=client,
                engine=ArgoWorkflowEngine(apis[tag]),
                rbac=RBACProvisioner(InMemoryRBACBackend()),
                recorder=EventRecorder(capacity=5000),
                metrics=metrics,
                clock=clock,
            )
            managers[tag] = Manager(
                client=client,
                reconciler=reconciler,
                max_parallel=24,
                shard_coordinator=coord,
                goodput_interval=600.0,  # 50k-list rollups stay off-path
            )
            coords[tag], mets[tag] = coord, metrics

        def argo_player():
            from activemonitor_tpu.kube import ApiError, api_path

            async def play():
                done = set()
                while True:
                    for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                        name = wf["metadata"]["name"]
                        if name in done:
                            continue
                        try:
                            await player_api.merge_patch(
                                api_path(
                                    WF_GROUP, WF_VERSION, WF_PLURAL,
                                    wf["metadata"]["namespace"], name, "status",
                                ),
                                {"status": {"phase": "Succeeded"}},
                            )
                            done.add(name)
                        except ApiError:
                            continue
                    await asyncio.sleep(0.05)

            return asyncio.create_task(play())

        def run_totals():
            """(total recorded runs, workflows created) from the stub's
            store directly — the exactly-once ledger, no HTTP."""
            runs = 0
            for hc in server.objs(GROUP, VERSION, "healthchecks"):
                runs += ((hc.get("status") or {}).get("totalHealthCheckRuns") or 0)
            return runs, len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))

        player = argo_player()
        try:
            # start the fleet FIRST (empty store: boot resync is a
            # no-op), then bulk-seed and resync by hand — the stub's
            # bulk path skips per-object broadcast, so 150k synthetic
            # watch events don't dominate the soak's wall clock
            await asyncio.gather(*(m.start() for m in managers.values()))
            server.bulk_seed(GROUP, VERSION, "healthchecks", objs)
            for manager in managers.values():
                for hc in await manager.client.list():
                    manager.enqueue(hc.metadata.namespace, hc.metadata.name)

            # drain the 50k-key resync (workers run in real time; only
            # the workflow polls need fake-clock pacing)
            for _ in range(2400):
                if all(m._queue.qsize() == 0 for m in managers.values()):
                    break
                await asyncio.sleep(0.25)
            assert all(m._queue.qsize() == 0 for m in managers.values())

            seeded_runs = N_SHARD_SOAK - OWED_BOOT  # pre-seeded history

            async def boot_batch_done():
                runs, workflows = run_totals()
                return (
                    runs >= seeded_runs + OWED_BOOT
                    and workflows >= OWED_BOOT
                )

            await drive_until(clock, boot_batch_done, max_seconds=90)
            runs, workflows = run_totals()
            # exactly once: every owed-at-boot check ran, nothing else did
            assert workflows == OWED_BOOT, workflows
            assert runs == seeded_runs + OWED_BOOT, runs

            # every replica owns exactly its home shard, and the fleet
            # rollup's per-shard counts sum to the 50k total
            for idx, tag in enumerate("abc"):
                assert coords[tag].owned_shards() == [idx]
            payloads = []
            for tag in "abc":
                manager = managers[tag]
                payloads.append(
                    manager.reconciler.fleet.statusz(await manager.client.list())
                )
            rollup = rollup_statusz(payloads)
            assert rollup["fleet"]["checks"] == N_SHARD_SOAK
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
                == N_SHARD_SOAK
            )

            # ---- hard-kill replica b mid-cycle (before the t=120 owed
            # batch; its lease rots unreleased) ------------------------
            from tests.kube_harness import hard_kill_shards

            victim = managers["b"]
            for task in list(victim._tasks) + list(victim._requeue_tasks):
                task.cancel()
            hard_kill_shards(coords["b"])
            await victim.reconciler.shutdown()

            await drive_until(
                clock,
                lambda: asyncio.sleep(
                    0, 1 in coords["a"].set.owned or 1 in coords["c"].set.owned
                ),
                max_seconds=120,
            )
            adopter = "a" if 1 in coords["a"].set.owned else "c"
            # adoption resync re-queues the dead shard's keys; drain it
            for _ in range(2400):
                if managers[adopter]._queue.qsize() == 0:
                    break
                await asyncio.sleep(0.25)

            # ---- the t≈120 owed batch fires on the SURVIVORS only ----
            async def later_batch_done():
                runs, workflows = run_totals()
                return workflows >= OWED_BOOT + OWED_LATER

            await drive_until(clock, later_batch_done, max_seconds=300)
            # let in-flight status writes land
            for _ in range(40):
                runs, workflows = run_totals()
                if runs >= seeded_runs + OWED_BOOT + OWED_LATER:
                    break
                await advance(clock, 2.5)
            runs, workflows = run_totals()
            # THE exactly-once ledger: one workflow per owed fire, one
            # recorded run per workflow, zero spurious fires across
            # 50k checks and a mid-cycle owner kill
            assert workflows == OWED_BOOT + OWED_LATER, workflows
            assert runs == seeded_runs + OWED_BOOT + OWED_LATER, runs
            for i in range(OWED_BOOT + OWED_LATER, OWED_BOOT + OWED_LATER + 50):
                hc = server.obj(GROUP, VERSION, "healthchecks", "health", f"s50-{i:05d}")
                assert (hc["status"].get("totalHealthCheckRuns") or 0) == 1

            # ---- the fenced old owner's late status write ------------
            fenced_name = next(
                f"s50-{i:05d}"
                for i in range(N_SHARD_SOAK)
                if coords["b"].shard_for(f"health/s50-{i:05d}") == 1
            )
            seeder = KubernetesHealthCheckClient(apis["a"])
            stale = await seeder.get("health", fenced_name)
            stale.status.error_message = "stale split-brain write"
            await victim.reconciler._update_status(stale)
            fresh = await seeder.get("health", fenced_name)
            assert fresh.status.error_message != "stale split-brain write"
            assert (
                mets["b"].sample_value(
                    "healthcheck_shard_fenced_writes_total", {"shard": "1"}
                )
                == 1.0
            )

            # ---- rollup after handoff: counts still sum to 50k -------
            payloads = []
            for tag in ("a", "c"):
                manager = managers[tag]
                payloads.append(
                    manager.reconciler.fleet.statusz(await manager.client.list())
                )
            rollup = rollup_statusz(payloads)
            assert rollup["fleet"]["checks"] == N_SHARD_SOAK
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
                == N_SHARD_SOAK
            )
            assert set(rollup["fleet"]["sharding"]["owners"]) == {"0", "1", "2"}
        finally:
            player.cancel()
            for manager in managers.values():
                await manager.stop()
            for tag in ("b", "c"):
                await apis[tag].close()
            await player_api.close()


# -- front-door soak (ISSUE 15 acceptance, full-scale tier) ------------
#
# ≥10k requests/s of open-loop tenant traffic against the stub
# apiserver: duplicate questions coalesce onto ONE probe run per check
# per freshness window, admission latency stays bounded at p99, a
# throttled tenant's refusals are structured and counted, and the
# per-tenant conservation ledger stays exact through two storm phases
# (a miss-heavy one that triggers runs and a hit-heavy one served from
# the rings). The fast-tier slice of this scenario lives in
# tests/test_frontdoor.py; this is the throughput proof.

N_FD_CHECKS = 48
N_FD_REQUESTS = 30_000  # per storm phase (two phases measured together)
FD_FRESHNESS = 300.0  # seconds a ring result satisfies a request
FD_TENANTS = [f"fd-tenant-{i}" for i in range(8)] + ["fd-throttled"]


@pytest.mark.slow
@pytest.mark.asyncio
async def test_frontdoor_soak_10k_rps_against_the_stub_apiserver():
    import time as _time

    from activemonitor_tpu import GROUP, VERSION
    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )
    from activemonitor_tpu.engine.argo import (
        WF_GROUP,
        WF_PLURAL,
        WF_VERSION,
        ArgoWorkflowEngine,
    )
    from activemonitor_tpu.frontdoor import (
        AdmissionController,
        FrontDoor,
        OUTCOME_HIT,
        OUTCOME_JOINED,
        OUTCOME_REFUSED,
        OUTCOME_RUN,
        REFUSE_QUOTA,
        TenantQuota,
        open_loop_checks,
    )
    from activemonitor_tpu.kube import ApiError, api_path
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import advance, drive_until, stub_env

    async with stub_env() as (server, api):
        clock = FakeClock()
        objs = [
            {
                "apiVersion": f"{GROUP}/{VERSION}",
                "kind": "HealthCheck",
                "metadata": {"name": f"fd-{i:03d}", "namespace": "health"},
                "spec": {
                    "repeatAfterSec": 86_400,  # never due inside the soak
                    "level": "cluster",
                    "workflow": {
                        "generateName": f"fd-{i:03d}-",
                        "workflowtimeout": 300,
                        "resource": {
                            "namespace": "health",
                            "serviceAccount": "fd-sa",
                            "source": {"inline": WF_INLINE},
                        },
                    },
                },
            }
            for i in range(N_FD_CHECKS)
        ]
        metrics = MetricsCollector()
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=EventRecorder(capacity=5000),
            metrics=metrics,
            clock=clock,
        )
        door = FrontDoor(
            reconciler.fleet.history,
            AdmissionController(
                quotas={
                    "fd-throttled": TenantQuota(
                        rate_per_minute=60.0, burst=50.0
                    )
                },
                default_quota=TenantQuota(rate_per_minute=10**9),
                clock=clock,
            ),
            clock=clock,
            metrics=metrics,
            resilience=reconciler.resilience,
            default_freshness=FD_FRESHNESS,
        )
        manager = Manager(
            client=client,
            reconciler=reconciler,
            max_parallel=24,
            frontdoor=door,
            goodput_interval=600.0,
        )

        async def play():
            done = set()
            while True:
                for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                    name = wf["metadata"]["name"]
                    if name in done:
                        continue
                    try:
                        await api.merge_patch(
                            api_path(
                                WF_GROUP, WF_VERSION, WF_PLURAL,
                                wf["metadata"]["namespace"], name, "status",
                            ),
                            {"status": {"phase": "Succeeded"}},
                        )
                        done.add(name)
                    except ApiError:
                        continue
                await asyncio.sleep(0.05)

        def run_totals():
            runs = 0
            for hc in server.objs(GROUP, VERSION, "healthchecks"):
                runs += (
                    (hc.get("status") or {}).get("totalHealthCheckRuns") or 0
                )
            return runs, len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))

        player = asyncio.create_task(play())
        try:
            await manager.start()
            server.bulk_seed(GROUP, VERSION, "healthchecks", objs)
            for hc in await client.list():
                manager.enqueue(hc.metadata.namespace, hc.metadata.name)

            # boot: every never-ran check fires exactly once
            async def booted():
                runs, workflows = run_totals()
                return runs >= N_FD_CHECKS and workflows >= N_FD_CHECKS
            await drive_until(clock, booted, max_seconds=120)
            assert run_totals()[1] == N_FD_CHECKS

            # age the boot results out of the freshness window
            await advance(clock, FD_FRESHNESS + 100.0)

            storm = open_loop_checks(
                N_FD_REQUESTS,
                rate_rps=20_000.0,
                seed=1915,
                checks=[f"health/fd-{i:03d}" for i in range(N_FD_CHECKS)],
                tenants=FD_TENANTS,
            )

            def submit_storm():
                tickets, latencies = [], []
                for req in storm:
                    t0 = _time.perf_counter()
                    tickets.append(door.submit(req.tenant, req.check))
                    latencies.append(_time.perf_counter() - t0)
                return tickets, latencies

            # ---- phase A: miss-heavy (every check's first asker
            # triggers ONE demand-run; every duplicate fans in) --------
            wall_a0 = _time.perf_counter()
            tickets_a, lat_a = submit_storm()
            wall_a = _time.perf_counter() - wall_a0
            outcomes_a = [t.outcome for t in tickets_a]
            assert outcomes_a.count(OUTCOME_RUN) == N_FD_CHECKS
            assert outcomes_a.count(OUTCOME_JOINED) > 0
            # mid-storm the ledger is already exact, per tenant
            assert door.conservation()["ok"]

            # the 48 demanded runs complete through the normal
            # reconcile path against the stub apiserver
            async def phase_a_done():
                runs, workflows = run_totals()
                return workflows >= 2 * N_FD_CHECKS
            await drive_until(clock, phase_a_done, max_seconds=300)
            runs, workflows = run_totals()
            # exactly ONE workflow per check per storm — 30k requests
            # cost 48 runs, everything else coalesced
            assert workflows == 2 * N_FD_CHECKS, workflows
            for ticket in tickets_a:
                if ticket.outcome != OUTCOME_REFUSED:
                    assert await ticket.wait() is not None
            # every fanned-out waiter of one check shares its run's
            # trace id (joinable at /debug/traces)
            by_check = {}
            for ticket in tickets_a:
                if ticket.outcome in (OUTCOME_RUN, OUTCOME_JOINED):
                    by_check.setdefault(ticket.check, set()).add(
                        ticket.trace_id
                    )
            assert by_check and all(
                len(ids) == 1 for ids in by_check.values()
            )

            # ---- phase B: hit-heavy (fresh rings serve everything the
            # quota admits; zero new workflows) ------------------------
            wall_b0 = _time.perf_counter()
            tickets_b, lat_b = submit_storm()
            wall_b = _time.perf_counter() - wall_b0
            outcomes_b = [t.outcome for t in tickets_b]
            assert outcomes_b.count(OUTCOME_RUN) == 0
            assert outcomes_b.count(OUTCOME_HIT) > 0
            assert run_totals()[1] == 2 * N_FD_CHECKS  # no new runs

            # ---- the acceptance gates --------------------------------
            total = 2 * N_FD_REQUESTS
            measured_rps = total / (wall_a + wall_b)
            assert measured_rps >= 10_000, (
                f"front door sustained only {measured_rps:,.0f} req/s"
            )
            latencies = sorted(lat_a + lat_b)
            p99 = latencies[int(0.99 * len(latencies)) - 1]
            assert p99 < 0.005, f"admission p99 {p99 * 1e3:.2f}ms"
            ratios = door.coalesce_ratios()
            assert ratios["hit"] > 0  # coalescing under duplicate traffic
            assert ratios["join"] > 0
            # the throttled tenant was refused — structured and counted
            refused = door.admission.refused["fd-throttled"]
            assert refused.get(REFUSE_QUOTA, 0) > 0
            assert (
                metrics.sample_value(
                    "healthcheck_frontdoor_refusals_total",
                    {"tenant": "fd-throttled", "reason": REFUSE_QUOTA},
                )
                == refused[REFUSE_QUOTA]
            )
            # exact per-tenant conservation across both phases
            conservation = door.conservation()
            assert conservation["ok"]
            assert conservation["submitted"] == total
            assert conservation["probe_runs"] == N_FD_CHECKS
            assert conservation["parked"] == 0
            per_tenant = conservation["tenants"]
            assert sum(r["submitted"] for r in per_tenant.values()) == total
            for tenant in FD_TENANTS:
                row = per_tenant[tenant]
                assert row["submitted"] == (
                    row["cache_hits"]
                    + row["coalesced_joins"]
                    + row["probe_runs"]
                    + row["parked"]
                    + row["refused_total"]
                ), tenant
            # the evidence surfaces: /statusz fleet block agrees
            payload = reconciler.fleet.statusz(await client.list())
            frontdoor = payload["fleet"]["frontdoor"]
            assert frontdoor["conservation_ok"] is True
            assert frontdoor["requests"]["submitted"] == total
        finally:
            player.cancel()
            await asyncio.gather(player, return_exceptions=True)
            await manager.stop()


def _series_count(metrics: MetricsCollector) -> int:
    return sum(
        1
        for line in metrics.exposition().decode().splitlines()
        if line and not line.startswith("#")
    )


@pytest.mark.asyncio
async def test_soak_two_simulated_hours_bounded_resources():
    from activemonitor_tpu.utils.clock import FakeClock

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    for i in range(2, N_SOAK, 3):  # remedy checks' health workflows fail
        engine.on_prefix(f"soak-{i:03d}-", fail_after(1, f"soak-fail-{i:03d}"))
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(capacity=5000),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=10)
    await manager.start()

    async def settle(rounds: int = 40) -> None:
        for _ in range(rounds):
            await asyncio.sleep(0)

    async def run_sim(seconds: int) -> None:
        for _ in range(seconds // 60):
            await clock.advance(60)
            await settle()

    churn = [f"soak-{i:03d}" for i in range(0, 60, 3)]  # 20 interval checks
    try:
        await asyncio.gather(*(client.apply(make_soak_hc(i)) for i in range(N_SOAK)))
        await settle(80)

        await run_sim(1800)
        mid_cardinality = _series_count(metrics)
        # churn: delete a slice, let half an hour pass, re-apply the
        # SAME names (bounded label space), run out the clock
        for name in churn:
            await client.delete("health", name)
        await settle(80)
        for name in churn:
            assert not reconciler.timers.pending(f"health/{name}"), name
        await run_sim(1800)
        await asyncio.gather(
            *(client.apply(make_soak_hc(int(n.split("-")[1]))) for n in churn)
        )
        await settle(80)
        await run_sim(SIM_SECONDS - 3600)
        # drain in-flight watches: a few extra minutes of fake time
        for _ in range(10):
            if not any(t for t in reconciler._watch_tasks.values() if not t.done()):
                break
            await clock.advance(60)
            await settle()
        await reconciler.wait_watches()

        # -- run-count invariants per cadence --------------------------
        for i in range(N_SOAK):
            name = f"soak-{i:03d}"
            hc = await client.get("health", name)
            runs = hc.status.total_healthcheck_runs
            kind = i % 3
            if kind == 0 and name not in churn:
                # 600 s cadence over 7200 s: one run per period, the
                # ±1-period slack covering start/drain edges
                assert 9 <= runs <= 14, (name, runs)
            elif kind == 0:
                assert 5 <= runs <= 14, (name, runs)  # churn gap allowed
            elif kind == 1:
                # */15 cron: 8 fires in two hours (storm-aligned)
                assert 7 <= runs <= 11, (name, runs)
                assert hc.status.status == "Succeeded", name
            else:
                assert 7 <= runs <= 11, (name, runs)
                assert hc.status.failed_count == runs, (name, hc.status)
                # hysteresis: the limit counter CYCLES (reset → rerun),
                # so the durable invariant is total submissions — at
                # most 2 per 1800 s reset window, never 1:1 with the
                # 900 s failure cadence
                fixes = sum(
                    1
                    for wf in engine.submitted
                    if wf["metadata"]["generateName"] == f"soak-fix-{i:03d}-"
                )
                assert 3 <= fixes <= 8, (name, fixes)
                assert fixes < runs, (name, fixes, runs)
                assert hc.status.remedy_total_runs <= 2, name

        # -- resource-discipline invariants ----------------------------
        alive_watches = sum(
            1 for t in reconciler._watch_tasks.values() if not t.done()
        )
        assert alive_watches == 0
        assert len(reconciler._watch_tasks) <= 2 * N_SOAK
        pending_timers = sum(
            1
            for i in range(N_SOAK)
            if reconciler.timers.pending(f"health/soak-{i:03d}")
        )
        # every live check keeps exactly one next-run timer
        assert pending_timers == N_SOAK
        assert len(reconciler.timers._timers) <= 2 * N_SOAK + 10
        # cardinality: the second hour (with churn + re-apply of the
        # same names) must not have grown the series space
        end_cardinality = _series_count(metrics)
        assert end_cardinality <= mid_cardinality + 5, (
            mid_cardinality,
            end_cardinality,
        )
        # per-check series budget: 5 scrape names + the runtime
        # histogram's buckets/sum/count (~22 series per check observed)
        # + the critical-path gauge (8 stages x 3 quantiles = 24)
        assert end_cardinality <= 48 * N_SOAK + 200
        assert len(reconciler.recorder._events) <= 5000  # capacity holds
    finally:
        await manager.stop()


# -- federation soak (ISSUE 19) ----------------------------------------

N_FED_TENANTS = 24
N_FED_KEYS = 18
N_FED_ROUNDS = 12


@pytest.mark.slow
@pytest.mark.asyncio
async def test_federation_soak_three_clusters_conserve_exactly():
    """Slow-tier federation soak: three stub clusters take thousands of
    coalesced submissions across a dozen liveness windows while one
    cluster goes dark mid-soak and recovers. The invariants under
    volume: the global per-(tenant, cluster) ledger stays EXACT
    (``submitted == hits + joins + runs + parked + refused +
    forwarded``), each membership transition fires exactly one flight
    bundle, nothing ever lands on the unhealthy cluster while it is
    dark, and every resolved coalition shares its run's trace_id."""
    from activemonitor_tpu.federation import (
        FEDERATION_TENANT,
        STATE_HEALTHY,
        STATE_UNHEALTHY,
        CapabilityRouter,
        ClusterDescriptor,
        ClusterRegistry,
        GlobalFrontDoor,
        federation_quota,
    )
    from activemonitor_tpu.federation.registry import (
        KIND_CLUSTER_JOIN,
        KIND_CLUSTER_RECOVERED,
        KIND_CLUSTER_UNHEALTHY,
    )
    from activemonitor_tpu.frontdoor import (
        OUTCOME_REFUSED,
        REFUSE_QUOTA,
        AdmissionController,
        FrontDoor,
        TenantQuota,
    )
    from activemonitor_tpu.obs.flightrec import FlightRecorder
    from activemonitor_tpu.obs.history import ResultHistory
    from activemonitor_tpu.utils.clock import FakeClock

    clock = FakeClock()
    flightrec = FlightRecorder(clock)
    registry = ClusterRegistry(
        clock=clock, liveness_seconds=90.0, flightrec=flightrec
    )
    names = ("east", "west", "pod")
    kinds = {"east": "TPU v5e", "west": "TPU v5e", "pod": "TPU v5p"}
    for name in names:
        registry.join(
            ClusterDescriptor.build(name, device_kind=kinds[name])
        )
    gdoor = GlobalFrontDoor(
        registry,
        CapabilityRouter(registry),
        AdmissionController(
            {"throttled": TenantQuota(rate_per_minute=0.5, burst=1.0)},
            default_quota=TenantQuota(rate_per_minute=10**9),
            clock=clock,
        ),
        clock=clock,
    )
    doors, histories, triggered = {}, {}, {}
    for name in names:
        history = ResultHistory(clock)
        door = FrontDoor(
            history,
            AdmissionController(
                {FEDERATION_TENANT: federation_quota()}, clock=clock
            ),
            clock=clock,
        )
        probes = []
        door.bind(lambda ns, hc, _p=probes: _p.append(f"{ns}/{hc}"))
        gdoor.attach(name, door)
        doors[name], histories[name], triggered[name] = door, history, probes

    keys = [f"soak/hc-{k:02d}" for k in range(N_FED_KEYS)]
    tickets = []
    throttled = []
    seen = {name: 0 for name in names}
    stamp = 0.0
    for round_no in range(N_FED_ROUNDS):
        # movement polls: "pod" freezes for rounds 4..7 (dark for >3
        # liveness windows), then starts moving again
        stamp += 1.0
        for name in names:
            if name == "pod" and 4 <= round_no < 8:
                continue
            registry.observe(
                name, {"fleet": {"generated_at": stamp, "replicas": 1}}
            )
        registry.sweep()
        dark = registry.state("pod") == STATE_UNHEALTHY
        round_tickets = []
        for key in keys:
            for i in range(N_FED_TENANTS):
                round_tickets.append(gdoor.submit(f"tenant-{i:02d}", key))
        throttled.append(gdoor.submit("throttled", keys[0]))
        if dark:
            assert all(t.cluster != "pod" for t in round_tickets)
        # resolve every probe the round triggered; a coalition's
        # joiners must all surface their run's trace_id
        for name in names:
            fresh = triggered[name][seen[name] :]
            seen[name] = len(triggered[name])
            for key in sorted(set(fresh)):
                histories[name].record(
                    key,
                    ok=True,
                    latency=1.0,
                    workflow=f"wf-{round_no}",
                    trace_id=f"tr-{round_no}-{name}-{key}",
                )
        results = await asyncio.gather(*(t.wait() for t in round_tickets))
        by_key = {}
        for t, r in zip(round_tickets, results):
            if t.outcome == OUTCOME_REFUSED:
                continue
            assert r is not None, (t.outcome, t.check)
            by_key.setdefault((t.cluster, t.check), set()).add(r.trace_id)
        for coalition, traces in by_key.items():
            assert len(traces) == 1, coalition  # one shared trace each
        tickets.extend(round_tickets)
        await clock.advance(30.0)

    # membership transitions: one bundle per join, ONE unhealthy and
    # ONE recovery for "pod" despite many sweeps past the window
    assert registry.state("pod") == STATE_HEALTHY
    assert len(flightrec.bundles(kind=KIND_CLUSTER_JOIN)) == 3
    assert len(flightrec.bundles(kind=KIND_CLUSTER_UNHEALTHY)) == 1
    assert len(flightrec.bundles(kind=KIND_CLUSTER_RECOVERED)) == 1

    # the throttled tenant burned its burst then got structured
    # quota refusals, all booked pre-admission
    refused = [t for t in throttled if t.outcome == OUTCOME_REFUSED]
    assert len(refused) >= N_FED_ROUNDS - 8
    assert {t.reason for t in refused} == {REFUSE_QUOTA}

    # the global ledger is exact at volume, per tenant per cluster
    conservation = gdoor.conservation()
    assert conservation["ok"], conservation
    total = N_FED_ROUNDS * (N_FED_TENANTS * N_FED_KEYS + 1)
    assert conservation["submitted"] == total
    assert len(tickets) + len(throttled) == total
    per_cluster = gdoor.snapshot()["per_cluster"]
    booked = sum(
        cell["submitted"]
        for cell in per_cluster.values()
    )
    assert booked == total
    # the fan-in held: at most one probe run per (round, key) coalition
    # — never one per tenant — and round one ran every key exactly once
    runs = sum(cell["probe_runs"] for cell in per_cluster.values())
    assert N_FED_KEYS <= runs <= N_FED_ROUNDS * N_FED_KEYS
