"""In-process stub Kubernetes API server.

The reference's integration tier runs a real kube-apiserver via envtest
(reference: internal/controllers/suite_test.go:67-134) — the data model
is real, no controllers run. This module is that tier for this
framework: a generic aiohttp server speaking enough of the Kubernetes
REST dialect for every cluster-mode component to run against it for
real — CRUD + generateName, resourceVersion conflict semantics, the
status subresource, JSON merge patch, list + streaming watch, and
optional bearer-token auth. Resource-agnostic by design: HealthChecks,
Argo Workflows, RBAC objects, Leases and Events all flow through the
same store, like an API server with ``x-kubernetes-preserve-unknown-
fields`` CRDs installed (the reference's trick for Argo Workflows,
config/crd/bases/argoproj_v1alpha1_workflows.yaml).
"""

from __future__ import annotations

import asyncio
import copy
import json
import re
import secrets
from typing import Dict, List, Tuple

Key = Tuple[str, str, str]  # (group, version, plural); core v1 -> ("", "v1", ...)


def _match_selector(obj: dict, selector: str) -> bool:
    """Equality-based labelSelector (``k=v,k2=v2``) — the subset the
    framework's clients use."""
    if not selector:
        return True
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        k, _, v = clause.partition("=")
        if labels.get(k) != v:
            return False
    return True


# canonical MicroTime wire form: RFC3339 with EXACTLY six fractional
# digits (what client-go always writes; docs/conformance.md "strict
# field-format parsing"). Old apiservers rejected anything else with a
# 400 decode error — the stub plays the strict parser so the leniency
# of current apimachinery can't hide a non-canonical writer.
_MICRO_TIME_RE = re.compile(
    # \Z, not $: '$' would accept a trailing newline, which a real
    # strict parser rejects
    r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{6}Z\Z"
)


def _lease_decode_error(key: Key, obj: dict):
    if key != ("coordination.k8s.io", "v1", "leases"):
        return None
    spec = obj.get("spec") or {}
    for field in ("acquireTime", "renewTime"):
        value = spec.get(field)
        if value is not None and not _MICRO_TIME_RE.match(str(value)):
            return (
                f'Lease in version "v1" cannot be handled as a Lease: '
                f'v1.LeaseSpec.{field}: unmarshalerDecoder: parsing time '
                f'"{value}" as RFC3339Micro: non-canonical MicroTime'
            )
    return None


def _json_type(value) -> str:
    """The JSON type name apiserver error messages use."""
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, int):
        return "integer"
    if isinstance(value, float):
        return "number"
    if isinstance(value, str):
        return "string"
    if isinstance(value, list):
        return "array"
    if isinstance(value, dict):
        return "object"
    return "null"


def _prune_unknown(value, schema: dict):
    """Structural-schema pruning, the apiserver's decode-time behavior
    for CRDs without ``x-kubernetes-preserve-unknown-fields``: unknown
    object keys are silently DROPPED before validation or storage — a
    writer relying on an unschema'd field loses it, which is exactly
    the drift this models. Untyped objects (no ``properties``, e.g.
    ObjectMeta or free-form maps) keep everything."""
    if schema.get("x-kubernetes-preserve-unknown-fields"):
        return value
    if isinstance(value, dict):
        props = schema.get("properties")
        if props is None:
            return value
        return {
            k: _prune_unknown(v, props[k])
            for k, v in value.items()
            if k in props
        }
    if isinstance(value, list) and "items" in schema:
        return [_prune_unknown(v, schema["items"]) for v in value]
    return value


def _validate_openapi(value, schema: dict, path: str, causes: list) -> None:
    """Structural-schema subset of apiserver CRD validation: type,
    required, enum, properties/items recursion. Renders causes in the
    real wire shape ({reason, message, field}) so the 422 the stub
    returns matches the machine format fixtures pin
    (tests/fixtures/apiserver/invalid_422.json). Unknown fields never
    reach this validator — ``_prune_unknown`` drops them first, like
    the real decode path — and ``metadata`` is skipped at the root:
    the real apiserver validates ObjectMeta separately from the CRD
    schema."""
    expected = schema.get("type")
    if expected:
        actual = _json_type(value)
        if actual != expected and not (
            expected == "number" and actual == "integer"
        ):
            causes.append(
                {
                    "reason": "FieldValueInvalid",
                    "message": (
                        f'Invalid value: "{actual}": {path or "body"} in '
                        f'body must be of type {expected}: "{actual}"'
                    ),
                    "field": path or "<root>",
                }
            )
            return  # children of a mistyped node can't be checked
    if "enum" in schema and value not in schema["enum"]:
        supported = ", ".join(f'"{v}"' for v in schema["enum"])
        causes.append(
            {
                "reason": "FieldValueNotSupported",
                "message": (
                    f'Unsupported value: "{value}": supported values: '
                    f"{supported}"
                ),
                "field": path or "<root>",
            }
        )
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for req in schema.get("required") or []:
            if req not in value:
                causes.append(
                    {
                        "reason": "FieldValueRequired",
                        "message": "Required value",
                        "field": f"{path}.{req}" if path else req,
                    }
                )
        for k, v in value.items():
            if not path and k == "metadata":
                continue
            if k in props:
                _validate_openapi(
                    v, props[k], f"{path}.{k}" if path else k, causes
                )
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            _validate_openapi(item, schema["items"], f"{path}[{i}]", causes)


def merge_patch(target, patch):
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    result = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = merge_patch(result.get(k), v)
    return result


class StubApiServer:
    """Start with :meth:`start`, point a :class:`KubeApi` at ``.url``."""

    def __init__(self, token: str = ""):
        self._token = token
        self._objects: Dict[Key, Dict[Tuple[str, str], dict]] = {}
        self._rv = 0
        # bounded event history for watch resume; (rv, key, event)
        self._history: List[Tuple[int, Key, str, dict]] = []
        self._watchers: List[dict] = []
        self._runner = None
        self.url = ""
        self.requests: List[Tuple[str, str]] = []  # (method, path) log
        # every watch connection's query params, for tests asserting
        # resume behavior (which resourceVersion a reconnect carried)
        self.watch_params: List[dict] = []
        # schema registry: key -> (Kind, openAPIV3Schema). Registered
        # resources get real server-side 422 validation (see
        # register_crd); unregistered ones stay schemaless, like CRDs
        # with x-kubernetes-preserve-unknown-fields
        self._schemas: Dict[Key, Tuple[str, dict]] = {}
        self._kinds: Dict[Key, str] = {}  # last-seen kind per resource
        # watch BOOKMARK cadence for clients that sent
        # allowWatchBookmarks=true (real apiservers send them about
        # once a minute; tests shrink this to exercise the path)
        self.bookmark_interval = 60.0
        # chaos injection (see inject_fault / drop_watches / latency)
        self.faults: List[dict] = []
        self.latency = 0.0
        # TokenReview/SubjectAccessReview tables (kube-native scrape
        # auth tests): token -> username it authenticates as, and the
        # set of usernames allowed to GET non-resource /metrics
        self.scrape_tokens: Dict[str, str] = {}
        self.metrics_allowed_users: set = set()

    # -- store ----------------------------------------------------------
    def _bump(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _bucket(self, key: Key) -> Dict[Tuple[str, str], dict]:
        return self._objects.setdefault(key, {})

    def _broadcast(self, key: Key, namespace: str, type_: str, obj: dict) -> None:
        event = {"type": type_, "object": copy.deepcopy(obj)}
        self._history.append((self._rv, key, namespace, event))
        del self._history[:-1000]
        for w in self._watchers:
            if (
                w["key"] == key
                and (not w["namespace"] or w["namespace"] == namespace)
                and _match_selector(obj, w["selector"])
            ):
                w["queue"].put_nowait(event)

    # test-visible accessors -------------------------------------------
    def obj(self, group: str, version: str, plural: str, namespace: str, name: str):
        return self._bucket((group, version, plural)).get((namespace, name))

    def objs(self, group: str, version: str, plural: str) -> List[dict]:
        return list(self._bucket((group, version, plural)).values())

    def seed(self, group: str, version: str, plural: str, obj: dict) -> dict:
        """Directly place an object (test fixture setup)."""
        meta = obj.setdefault("metadata", {})
        meta.setdefault("resourceVersion", self._bump())
        meta.setdefault("uid", secrets.token_hex(8))
        key = (group, version, plural)
        if obj.get("kind"):
            self._kinds.setdefault(key, obj["kind"])
        namespace = meta.get("namespace", "")
        self._bucket(key)[(namespace, meta["name"])] = obj
        self._broadcast(key, namespace, "ADDED", obj)
        return obj

    def bulk_seed(self, group: str, version: str, plural: str, objs) -> int:
        """Fixture-scale seeding for soak tiers (50k+ objects): place
        many objects WITHOUT per-object watch broadcast or history —
        a fleet seeded before any client connects doesn't need 50k
        ADDED events queued per watcher, and the bounded event history
        would evict them all anyway. Clients started afterwards see the
        objects through list() / the no-resourceVersion watch replay,
        exactly like state that predates the controller. Returns the
        count seeded."""
        key = (group, version, plural)
        bucket = self._bucket(key)
        count = 0
        for obj in objs:
            meta = obj.setdefault("metadata", {})
            meta.setdefault("resourceVersion", self._bump())
            meta.setdefault("uid", secrets.token_hex(8))
            if obj.get("kind"):
                self._kinds.setdefault(key, obj["kind"])
            bucket[(meta.get("namespace", ""), meta["name"])] = obj
            count += 1
        return count

    # -- schema validation ----------------------------------------------
    def register_schema(
        self, group: str, version: str, plural: str, kind: str, schema: dict
    ) -> None:
        """Turn on server-side 422 validation for one resource. The
        schema is an openAPIV3Schema dict (what a CRD manifest carries);
        creates and updates of this resource are validated and rejected
        with a real ``Invalid`` Status carrying ``details.causes``, the
        way a real apiserver enforces structural CRD schemas."""
        key = (group, version, plural)
        self._schemas[key] = (kind, schema)
        self._kinds[key] = kind

    def register_crd(self, crd: dict) -> None:
        """Install a CRD manifest (e.g. ``api.crd.build_crd()``):
        registers the served version's schema for validation."""
        spec = crd["spec"]
        group = spec["group"]
        plural = spec["names"]["plural"]
        kind = spec["names"]["kind"]
        for version in spec["versions"]:
            schema = (version.get("schema") or {}).get("openAPIV3Schema")
            if schema:
                self.register_schema(
                    group, version["name"], plural, kind, schema
                )

    def _invalid(self, key: Key, name: str, causes: List[dict]):
        """422 Invalid the way apimachinery's NewInvalid renders it:
        message aggregates every cause (bracketed when more than one),
        details.kind is the KIND (unlike NotFound's resource)."""
        kind = self._schemas[key][0]
        group = key[0]
        qualified = f"{kind}.{group}" if group else kind
        parts = [f"{c['field']}: {c['message']}" for c in causes]
        agg = parts[0] if len(parts) == 1 else "[" + ", ".join(parts) + "]"
        return self._error(
            422,
            f'{qualified} "{name}" is invalid: {agg}',
            reason="Invalid",
            details={
                "name": name,
                "group": group,
                "kind": kind,
                "causes": causes,
            },
        )

    def _schema_causes(self, key: Key, obj: dict) -> List[dict]:
        entry = self._schemas.get(key)
        if entry is None:
            return []
        causes: List[dict] = []
        _validate_openapi(obj, entry[1], "", causes)
        return causes

    # -- chaos injection (the fault-injection tier: SURVEY.md §5.3) ----
    def inject_fault(
        self,
        path_substr: str,
        *,
        status: int = 500,
        times: int = 1,
        method: str = "",
    ) -> None:
        """The next ``times`` requests whose path contains
        ``path_substr`` (and match ``method``, if given) fail with
        ``status``. Faults are consumed in registration order."""
        self.faults.append(
            {
                "path_substr": path_substr,
                "status": status,
                "remaining": times,
                "method": method.upper(),
            }
        )

    def _consume_fault(self, request):
        for fault in self.faults:
            if fault["remaining"] <= 0:
                continue
            if fault["method"] and fault["method"] != request.method:
                continue
            if fault["path_substr"] not in request.path:
                continue
            fault["remaining"] -= 1
            return self._error(
                fault["status"], f"chaos: injected {fault['status']}"
            )
        return None

    def drop_watches(self) -> int:
        """Abruptly end every live watch stream (the client sees EOF and
        must reconnect). Returns how many streams were dropped."""
        dropped = 0
        for w in list(self._watchers):
            w["queue"].put_nowait(None)  # sentinel: close the stream
            dropped += 1
        return dropped

    def live_watch_count(self) -> int:
        """How many watch streams are connected right now — the public
        face of the watcher list for boundedness assertions (tests must
        not reach into ``_watchers``)."""
        return len(self._watchers)

    def emit_bookmarks(self) -> int:
        """Push an immediate BOOKMARK to every live watch that asked
        for them (``allowWatchBookmarks=true``) — the on-demand
        counterpart of the interval cadence, so tests can exercise the
        client's bookmark-resume path without waiting."""
        sent = 0
        for w in self._watchers:
            if w["bookmarks"]:
                # render NOW, not at dequeue: events already queued
                # behind this bookmark must not be covered by its RV
                # (a resume from the bookmark would skip them forever)
                w["queue"].put_nowait(self._bookmark_event(w["key"]))
                sent += 1
        return sent

    def _bookmark_event(self, key: Key) -> dict:
        """Metadata-only progress event: just the resume RV, shaped
        like the real wire (fixture watch_stream's BOOKMARK entry)."""
        group, version, _plural = key
        kind = self._kinds.get(key, "Object")
        return {
            "type": "BOOKMARK",
            "object": {
                "apiVersion": f"{group}/{version}" if group else version,
                "kind": kind,
                "metadata": {
                    "resourceVersion": str(self._rv),
                    "creationTimestamp": None,
                },
            },
        }

    # -- lifecycle ------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        from aiohttp import web

        # accept bodies up to what etcd would (default 1 MiB is too small)
        app = web.Application(
            middlewares=[self._auth_middleware], client_max_size=4 * 1024**2
        )
        # longest patterns first: aiohttp resolves dynamic routes in
        # registration order, and /apis/{g}/{v}/{plural}/{name} would
        # otherwise swallow /apis/{g}/{v}/namespaces/{ns}/{plural}
        patterns = [
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}/status", True),
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}", False),
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}", None),
            ("/apis/{group}/{version}/{plural}/{name}/status", True),
            ("/apis/{group}/{version}/{plural}/{name}", False),
            ("/apis/{group}/{version}/{plural}", None),
            ("/api/v1/namespaces/{namespace}/{plural}/{name}", False),
            ("/api/v1/namespaces/{namespace}/{plural}", None),
            ("/api/v1/{plural}/{name}", False),
            ("/api/v1/{plural}", None),
        ]
        for pattern, status_sub in patterns:
            if status_sub is None:  # collection
                app.router.add_get(pattern, self._handle_list_or_watch)
                app.router.add_post(pattern, self._handle_create)
            else:
                handler = self._handle_status if status_sub else self._handle_object
                app.router.add_get(pattern, handler)
                app.router.add_put(pattern, handler)
                app.router.add_patch(pattern, handler)
                if not status_sub:
                    app.router.add_delete(pattern, handler)
        # don't wait out live watch streams on cleanup (default 60 s)
        self._runner = web.AppRunner(app, shutdown_timeout=0.25)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual_port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://{host}:{actual_port}"
        return self.url

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- request plumbing ----------------------------------------------
    @staticmethod
    def _parse(request) -> Tuple[Key, str, str]:
        info = request.match_info
        group = info.get("group", "")
        version = info.get("version", "v1")
        return (
            (group, version, info["plural"]),
            info.get("namespace", ""),
            info.get("name", ""),
        )

    # default StatusReason per HTTP code, mirroring apimachinery's
    # reasonAndCodeForError mapping — the conformance fixtures
    # (tests/fixtures/apiserver/) pin these against the real wire shape
    _REASONS = {
        400: "BadRequest",
        401: "Unauthorized",
        403: "Forbidden",
        404: "NotFound",
        405: "MethodNotAllowed",
        409: "Conflict",
        410: "Expired",
        422: "Invalid",
        500: "InternalError",
        503: "ServiceUnavailable",
    }

    @staticmethod
    def _qualified(key: Key) -> str:
        """Resource rendering in real Status messages: grouped resources
        as ``plural.group``, core (empty-group) resources as bare
        ``plural`` — never a trailing dot."""
        return f"{key[2]}.{key[0]}" if key[0] else key[2]

    @classmethod
    def _status_body(
        cls, status: int, message: str, reason: str = "", details: dict | None = None
    ) -> dict:
        body = {
            "kind": "Status",
            "apiVersion": "v1",
            "metadata": {},
            "status": "Failure",
            "message": message,
            "reason": reason or cls._REASONS.get(status, ""),
            "code": status,
        }
        if details:
            body["details"] = details
        return body

    @classmethod
    def _error(
        cls, status: int, message: str, reason: str = "", details: dict | None = None
    ):
        from aiohttp import web

        return web.json_response(
            cls._status_body(status, message, reason, details), status=status
        )

    from aiohttp import web as _web  # for the middleware decorator

    @_web.middleware
    async def _auth_middleware(self, request, handler):
        self.requests.append((request.method, request.path))
        if self._token:
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {self._token}":
                return self._error(401, "Unauthorized")
        if self.latency:
            await asyncio.sleep(self.latency)
        injected = self._consume_fault(request)
        if injected is not None:
            return injected
        return await handler(request)

    # -- handlers -------------------------------------------------------
    async def _handle_list_or_watch(self, request):
        from aiohttp import web

        key, namespace, _ = self._parse(request)
        if request.query.get("watch") == "true":
            return await self._serve_watch(request, key, namespace)
        selector = request.query.get("labelSelector", "")
        items = [
            copy.deepcopy(obj)
            for (ns, _), obj in self._bucket(key).items()
            if (not namespace or ns == namespace)
            and _match_selector(obj, selector)
        ]
        return web.json_response(
            {
                "kind": "List",
                "items": items,
                "metadata": {"resourceVersion": str(self._rv)},
            }
        )

    async def _serve_watch(self, request, key: Key, namespace: str):
        from aiohttp import web

        self.watch_params.append(dict(request.query))
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()

        selector = request.query.get("labelSelector", "")
        start_rv = request.query.get("resourceVersion", "")
        bookmarks = request.query.get("allowWatchBookmarks") == "true"
        if start_rv:
            oldest = self._history[0][0] if self._history else self._rv + 1
            if int(start_rv) + 1 < oldest and int(start_rv) < self._rv:
                # requested window already evicted — real apiserver
                # sends an ERROR event whose object is a full Status
                # with reason Expired
                line = json.dumps(
                    {
                        "type": "ERROR",
                        "object": self._status_body(
                            410,
                            f"too old resource version: {start_rv} ({self._rv})",
                            reason="Expired",
                        ),
                    }
                )
                await resp.write(line.encode() + b"\n")
                return resp
            backlog = [
                ev
                for rv, k, ns, ev in self._history
                if k == key
                and (not namespace or ns == namespace)
                and rv > int(start_rv)
                and _match_selector(ev.get("object", {}), selector)
            ]
        else:
            # no resourceVersion: synthesize ADDED for current state
            backlog = [
                {"type": "ADDED", "object": copy.deepcopy(obj)}
                for (ns, _), obj in self._bucket(key).items()
                if (not namespace or ns == namespace)
                and _match_selector(obj, selector)
            ]
        entry = {
            "key": key,
            "namespace": namespace,
            "selector": selector,
            "queue": queue,
            "bookmarks": bookmarks,
        }
        self._watchers.append(entry)
        try:
            for ev in backlog:
                await resp.write(json.dumps(ev).encode() + b"\n")
            timeout = float(request.query.get("timeoutSeconds", "300"))
            loop = asyncio.get_event_loop()
            deadline = loop.time() + timeout
            next_bookmark = (
                loop.time() + self.bookmark_interval
                if bookmarks and self.bookmark_interval > 0
                else None
            )
            while True:
                now = loop.time()
                remaining = deadline - now
                if remaining <= 0:
                    break
                wait = remaining
                if next_bookmark is not None:
                    wait = min(wait, max(next_bookmark - now, 0.0))
                try:
                    ev = await asyncio.wait_for(
                        queue.get(), timeout=wait
                    )
                except asyncio.TimeoutError:
                    if (
                        next_bookmark is not None
                        and loop.time() >= next_bookmark
                    ):
                        # queue is empty here (the wait timed out), so
                        # a bookmark at the CURRENT rv covers nothing
                        # undelivered on this stream
                        ev = self._bookmark_event(key)
                        next_bookmark = loop.time() + self.bookmark_interval
                    else:
                        break  # server-side timeoutSeconds elapsed
                if ev is None:  # drop_watches sentinel: abrupt stream end
                    break
                await resp.write(json.dumps(ev).encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self._watchers.remove(entry)
        return resp

    async def _handle_create(self, request):
        from aiohttp import web

        key, namespace, _ = self._parse(request)
        body = await request.json()
        if key[2] in ("tokenreviews", "subjectaccessreviews"):
            # review APIs evaluate and answer — nothing is stored
            return web.json_response(self._evaluate_review(key[2], body), status=201)
        meta = body.setdefault("metadata", {})
        if namespace:
            meta["namespace"] = namespace
        name = meta.get("name", "")
        if not name:
            generate = meta.get("generateName")
            if not generate:
                return self._error(422, "name or generateName is required")
            name = generate + secrets.token_hex(3)[:5]
            meta["name"] = name
        if body.get("kind"):
            self._kinds.setdefault(key, body["kind"])
        decode_err = _lease_decode_error(key, body)
        if decode_err:
            return self._error(400, decode_err)
        entry = self._schemas.get(key)
        if entry is not None:
            # pruning precedes validation, like the real decode path
            body = _prune_unknown(body, entry[1])
            meta = body.setdefault("metadata", {})
        causes = self._schema_causes(key, body)
        if causes:
            # schema validation rejects before storage is consulted —
            # an invalid duplicate gets 422, not AlreadyExists
            return self._invalid(key, name, causes)
        if (namespace, name) in self._bucket(key):
            # real apiserver: 409 with reason AlreadyExists (distinct
            # from optimistic-concurrency Conflict at the same code)
            return self._error(
                409,
                f'{self._qualified(key)} "{name}" already exists',
                reason="AlreadyExists",
                details={"name": name, "group": key[0], "kind": key[2]},
            )
        meta["resourceVersion"] = self._bump()
        meta["uid"] = secrets.token_hex(8)
        meta.setdefault("creationTimestamp", _now_iso())
        self._bucket(key)[(namespace, name)] = body
        self._broadcast(key, namespace, "ADDED", body)
        return web.json_response(copy.deepcopy(body), status=201)

    def _cascade_delete(self, owner_uid: str) -> None:
        for key, bucket in list(self._objects.items()):
            for (ns, name), obj in list(bucket.items()):
                if (ns, name) not in bucket:
                    # already removed by a recursive cascade (an object
                    # may list several owners and be reachable twice)
                    continue
                refs = (obj.get("metadata") or {}).get("ownerReferences") or []
                if any(r.get("uid") == owner_uid for r in refs):
                    del bucket[(ns, name)]
                    self._bump()
                    self._broadcast(key, ns, "DELETED", obj)
                    child_uid = obj["metadata"].get("uid")
                    if child_uid:  # grandchildren cascade too
                        self._cascade_delete(child_uid)

    def _evaluate_review(self, plural: str, body: dict) -> dict:
        """The authentication/authorization review APIs, table-driven:
        ``scrape_tokens`` authenticates, ``metrics_allowed_users``
        authorizes GETs of the non-resource /metrics path."""
        spec = body.get("spec") or {}
        if plural == "tokenreviews":
            username = self.scrape_tokens.get(spec.get("token", ""))
            status = (
                {"authenticated": True, "user": {"username": username, "groups": []}}
                if username
                else {"authenticated": False}
            )
        else:
            attrs = spec.get("nonResourceAttributes") or {}
            status = {
                "allowed": (
                    spec.get("user", "") in self.metrics_allowed_users
                    and attrs.get("path") == "/metrics"
                    and attrs.get("verb") == "get"
                )
            }
        return {**body, "status": status}

    async def _handle_object(self, request):
        return await self._object_rw(request, status_only=False)

    async def _handle_status(self, request):
        if request.method == "GET":
            return self._error(405, "GET on status subresource not supported")
        return await self._object_rw(request, status_only=True)

    async def _object_rw(self, request, status_only: bool):
        from aiohttp import web

        key, namespace, name = self._parse(request)
        existing = self._bucket(key).get((namespace, name))
        if existing is None:
            return self._error(
                404,
                f'{self._qualified(key)} "{name}" not found',
                details={"name": name, "group": key[0], "kind": key[2]},
            )

        if request.method == "GET":
            return web.json_response(copy.deepcopy(existing))

        if request.method == "DELETE":
            del self._bucket(key)[(namespace, name)]
            self._bump()
            self._broadcast(key, namespace, "DELETED", existing)
            # ownerReference garbage collection, the real apiserver's
            # background cascade made synchronous: anything owned by
            # the deleted object's uid goes too (how a HealthCheck's
            # submitted Workflows disappear on HC delete — the
            # controller's None-workflow path expects exactly this)
            owner_uid = existing["metadata"].get("uid")
            if owner_uid:
                self._cascade_delete(owner_uid)
            return web.json_response(
                {
                    "kind": "Status",
                    "apiVersion": "v1",
                    "metadata": {},
                    "status": "Success",
                    "details": {
                        "name": name,
                        "group": key[0],
                        "kind": key[2],
                        "uid": existing["metadata"].get("uid", ""),
                    },
                }
            )

        body = await request.json()
        # optimistic concurrency: a stale resourceVersion in the payload
        # is a conflict (this is what RetryOnConflict paths exercise)
        claimed = (body.get("metadata") or {}).get("resourceVersion")
        if claimed and claimed != existing["metadata"]["resourceVersion"]:
            return self._error(
                409,
                f'Operation cannot be fulfilled on {self._qualified(key)} "{name}": '
                "the object has been modified; please apply your changes to "
                "the latest version and try again",
                reason="Conflict",
                details={"name": name, "group": key[0], "kind": key[2]},
            )

        if request.method == "PUT":
            updated = body
            if status_only:
                updated = copy.deepcopy(existing)
                updated["status"] = body.get("status")
            else:
                # status is a subresource: a main-resource replace never
                # touches it (real API-server behavior for CRDs with the
                # status subresource enabled)
                updated.pop("status", None)
                if "status" in existing:
                    updated["status"] = existing["status"]
        else:  # PATCH (JSON merge patch)
            patch = {"status": body.get("status")} if status_only else body
            updated = merge_patch(existing, patch)
        decode_err = _lease_decode_error(key, updated)
        if decode_err:
            return self._error(400, decode_err)
        entry = self._schemas.get(key)
        if entry is not None:
            updated = _prune_unknown(updated, entry[1])
        causes = self._schema_causes(key, updated)
        if causes:
            # updates are validated on the FULL post-merge object (the
            # real apiserver validates what would be stored, so a merge
            # patch cannot smuggle a schema-invalid field in)
            return self._invalid(key, name, causes)
        meta = updated.setdefault("metadata", {})
        meta["name"] = name
        if namespace:
            meta["namespace"] = namespace
        meta["uid"] = existing["metadata"].get("uid", secrets.token_hex(8))
        meta["resourceVersion"] = self._bump()
        self._bucket(key)[(namespace, name)] = updated
        self._broadcast(key, namespace, "MODIFIED", updated)
        return web.json_response(copy.deepcopy(updated))


def _now_iso() -> str:
    import datetime

    return (
        datetime.datetime.now(datetime.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )
