"""ArtifactReader protocol and dispatch (reference: internal/store/store.go:10-22)."""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from activemonitor_tpu.api.types import ArtifactLocation


class UnknownArtifactLocation(ValueError):
    """No reader exists for the given artifact location."""


@runtime_checkable
class ArtifactReader(Protocol):
    """Reads a workflow manifest from some source."""

    def read(self) -> bytes:  # pragma: no cover - protocol
        ...


def get_artifact_reader(loc: ArtifactLocation) -> ArtifactReader:
    """Return the reader for a location.

    Dispatch order matches the reference (inline, then URL;
    store/store.go:15-21) with file support added after, so existing
    specs resolve identically.
    """
    from activemonitor_tpu.store.file import FileReader
    from activemonitor_tpu.store.inline import InlineReader
    from activemonitor_tpu.store.url import URLReader

    if loc.inline is not None:
        return InlineReader(loc.inline)
    if loc.url is not None:
        return URLReader(loc.url)
    if loc.file is not None:
        return FileReader(loc.file)
    raise UnknownArtifactLocation(f"unknown artifact location: {loc!r}")
