"""hack/lint.py — the in-repo AST lint gate.

Each check must fire on a seeded example and stay quiet on the
idiomatic counter-example (the linter's leniency contract: a false
positive that makes `make lint` cry wolf is worse than a miss).
Reference analogue: golangci-lint gating CI
(/root/reference/.github/workflows/golangci-lint.yml).
"""

import importlib.util
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location("lint", REPO / "hack" / "lint.py")
lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lint)


def findings(tmp_path, source, name="mod.py"):
    path = tmp_path / name
    path.write_text(source)
    return lint.lint_file(path)


def codes(results):
    return {line.split(": ")[1] for line in results}


def test_unawaited_coroutine_fires(tmp_path):
    got = findings(
        tmp_path,
        "async def fetch():\n"
        "    return 1\n"
        "def schedule():\n"
        "    fetch()\n",
    )
    assert codes(got) == {"unawaited-coroutine"}


def test_unawaited_coroutine_quiet_when_awaited_or_wrapped(tmp_path):
    got = findings(
        tmp_path,
        "import asyncio\n"
        "async def fetch():\n"
        "    return 1\n"
        "async def main():\n"
        "    await fetch()\n"
        "    task = asyncio.create_task(fetch())\n"
        "    return task\n",
    )
    assert got == []


def test_unawaited_coroutine_quiet_on_sync_name_collision(tmp_path):
    # a sync def sharing the name anywhere in the file silences the
    # check — leniency beats a wrong accusation
    got = findings(
        tmp_path,
        "class A:\n"
        "    async def run(self):\n"
        "        return 1\n"
        "class B:\n"
        "    def run(self):\n"
        "        return 2\n"
        "def go(b):\n"
        "    b.run()\n",
    )
    assert got == []


def test_shadowed_builtin_fires_on_assign_param_and_def(tmp_path):
    got = findings(
        tmp_path,
        "list = [1]\n"
        "def handler(id):\n"
        "    type = 'x'\n"
        "    return id, type\n"
        "def sum():\n"
        "    return 0\n",
    )
    assert codes(got) == {"shadowed-builtin"}
    assert len(got) == 4  # list, id, type, sum


def test_shadowed_builtin_exempts_class_fields(tmp_path):
    # API models legitimately mirror builtin names as field names
    got = findings(
        tmp_path,
        "class Probe:\n"
        "    type: str = 'x'\n"
        "    id: int = 0\n",
    )
    assert got == []


def test_redefined_test_fires(tmp_path):
    got = findings(
        tmp_path,
        "def test_a():\n"
        "    assert True\n"
        "def test_a():\n"
        "    assert False\n",
        name="test_mod.py",
    )
    assert codes(got) == {"redefined-test"}


def test_redefined_test_quiet_on_distinct_scopes(tmp_path):
    got = findings(
        tmp_path,
        "class TestA:\n"
        "    def test_x(self):\n"
        "        pass\n"
        "class TestB:\n"
        "    def test_x(self):\n"
        "        pass\n",
        name="test_mod.py",
    )
    assert got == []


def test_unused_local_exempts_class_body_in_function(tmp_path):
    # attributes of a class DEFINED INSIDE a function are class members
    # (a common test-double idiom), not dead function locals
    got = findings(
        tmp_path,
        "def make_stub():\n"
        "    class Proc:\n"
        "        returncode = 0\n"
        "        stdout = b''\n"
        "    return Proc\n",
    )
    assert got == []


def test_undefined_name_and_unused_import_still_fire(tmp_path):
    got = findings(tmp_path, "import os\nprint(sys.argv)\n")
    assert codes(got) == {"undefined-name", "unused-import"}


def test_repo_tree_is_clean():
    """The gate the CI run enforces, as a test: every default target
    lints clean (mirrors `make lint`)."""
    assert lint.main([]) == 0


def test_seeded_file_exits_nonzero(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    return undefined_thing\n")
    assert lint.main([str(bad)]) == 1


def test_unreachable_code_fires_and_stays_quiet(tmp_path):
    got = findings(
        tmp_path,
        "def f(x):\n"
        "    return x\n"
        "    x += 1\n",
    )
    assert codes(got) == {"unreachable-code"}
    # early return inside a branch: everything after the if is live
    assert (
        findings(
            tmp_path,
            "def f(x):\n"
            "    if x:\n"
            "        return 0\n"
            "    return x + 1\n",
        )
        == []
    )


def test_unused_parameter_fires_on_plain_function(tmp_path):
    got = findings(
        tmp_path,
        "def f(a, b):\n"
        "    return a + 1\n",
    )
    assert codes(got) == {"unused-parameter"}


def test_unused_parameter_exemptions_hold(tmp_path):
    quiet = (
        # method: override signatures are contracts
        "class C:\n"
        "    def m(self, unused):\n"
        "        return 1\n"
        # decorated: callback contracts
        "import functools\n"
        "@functools.cache\n"
        "def g(unused):\n"
        "    return 2\n"
        # pytest fixture request by name
        "def test_thing(capsys):\n"
        "    assert True\n"
        # underscore convention
        "def h(_ignored, x):\n"
        "    return x\n"
        # closure consumes the parameter
        "def outer(cb):\n"
        "    def inner():\n"
        "        return cb()\n"
        "    return inner\n"
        # stub body
        "def stub(a, b):\n"
        "    raise NotImplementedError\n"
        # the canonical docstring-then-raise stub is exempt too
        "def stub2(a, b):\n"
        "    '''Interface contract.'''\n"
        "    raise NotImplementedError\n"
    )
    assert findings(tmp_path, quiet) == []


def test_event_reasons_come_from_declared_table():
    """Every EventRecorder.event() call site in the package must draw
    its reason (3rd argument) from events.EVENT_REASONS — the reference
    free-hands ~40 reason strings and dashboards grouping on reason
    break on the first typo. Literals are checked by value; names must
    be the declared REASON_*/EVENT_* constants. events.py itself is
    exempt (its recorder methods forward a `reason` parameter)."""
    import ast

    from activemonitor_tpu.controller import events as events_mod

    declared = events_mod.EVENT_REASONS
    const_names = {
        name
        for name in vars(events_mod)
        if name.startswith(("REASON_", "EVENT_"))
    }
    violations = []
    for path in sorted((REPO / "activemonitor_tpu").rglob("*.py")):
        if path.name == "events.py":
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "event"
                and len(node.args) + len(node.keywords) >= 4
            ):
                continue
            # the reason may arrive positionally (3rd arg) or as a
            # keyword — both forms must pass through the gate
            reason = node.args[2] if len(node.args) >= 3 else None
            for kw in node.keywords:
                if kw.arg == "reason":
                    reason = kw.value
            if reason is None:
                continue
            if isinstance(reason, ast.Constant) and isinstance(reason.value, str):
                if reason.value not in declared:
                    violations.append(
                        f"{path}:{node.lineno}: ad-hoc event reason "
                        f"{reason.value!r} (declare it in events.EVENT_REASONS)"
                    )
            elif isinstance(reason, ast.Name):
                if reason.id not in const_names:
                    violations.append(
                        f"{path}:{node.lineno}: event reason from "
                        f"undeclared name {reason.id!r}"
                    )
            else:
                violations.append(
                    f"{path}:{node.lineno}: event reason is a computed "
                    "expression — use a declared constant"
                )
    assert violations == []


def test_declared_metric_names_pass_the_sanitizer():
    """Every statically-declared Prometheus metric name in the package
    must already be in sanitized, exposition-legal form — a name the
    sanitizer would rewrite means the declared name and the scraped
    name silently diverge."""
    import ast
    import re

    from activemonitor_tpu.metrics.collector import _sanitize

    legal = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
    names = []
    for path in sorted((REPO / "activemonitor_tpu").rglob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in {"Gauge", "Counter", "Histogram", "Summary"}
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            names.append((path, node.lineno, node.args[0].value))
    # the static families must actually be found (a refactor that moves
    # them out of AST reach would hollow this gate out silently)
    assert len(names) >= 15
    for path, lineno, name in names:
        assert legal.match(name), f"{path}:{lineno}: illegal metric name {name!r}"
        assert _sanitize(name) == name, (
            f"{path}:{lineno}: metric name {name!r} is not in sanitized form"
        )


def test_collector_families_are_pinned_in_the_exposition_contract():
    """Every Gauge/Counter/Histogram/Summary constructed in
    metrics/collector.py must appear in tests/test_metrics.py's
    PINNED_FAMILIES table — a new family cannot ship without its scrape
    name being part of the exposition contract."""
    import ast

    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    pinned = set(contract.PINNED_FAMILIES)

    collector_path = REPO / "activemonitor_tpu" / "metrics" / "collector.py"
    tree = ast.parse(collector_path.read_text())
    declared = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in {"Gauge", "Counter", "Histogram", "Summary"}
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            declared.append((node.lineno, node.args[0].value))
    # the collector's static families must actually be found — an AST
    # refactor that hides them would hollow this gate out silently
    assert len(declared) >= 20
    unpinned = [
        f"collector.py:{lineno}: {name!r} not in PINNED_FAMILIES"
        for lineno, name in declared
        if name not in pinned
    ]
    assert unpinned == []
    # and the pin list carries no dead names the collector dropped
    declared_names = {name for _ln, name in declared}
    stale = pinned - declared_names
    assert stale == set(), f"PINNED_FAMILIES entries no longer declared: {stale}"


def test_wallclock_banned_in_resilience_package(tmp_path):
    """resilience/ runs entirely on the injectable Clock — breaker open
    windows and token-bucket refill must be scriptable by fake-clock
    tests, so a bare time.time()/time.monotonic() there is a lint
    error. The same code OUTSIDE resilience/ stays quiet."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    res_dir = tmp_path / "resilience"
    res_dir.mkdir()
    (res_dir / "mod.py").write_text(source)
    got = lint.lint_file(res_dir / "mod.py")
    assert {line.split(": ")[1] for line in got} == {"wallclock-in-resilience"}
    assert len(got) == 2  # both the time() and the monotonic() call
    # identical code outside resilience/: no finding
    assert findings(tmp_path, source) == []
    # clock-disciplined resilience code: no finding
    clean = (
        "def delay(clock):\n"
        "    return clock.monotonic() + 1.0\n"
    )
    (res_dir / "clean.py").write_text(clean)
    assert lint.lint_file(res_dir / "clean.py") == []


def test_resilience_package_really_is_wallclock_free():
    """The gate, applied: the shipped resilience/ package lints clean,
    and the ban actually covers its files (path-scoping regression
    guard)."""
    package = REPO / "activemonitor_tpu" / "resilience"
    files = sorted(package.rglob("*.py"))
    assert files, "resilience package missing?"
    for path in files:
        assert lint.lint_file(path) == []
        # the scope bit must be ON for these files — otherwise the
        # check above passed vacuously
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock


def test_resilience_metric_families_are_pinned():
    """The ISSUE-3 families must stay in the exposition contract — a
    rename breaks the degraded-mode alert every fleet dashboard leads
    with."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_resilience", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_controller_degraded",
        "healthcheck_check_state",
        "healthcheck_remedy_runs_total",
        "healthcheck_status_write_queue_depth",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_adaptive_module_rides_the_resilience_wallclock_ban():
    """resilience/adapt.py (ISSUE 18) must be covered by the path-keyed
    wall-clock ban — the adaptive controller's hysteresis streaks and
    episode `since` stamps ride the injected Clock, and the closed-loop
    chaos test scripts engage→release purely on a FakeClock. An
    accidental move out of resilience/ would silently drop the ban."""
    path = REPO / "activemonitor_tpu" / "resilience" / "adapt.py"
    assert path.exists(), "adaptive controller module missing?"
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "resilience"
    assert lint.lint_file(path) == []


def test_adaptive_metric_families_are_pinned():
    """The ISSUE-18 families must stay in the exposition contract — the
    adaptation runbook (docs/resilience.md "Adaptive control loop")
    alerts on lever engagement and the cadence factor; a rename
    silently blinds the operator to a controller that is actively
    reshaping the probe schedule."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_adaptive", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_adaptive_cadence_factor",
        "healthcheck_adaptive_lever_active",
        "healthcheck_adaptive_transitions_total",
        "healthcheck_adaptive_freshness_ceiling_seconds",
        "healthcheck_frontdoor_freshness_clamped_total",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_analysis_metric_families_are_pinned():
    """The ISSUE-4 families must stay in the exposition contract — a
    rename silently breaks baseline dashboards and anomaly alerts."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_analysis", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_metric_baseline",
        "healthcheck_metric_zscore",
        "healthcheck_anomaly_state",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_shard_metric_families_are_pinned():
    """The ISSUE-6 families must stay in the exposition contract — the
    fleet rollup dashboard sums healthcheck_shard_checks against the
    check total, and a rename silently breaks the handoff alert."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_sharding", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_shard_owned",
        "healthcheck_shard_checks",
        "healthcheck_shard_handoffs_total",
        "healthcheck_shard_fenced_writes_total",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_wallclock_banned_in_sharding_module(tmp_path):
    """controller/sharding.py runs entirely on the injectable Clock —
    lease expiry, fencing freshness windows, and shed cooldowns must be
    scriptable by fake-clock tests, so a bare time.time()/monotonic()
    there is a lint error (same ban as resilience/ and analysis/, keyed
    by MODULE name because sharding is a file, not a package)."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    (tmp_path / "sharding.py").write_text(source)
    got = lint.lint_file(tmp_path / "sharding.py")
    assert {line.split(": ")[1] for line in got} == {"wallclock-in-sharding"}
    assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="scheduling.py") == []


def test_sharding_module_really_is_wallclock_free():
    """The gate, applied: the shipped sharding module lints clean and
    the ban actually covers it (path-scoping regression guard, like the
    resilience/analysis twins)."""
    path = REPO / "activemonitor_tpu" / "controller" / "sharding.py"
    assert path.exists(), "sharding module missing?"
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "sharding"


def test_wallclock_banned_in_attribution_and_flightrec(tmp_path):
    """obs/attribution.py and obs/flightrec.py carry the injectable-
    Clock contract (ISSUE 7 satellite): attribution windows are judged
    on result timestamps and flight bundles are stamped on scripted
    transitions, so a bare wall-clock read there is a lint error —
    same module-name keying as the sharding ban."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    for module in ("attribution", "flightrec"):
        (tmp_path / f"{module}.py").write_text(source)
        got = lint.lint_file(tmp_path / f"{module}.py")
        assert {line.split(": ")[1] for line in got} == {
            f"wallclock-in-{module}"
        }, module
        assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="summarizer.py") == []


def test_attribution_and_flightrec_really_are_wallclock_free():
    """The gate, applied: the shipped modules lint clean and the ban
    covers them (path-scoping regression guard, like the sharding
    twin)."""
    for module in ("attribution", "flightrec"):
        path = REPO / "activemonitor_tpu" / "obs" / f"{module}.py"
        assert path.exists(), f"{module} module missing?"
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock
        assert checker.wallclock_pkg == module


def test_goodput_attribution_families_are_pinned():
    """The ISSUE-7 families must stay in the exposition contract — the
    conservation dashboard stacks healthcheck_goodput_lost_ratio under
    the fleet goodput line, and a rename silently breaks it."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_goodput", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_goodput_lost_ratio",
        "healthcheck_goodput_attribution_info",
        "healthcheck_phase_timings_skipped_total",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_wallclock_banned_in_analysis_package(tmp_path):
    """analysis/ baselines are stamped on the injectable Clock so
    fake-clock tests can script exact warm-up windows — the same
    wall-clock ban as resilience/, with the package in the code."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
    )
    ana_dir = tmp_path / "analysis"
    ana_dir.mkdir()
    (ana_dir / "mod.py").write_text(source)
    got = lint.lint_file(ana_dir / "mod.py")
    assert {line.split(": ")[1] for line in got} == {"wallclock-in-analysis"}


def test_analysis_package_really_is_wallclock_free():
    """The gate, applied to the shipped analysis/ package (path-scoping
    regression guard, like the resilience twin above)."""
    package = REPO / "activemonitor_tpu" / "analysis"
    files = sorted(package.rglob("*.py"))
    assert files, "analysis package missing?"
    for path in files:
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock


def test_overlap_metric_names_are_pinned():
    """The ISSUE-5 overlap-telemetry names are contract spelling: the
    probes emit them, docs/probes.md's metric table registers them (the
    names spec.analysis.metrics[] takes), and bench.py carries the
    secondary keys — a rename in any one layer silently orphans the
    others, so the gate pins all three."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    pinned_metrics = {
        "ring-overlap-efficiency": "probes/ring.py",
        "ring-attention-busbw-gbps": "probes/ring.py",
        "ring-attention-busbw-fraction-of-rated": "probes/ring.py",
        "ici-ring-hop-bidir-gbps": "probes/ici.py",
        "ici-ring-hop-fraction-of-rated": "probes/ici.py",
        "ici-ring-hop-bidir-fraction-of-rated": "probes/ici.py",
    }
    for name, rel in pinned_metrics.items():
        assert name in docs, f"{name} missing from docs/probes.md metric table"
        src = (REPO / "activemonitor_tpu" / rel).read_text()
        tree = ast.parse(src)
        declared = {
            node.value
            for node in ast.walk(tree)
            if isinstance(node, ast.Constant) and isinstance(node.value, str)
        }
        assert name in declared, f"{name} not declared in {rel}"
    # the bidirectional collective case is part of the sweep contract
    from activemonitor_tpu.probes.collectives import ALL_CASES, _BENCH

    assert "ringhop-bidir" in ALL_CASES
    assert "ringhop-bidir" in _BENCH
    assert "ringhop-bidir" in docs
    # bench.py's secondary keys for the overlap evidence
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "ring_overlap_efficiency",
        "ring_overlap_vs_serial_max_error",
        "ring_bidir_max_error_interpret",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"


def test_zoo_metric_names_are_pinned():
    """The ISSUE-8 collective-zoo/autotune names are contract spelling
    across three layers: the probes emit them, docs/probes.md's metric
    table registers them (the names spec.analysis.metrics[] takes),
    and bench.py stamps the autotune evidence block — a rename in any
    one layer silently orphans the others, so the gate pins all three
    (the same gate the ring-overlap metrics got)."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    pinned_metrics = {
        "collective-sweep-zoo-best-win": "probes/collectives.py",
        "collective-sweep-crossovers": "probes/collectives.py",
        "ici-allreduce-rsag-fraction-of-rated": "probes/ici.py",
        "ici-allreduce-recdouble-fraction-of-rated": "probes/ici.py",
        "ici-allreduce-tree-fraction-of-rated": "probes/ici.py",
        "ici-allreduce-rsag-busbw-gbps": "probes/ici.py",
        "ici-allreduce-recdouble-busbw-gbps": "probes/ici.py",
        "ici-allreduce-tree-busbw-gbps": "probes/ici.py",
    }
    for name, rel in pinned_metrics.items():
        assert name in docs, f"{name} missing from docs/probes.md metric table"
        src = (REPO / "activemonitor_tpu" / rel).read_text()
        tree = ast.parse(src)
        declared = {
            node.value
            for node in ast.walk(tree)
            if isinstance(node, ast.Constant) and isinstance(node.value, str)
        }
        assert name in declared, f"{name} not declared in {rel}"
    # the zoo cases are part of the collectives-probe sweep contract
    from activemonitor_tpu.probes.collectives import ZOO_CASES, _BENCH

    for case in (
        "allreduce-rsag", "allreduce-recdouble", "allreduce-tree",
        "allgather-ring", "allgather-recdouble",
    ):
        assert case in ZOO_CASES
        assert case in _BENCH
        assert case in docs, f"zoo case {case} missing from docs/probes.md"
    # the catalog section the metric table points at must exist
    training = (REPO / "docs" / "training.md").read_text()
    assert "Collective schedule catalog" in training
    assert "autotune_table" in training
    # bench.py's autotune evidence block (both TPU and CPU-fallback
    # paths stamp it; interpret-mode tables are labeled as such)
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "collective_autotune", "interpret_mode", "zoo_best_win", "crossovers",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"


def test_shard_map_import_banned_outside_partition(tmp_path):
    """ISSUE-10 one-sharding-surface pin: every direct shard_map import
    (legacy experimental home, modern jax export, or the in-tree compat
    adapter) is a lint error outside parallel/partition.py and
    utils/compat.py; the sanctioned partition import stays quiet."""
    for banned in (
        "from jax.experimental.shard_map import shard_map\n"
        "fn = shard_map\n",
        "import jax.experimental.shard_map\n"
        "fn = jax.experimental.shard_map.shard_map\n",
        "from jax import shard_map\n"
        "fn = shard_map\n",
        "from activemonitor_tpu.utils.compat import shard_map\n"
        "fn = shard_map\n",
    ):
        got = findings(tmp_path, banned)
        assert codes(got) == {"shard-map-outside-partition"}, banned
        # the two surface files are exempt — same code, no finding
        assert findings(tmp_path, banned, name="partition.py") == []
        assert findings(tmp_path, banned, name="compat.py") == []
    for quiet in (
        "from activemonitor_tpu.parallel.partition import shard_map\n"
        "fn = shard_map\n",
        # a third-party module merely NAMED *compat is not the adapter
        "from jax_compat import shard_map\n"
        "fn = shard_map\n",
    ):
        assert findings(tmp_path, quiet) == [], quiet


def test_shard_map_surface_really_is_one_file_pair():
    """The gate, applied: the shipped tree lints clean (covered by
    test_repo_tree_is_clean) AND the exemption bit is scoped to exactly
    the two surface files — so the clean run is not vacuous."""
    import ast

    for rel, allowed in (
        ("activemonitor_tpu/parallel/partition.py", True),
        ("activemonitor_tpu/utils/compat.py", True),
        ("activemonitor_tpu/ops/ring_attention.py", False),
        ("activemonitor_tpu/ops/pipeline.py", False),
        ("activemonitor_tpu/ops/moe.py", False),
        ("activemonitor_tpu/probes/training_step.py", False),
    ):
        path = REPO / rel
        src = path.read_text()
        checker = lint.Checker(str(path), ast.parse(src), src)
        assert checker.allow_shard_map is allowed, rel


def test_tuned_dispatch_metric_names_are_pinned():
    """The ISSUE-10 tuned-dispatch names are contract spelling across
    the layers: the training-step probe emits the metric and details,
    docs register the spellings, and bench.py stamps the evidence keys
    next to collective_autotune — a rename in any one layer silently
    orphans the others (same gate as the overlap/zoo/roofline names)."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    src = (REPO / "activemonitor_tpu" / "probes" / "training_step.py").read_text()
    declared = {
        node.value
        for node in ast.walk(ast.parse(src))
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }
    assert "training-step-allreduce-sched" in declared
    assert "training-step-allreduce-sched" in docs
    # the probe's stdout-contract detail keys
    for key in ("allreduce_schedule", "grad_sync"):
        assert key in src, f"training_step.py no longer records {key}"
    # every lifted op resolves its specs from rules, not hand threading
    for rel, symbol in (
        ("ops/ring_attention.py", "ring_partition_rules"),
        ("ops/pipeline.py", "stacked_layer_rules"),
        ("ops/pipeline.py", "pipeline_io_rules"),
        ("ops/moe.py", "moe_partition_rules"),
        ("models/probe_model.py", "param_partition_rules"),
        ("probes/training_step.py", "composed_param_rules"),
        ("probes/training_step.py", "grad_sync_plan"),
    ):
        assert symbol in (REPO / "activemonitor_tpu" / rel).read_text(), (
            f"{rel} no longer defines/uses {symbol}"
        )
    # docs: the partition-rules section exists and README points at it
    training = (REPO / "docs" / "training.md").read_text()
    assert "Partition rules" in training
    assert "match_partition_rules" in training
    assert "Partition rules" in (REPO / "README.md").read_text()
    # bench.py's evidence keys (both TPU and CPU-fallback paths;
    # interpret-mode labeled)
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "training_step_grad_sync",
        "tuned_vs_builtin",
        "train_allreduce_schedule",
        "composed_allreduce_schedule",
        "composed_allreduce_tuned_vs_builtin_interpret",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"


def test_swallowed_exception_fires_and_stays_quiet(tmp_path):
    got = findings(
        tmp_path,
        "def f():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        pass\n"
        "def work():\n"
        "    return 1\n",
    )
    assert codes(got) == {"swallowed-exception"}
    # a handler that DOES something (log, return, re-raise) is fine,
    # and narrow catches may pass silently
    quiet = (
        "import logging\n"
        "def f():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        logging.debug('x', exc_info=True)\n"
        "    try:\n"
        "        work()\n"
        "    except KeyError:\n"
        "        pass\n"
        "def work():\n"
        "    return 1\n"
    )
    assert findings(tmp_path, quiet) == []


def test_wallclock_banned_in_roofline_module(tmp_path):
    """obs/roofline.py is pure math over seconds passed IN as
    arguments (ISSUE 9 satellite): a bare wall-clock read there would
    silently couple bound classification to real time — same
    module-name keying as the sharding/attribution bans."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    (tmp_path / "roofline.py").write_text(source)
    got = lint.lint_file(tmp_path / "roofline.py")
    assert {line.split(": ")[1] for line in got} == {"wallclock-in-roofline"}
    assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="ceilings.py") == []


def test_roofline_module_really_is_wallclock_free():
    """The gate, applied: the shipped module lints clean and the ban
    covers it (path-scoping regression guard)."""
    path = REPO / "activemonitor_tpu" / "obs" / "roofline.py"
    assert path.exists(), "roofline module missing?"
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "roofline"


def test_roofline_families_are_pinned():
    """The ISSUE-9 families must stay in the exposition contract — the
    roofline dashboards key on the bound label and a rename silently
    orphans them."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_roofline", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_probe_roofline_fraction",
        "healthcheck_probe_arithmetic_intensity",
        "healthcheck_hbm_peak_bytes",
        "healthcheck_probe_roofline_runs_total",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_roofline_metric_names_are_pinned():
    """The ISSUE-9 contract suffixes and the per-probe capture are
    pinned across three layers — the probes build the gauges from the
    obs/roofline.py suffix constants, docs/probes.md's roofline table
    registers the names (the spellings spec.analysis.metrics[] takes),
    and bench.py stamps the roofline_summary block — so a rename in
    any one layer cannot silently orphan the others (the same gate the
    overlap/zoo metrics got)."""
    from activemonitor_tpu.obs import roofline as roofline_model

    assert roofline_model.INTENSITY_SUFFIX == "-arithmetic-intensity"
    assert roofline_model.FRACTION_SUFFIX == "-roofline-fraction"
    docs = (REPO / "docs" / "probes.md").read_text()
    for name in (
        "mxu-arithmetic-intensity",
        "mxu-roofline-fraction",
        "hbm-arithmetic-intensity",
        "hbm-roofline-fraction",
        "flash-attention-roofline-fraction",
        "train-roofline-fraction",
        "decode-roofline-fraction",
        "ring-attention-roofline-fraction",
        "ici-allreduce-roofline-fraction",
        "healthcheck_probe_roofline_fraction",
        "healthcheck_probe_arithmetic_intensity",
        "healthcheck_hbm_peak_bytes",
    ):
        assert name in docs, f"{name} missing from docs/probes.md"
    # every integrated probe routes through the capture helpers, so the
    # suffix constants are the single spelling source
    for rel, symbol in (
        ("probes/matmul.py", "roofline_model.capture"),
        ("probes/hbm.py", "roofline_model.capture"),
        ("probes/flash.py", "roofline_model.capture"),
        ("probes/training_step.py", "roofline_model.capture"),
        ("probes/decode.py", "roofline_model.capture"),
        ("probes/ring.py", "roofline_model.capture"),
        ("probes/ici.py", "roofline_model.comm_capture"),
        ("probes/collectives.py", "roofline_model.comm_capture"),
    ):
        src = (REPO / "activemonitor_tpu" / rel).read_text()
        assert symbol in src, f"{rel} no longer captures a roofline"
    # the "Reading a roofline" section the metric table points at
    observability = (REPO / "docs" / "observability.md").read_text()
    assert "Reading a roofline" in observability
    assert "ridge point" in observability.lower()
    assert "am-tpu roofline" in observability
    # bench.py's artifact stamp (both paths; interpret runs labeled)
    bench_src = (REPO / "bench.py").read_text()
    for key in ("roofline_summary", "_stamp_roofline", "cost_source"):
        assert key in bench_src, f"bench.py no longer records {key}"


def test_hierarchical_metric_names_are_pinned():
    """The ISSUE-13 hierarchical-collective names are contract
    spelling across the layers: the dcn probe emits the per-tier
    gauges, the collectives probe the composition cases, docs register
    every spelling, and bench.py stamps the hierarchical_autotune
    evidence block — a rename in any one layer silently orphans the
    others (the same gate the overlap/zoo/roofline names got)."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    pinned_metrics = {
        "dcn-xslice-busbw-gbps": "probes/dcn.py",
        "dcn-xslice-fraction-of-rated": "probes/dcn.py",
        "dcn-hier-allreduce-correct": "probes/dcn.py",
        "training-step-hier-sync": "probes/training_step.py",
        "collective-allreduce-hier-busbw-gbps": None,  # f-string-built
        "collective-allreduce-hier-latency-busbw-gbps": None,
    }
    for name, rel in pinned_metrics.items():
        assert name in docs, f"{name} missing from docs/probes.md metric table"
        if rel is None:
            continue
        src = (REPO / "activemonitor_tpu" / rel).read_text()
        declared = {
            node.value
            for node in ast.walk(ast.parse(src))
            if isinstance(node, ast.Constant) and isinstance(node.value, str)
        }
        assert name in declared, f"{name} not declared in {rel}"
    # the composition cases are part of the collectives-probe contract
    from activemonitor_tpu.probes.collectives import HIER_CASES, _BENCH

    for case in ("allreduce-hier", "allreduce-hier-latency"):
        assert case in HIER_CASES
        assert case in _BENCH
        assert case in docs, f"hier case {case} missing from docs/probes.md"
    # the rated DCN denominator + its override are registered
    from activemonitor_tpu.probes.rated import RatedSpec

    assert "dcn_gbps" in {f.name for f in __import__("dataclasses").fields(RatedSpec)}
    rated_src = (
        REPO / "activemonitor_tpu" / "probes" / "rated.py"
    ).read_text()
    assert "ACTIVEMONITOR_RATED_DCN_GBPS" in rated_src
    assert "ACTIVEMONITOR_RATED_DCN_GBPS" in docs
    # the catalog section the metric rows point at
    training = (REPO / "docs" / "training.md").read_text()
    for anchor in (
        "Hierarchical collectives",
        "hier_all_reduce",
        "latency_threshold",
        "resolve_tiers",
        "hier_plan",
    ):
        assert anchor in training, f"training.md lost {anchor}"
    # bench.py's hierarchical evidence block (both paths stamp it;
    # interpret-mode tables labeled) and the matrix's two-tier rows
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "hierarchical_autotune",
        "latency_threshold_bytes",
        "tiered_vs_flat",
        "tier_table",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"
    import json

    matrix_spec = json.loads(
        (REPO / "config" / "bench_matrix.json").read_text()
    )
    assert "hier-allreduce" in matrix_spec["ops"]
    assert {"dcn": 2, "ici": 4} in matrix_spec["meshes"]
    assert matrix_spec.get("payloads_kb"), "matrix lost its payload octaves"
    # the matrix op registry carries the runner-backed op
    from activemonitor_tpu.analysis.matrix import OPS, _RUNNERS

    assert "hier-allreduce" in OPS and "hier-allreduce" in _RUNNERS


def test_wallclock_banned_in_serving_and_kv_cache_modules(tmp_path):
    """The ISSUE-14 serving runtime carries the injectable-clock
    contract wherever its modules land: the admission scheduler takes
    every timestamp as an argument, the serving probe's soak runs on
    an injectable timer or the scripted StepCosts virtual clock, and
    the paged-cache manager is pure allocation arithmetic — so a bare
    wall-clock CALL in any serving.py or kv_cache.py is a lint error
    (same module-name keying as the sharding/matrix bans)."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    for module in ("serving", "kv_cache"):
        got = findings(tmp_path, source, name=f"{module}.py")
        assert codes(got) == {f"wallclock-in-{module}"}, module
        assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="admission.py") == []
    # the injectable default-timer idiom (referencing time.monotonic
    # WITHOUT calling it) stays quiet — it is how the probe does it
    clean = (
        "import time\n"
        "def run(timer=time.monotonic):\n"
        "    return timer()\n"
    )
    assert findings(tmp_path, clean, name="serving.py") == []


def test_serving_and_kv_cache_modules_really_are_wallclock_free():
    """The gate, applied: every shipped serving/kv module lints clean
    and the ban actually covers it (path-scoping regression guard —
    BOTH serving.py homes, scheduler and probe, plus the cache)."""
    for rel, pkg in (
        ("activemonitor_tpu/scheduler/serving.py", "serving"),
        ("activemonitor_tpu/probes/serving.py", "serving"),
        ("activemonitor_tpu/ops/kv_cache.py", "kv_cache"),
    ):
        path = REPO / rel
        assert path.exists(), f"{rel} missing?"
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock, rel
        assert checker.wallclock_pkg == pkg, rel


def test_serving_metric_names_are_pinned():
    """The ISSUE-14 serving names are contract spelling across the
    layers: the probe emits the serving-* gauges, the static decode
    probe exports the shared kv-bytes figure, docs/probes.md +
    docs/serving.md register the spellings (the names
    spec.analysis.metrics[] takes), bench.py stamps serving_summary on
    BOTH paths, and the matrix registry carries the runner-backed op
    with its batch-ceiling dimension and the deliberately impossible
    config cell — a rename in any one layer silently orphans the
    others (the same gate every prior subsystem's names got)."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    serving_docs = (REPO / "docs" / "serving.md").read_text()
    pinned_metrics = {
        "serving-tokens-per-s": "probes/serving.py",
        "serving-ttft-p50-ms": "probes/serving.py",
        "serving-ttft-p99-ms": "probes/serving.py",
        "serving-intertoken-p99-ms": "probes/serving.py",
        "serving-batch-occupancy": "probes/serving.py",
        "serving-kv-frag-ratio": "probes/serving.py",
        "serving-consistency": "probes/serving.py",
        "serving-kv-bytes-per-token": "probes/serving.py",
        "decode-kv-bytes-per-token": "probes/decode.py",
    }
    for name, rel in pinned_metrics.items():
        assert name in docs, f"{name} missing from docs/probes.md metric table"
        src = (REPO / "activemonitor_tpu" / rel).read_text()
        declared = {
            node.value
            for node in ast.walk(ast.parse(src))
            if isinstance(node, ast.Constant) and isinstance(node.value, str)
        }
        assert name in declared, f"{name} not declared in {rel}"
    # the runtime pieces the docs describe must exist under the
    # documented names (block tables, admission, open-loop, ceiling)
    for anchor in (
        "block table",
        "admission",
        "open-loop",
        "memory-bound",
        "fragmentation",
        "kv_bytes_per_token",
    ):
        assert anchor.lower() in serving_docs.lower(), (
            f"docs/serving.md lost {anchor!r}"
        )
    assert "docs/serving.md" in (REPO / "README.md").read_text()
    # the shared kv-bytes figure has ONE source both probes import
    for rel in ("probes/serving.py", "probes/decode.py"):
        assert "kv_bytes_per_token" in (
            REPO / "activemonitor_tpu" / rel
        ).read_text(), f"{rel} no longer uses the shared kv-bytes source"
    # bench.py's serving evidence block (both paths stamp it;
    # interpret-mode labeled, env-disableable)
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "serving_summary",
        "_stamp_serving",
        "ACTIVEMONITOR_BENCH_SERVING",
        "kv_frag_ratio",
        "ttft_p99_ms",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"
    # the matrix registry: runner-backed op, batch-ceiling expansion,
    # and the config's serving rows with the impossible model16 cell
    import json

    from activemonitor_tpu.analysis.matrix import OPS, _RUNNERS

    assert "serving" in OPS and "serving" in _RUNNERS
    assert OPS["serving"].accepts_batch
    matrix_spec = json.loads(
        (REPO / "config" / "bench_matrix.json").read_text()
    )
    assert "serving" in matrix_spec["ops"]
    assert matrix_spec.get("batch_ceilings"), "matrix lost batch ceilings"
    assert {"model": 16} in matrix_spec["meshes"]  # the deliberate deficit
    # CLI + battery registration
    cli_src = (REPO / "activemonitor_tpu" / "probes" / "cli.py").read_text()
    assert '"serving"' in cli_src
    assert "serving" in (
        REPO / "activemonitor_tpu" / "probes" / "suite.py"
    ).read_text()


def test_wallclock_banned_in_matrix_module(tmp_path):
    """The scenario-matrix module (ISSUE 12) carries the injectable-
    Clock contract wherever it lands: verdicts/baselines run on the
    Clock and the executor's timer is injectable (the PhaseTimings
    idiom), so a bare wall-clock CALL in any matrix.py is a lint error
    — under analysis/ via the package ban, elsewhere via the
    module-name keying the sharding/attribution bans use."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    # a matrix.py outside any banned package: module-name keyed ban
    got = findings(tmp_path, source, name="matrix.py")
    assert codes(got) == {"wallclock-in-matrix"}
    assert len(got) == 2
    # the shipped location (analysis/matrix.py): the package ban wins
    analysis_dir = tmp_path / "analysis"
    analysis_dir.mkdir()
    (analysis_dir / "matrix.py").write_text(source)
    got = lint.lint_file(analysis_dir / "matrix.py")
    assert codes(got) == {"wallclock-in-analysis"}
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="scenario.py") == []
    # referencing time.monotonic WITHOUT calling it (the injectable
    # default-timer idiom) stays quiet
    clean = (
        "import time\n"
        "def run(timer=time.monotonic):\n"
        "    return timer()\n"
    )
    assert findings(tmp_path, clean, name="matrix.py") == []


def test_matrix_module_really_is_wallclock_free():
    """The gate, applied: the shipped analysis/matrix.py lints clean
    and the ban covers it (path-scoping regression guard)."""
    path = REPO / "activemonitor_tpu" / "analysis" / "matrix.py"
    assert path.exists(), "matrix module missing?"
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "analysis"


def test_matrix_families_are_pinned():
    """The ISSUE-12 families must stay in the exposition contract —
    the matrix dashboard keys cells by label and a rename silently
    orphans it (same pin gate as every other subsystem's families)."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_matrix", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_matrix_cell_value",
        "healthcheck_matrix_cell_state",
        "healthcheck_matrix_cell_roofline_fraction",
        "healthcheck_matrix_cells",
        "healthcheck_matrix_bisect_runs_total",
    ):
        assert family in contract.PINNED_FAMILIES, family


def test_matrix_contract_names_are_pinned():
    """The ISSUE-12 contract spellings across the layers: the spec file
    ships the declared dimensions, docs register the cell schema and
    CLI verb, and bench.py stamps matrix_summary on BOTH paths with the
    interpret/fallback labeling — a rename in any one layer silently
    orphans the others (the roofline/zoo gate applied to the matrix)."""
    import json

    spec_doc = json.loads((REPO / "config" / "bench_matrix.json").read_text())
    from activemonitor_tpu.analysis import matrix as matrix_model

    for op in spec_doc["ops"]:
        assert op in matrix_model.OPS, f"spec op {op!r} not in registry"
    # expansion over the shipped spec must stay crash-free and produce
    # both runnable cells and structured skips on the 8-device platform
    cells, skipped = matrix_model.expand(spec_doc, n_devices=8)
    assert cells and skipped
    for result in skipped:
        assert result.status == matrix_model.STATUS_SKIPPED
        assert result.details["skip"]["code"]
    docs = (REPO / "docs" / "observability.md").read_text()
    assert "Reading the matrix" in docs
    assert "am-tpu matrix" in docs
    assert "BENCH_BASELINES.json" in docs
    probes_docs = (REPO / "docs" / "probes.md").read_text()
    for family in (
        "healthcheck_matrix_cell_value",
        "healthcheck_matrix_cell_state",
        "healthcheck_matrix_cell_roofline_fraction",
        "healthcheck_matrix_cells",
        "healthcheck_matrix_bisect_runs_total",
    ):
        assert family in probes_docs, f"{family} missing from docs/probes.md"
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "matrix_summary", "_stamp_matrix", "interpret_mode",
        "fallback_reason", "BENCH_BASELINES",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"


# -- front door (ISSUE 15) ---------------------------------------------


def test_wallclock_banned_in_frontdoor_package(tmp_path):
    """frontdoor/ runs entirely on the injectable Clock — quota-bucket
    refill, freshness-window expiry, and the QPS buckets must all be
    scriptable by fake-clock tests, so a bare time.time()/
    time.monotonic() anywhere under a frontdoor/ directory is a lint
    error (package-scoped like resilience/ and analysis/). The same
    code OUTSIDE frontdoor/ stays quiet."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    pkg_dir = tmp_path / "frontdoor"
    pkg_dir.mkdir()
    (pkg_dir / "mod.py").write_text(source)
    got = lint.lint_file(pkg_dir / "mod.py")
    assert codes(got) == {"wallclock-in-frontdoor"}
    assert len(got) == 2  # both the time() and the monotonic() call
    # identical code outside frontdoor/: no finding
    assert findings(tmp_path, source) == []
    # clock-disciplined front-door code: no finding
    clean = (
        "def fresh(clock, window):\n"
        "    return clock.monotonic() + window\n"
    )
    (pkg_dir / "clean.py").write_text(clean)
    assert lint.lint_file(pkg_dir / "clean.py") == []


def test_frontdoor_package_really_is_wallclock_free():
    """The gate, applied: the shipped frontdoor/ package lints clean,
    and the ban actually covers its files (path-scoping regression
    guard, like the resilience/analysis twins)."""
    package = REPO / "activemonitor_tpu" / "frontdoor"
    files = sorted(package.rglob("*.py"))
    assert files, "frontdoor package missing?"
    for path in files:
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock, path
        assert checker.wallclock_pkg == "frontdoor", path


def test_wallclock_banned_in_arrivals_module(tmp_path):
    """scheduler/arrivals.py is the ONE seeded open-loop arrival
    contract (serving's generator and the front door's share it):
    schedules live on the caller's timeline, so a wall-clock read
    there would smuggle nondeterminism into both generators at once.
    Module-name keyed like serving.py/kv_cache.py."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
    )
    got = findings(tmp_path, source, name="arrivals.py")
    assert codes(got) == {"wallclock-in-arrivals"}
    path = REPO / "activemonitor_tpu" / "scheduler" / "arrivals.py"
    assert path.exists()
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "arrivals"


def test_frontdoor_metric_families_are_pinned():
    """The ISSUE-15 families must stay in the exposition contract — the
    coalescing dashboard reads the hit/join ratios next to the request
    counters, and a rename silently breaks the per-tenant refusal
    alert."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_frontdoor", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in (
        "healthcheck_frontdoor_requests_total",
        "healthcheck_frontdoor_refusals_total",
        "healthcheck_frontdoor_coalesce_ratio",
        "healthcheck_frontdoor_queue_depth",
        "healthcheck_frontdoor_admission_seconds",
    ):
        assert family in contract.PINNED_FAMILIES, family
    # and the operator docs register every family next to the runbook
    docs = (REPO / "docs" / "observability.md").read_text()
    for family in (
        "healthcheck_frontdoor_requests_total",
        "healthcheck_frontdoor_refusals_total",
        "healthcheck_frontdoor_coalesce_ratio",
        "healthcheck_frontdoor_queue_depth",
        "healthcheck_frontdoor_admission_seconds",
    ):
        assert family in docs, f"{family} missing from docs/observability.md"
    ops_docs = (REPO / "docs" / "operations.md").read_text()
    assert "Probe-as-a-service front door" in ops_docs
    assert "/frontdoor/submit" in ops_docs


def test_wallclock_banned_in_journal_and_replay(tmp_path):
    """obs/journal.py and obs/replay.py carry the injectable-Clock
    contract (ISSUE 16): event timestamps, lag and the replay drive all
    live on the injected Clock/FakeClock, so a bare wall-clock read
    there is a lint error — same module-name keying as the
    attribution/flightrec twins."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    for module in ("journal", "replay"):
        (tmp_path / f"{module}.py").write_text(source)
        got = lint.lint_file(tmp_path / f"{module}.py")
        assert {line.split(": ")[1] for line in got} == {
            f"wallclock-in-{module}"
        }, module
        assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="summarizer.py") == []


def test_journal_and_replay_really_are_wallclock_free():
    """The gate, applied: the shipped modules lint clean and the ban
    covers them (path-scoping regression guard, like the sharding
    twin)."""
    for module in ("journal", "replay"):
        path = REPO / "activemonitor_tpu" / "obs" / f"{module}.py"
        assert path.exists(), f"{module} module missing?"
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock
        assert checker.wallclock_pkg == module


JOURNAL_FAMILIES = (
    "healthcheck_journal_appended_total",
    "healthcheck_journal_replayed_total",
    "healthcheck_journal_dropped_total",
    "healthcheck_journal_segments",
    "healthcheck_journal_lag_seconds",
)


def test_journal_metric_families_are_pinned():
    """The ISSUE-16 families must stay in the exposition contract — the
    durability dashboard stacks the appended/replayed counters next to
    the lag gauge, and a rename silently breaks the staleness alert."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_journal", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in JOURNAL_FAMILIES:
        assert family in contract.PINNED_FAMILIES, family
    # and the operator docs register every family next to the runbook
    docs = (REPO / "docs" / "observability.md").read_text()
    for family in JOURNAL_FAMILIES:
        assert family in docs, f"{family} missing from docs/observability.md"
    assert "Durable telemetry journal" in docs


def test_frontdoor_replay_op_is_cross_pinned():
    """The ``frontdoor-replay`` matrix op must exist everywhere an
    operator meets it: the op registry + runner table + default spec,
    the shipped config matrix, the record/replay runbook, and the
    integrity checker the runbook points at — a rename in one place
    strands the others."""
    from activemonitor_tpu.analysis import matrix as matrix_mod

    assert "frontdoor-replay" in matrix_mod.OPS
    assert "frontdoor-replay" in matrix_mod._RUNNERS
    assert "frontdoor-replay" in matrix_mod.DEFAULT_SPEC["ops"]
    assert "frontdoor-replay" in (
        REPO / "config" / "bench_matrix.json"
    ).read_text()
    ops_docs = (REPO / "docs" / "operations.md").read_text()
    assert "Recording and replaying a traffic trace" in ops_docs
    assert "am-tpu record" in ops_docs
    assert "am-tpu replay" in ops_docs
    assert "hack/journal_check.py" in ops_docs
    assert (REPO / "hack" / "journal_check.py").exists()
    obs_docs = (REPO / "docs" / "observability.md").read_text()
    assert "frontdoor-replay" in obs_docs


def test_wallclock_banned_in_criticalpath(tmp_path):
    """obs/criticalpath.py is pure math over span monotonics and
    PhaseTimings passed IN (ISSUE 17): a bare wall-clock read there
    would desync the stage sums from the trace's own timeline — same
    module-name keying as the journal/replay twins."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    (tmp_path / "criticalpath.py").write_text(source)
    got = lint.lint_file(tmp_path / "criticalpath.py")
    assert {line.split(": ")[1] for line in got} == {
        "wallclock-in-criticalpath"
    }
    assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="waterfaller.py") == []


def test_criticalpath_really_is_wallclock_free():
    """The gate, applied: the shipped module lints clean and the ban
    covers it (path-scoping regression guard, like the journal twin)."""
    path = REPO / "activemonitor_tpu" / "obs" / "criticalpath.py"
    assert path.exists(), "criticalpath module missing?"
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "criticalpath"


CRITICAL_PATH_FAMILIES = (
    "healthcheck_critical_path_seconds",
    "healthcheck_profile_captures_total",
)


def test_critical_path_metric_families_are_pinned():
    """The ISSUE-17 families must stay in the exposition contract — the
    latency dashboard stacks the per-stage percentile gauge under the
    capture counter, and a rename silently breaks the dominant-stage
    alert."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_criticalpath",
        REPO / "tests" / "test_metrics.py",
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    for family in CRITICAL_PATH_FAMILIES:
        assert family in contract.PINNED_FAMILIES, family
    # and the operator docs register every family next to the runbook
    docs = (REPO / "docs" / "observability.md").read_text()
    for family in CRITICAL_PATH_FAMILIES:
        assert family in docs, f"{family} missing from docs/observability.md"
    assert "Reading a waterfall" in docs


def test_critical_path_stage_vocabulary_is_cross_pinned():
    """The stage vocabulary is a cross-layer contract: the waterfall
    builder emits it, the gauge labels carry it, the /statusz block
    serializes it, and the docs table teaches it. One rename strands
    dashboards and the runbook — pin the literal tuple and check every
    surface against it."""
    from activemonitor_tpu.obs import criticalpath

    assert criticalpath.STAGES == (
        "queue_wait",
        "admission",
        "schedule",
        "submit",
        "poll",
        "probe_phase",
        "status_write",
        "untracked",
    )
    # every mapped span stage is in the vocabulary, and untracked is
    # never a span mapping target (it's the residual, not a span)
    assert set(criticalpath.SPAN_STAGES.values()) <= set(criticalpath.STAGES)
    assert "untracked" not in criticalpath.SPAN_STAGES.values()
    # the docs stage table names every stage
    docs = (REPO / "docs" / "observability.md").read_text()
    for stage in criticalpath.STAGES:
        assert f"`{stage}`" in docs, f"{stage} missing from the docs table"
    # the gauge helper clears exactly this vocabulary (metrics ↔
    # criticalpath can't drift: collector imports STAGES directly)
    collector_src = (
        REPO / "activemonitor_tpu" / "metrics" / "collector.py"
    ).read_text()
    assert "from activemonitor_tpu.obs.criticalpath import" in collector_src


def test_criticalpath_quantile_matches_slo():
    """Both percentile surfaces use the same nearest-rank estimator and
    the same quantile triple — a drift would make the waterfall's p95
    disagree with the SLO window's p95 over identical samples."""
    from activemonitor_tpu.obs import criticalpath, slo

    assert criticalpath.QUANTILES == slo.QUANTILES == (0.50, 0.95, 0.99)
    samples = [0.1, 0.5, 0.2, 4.0, 0.9, 1.5, 0.3]
    for q in criticalpath.QUANTILES:
        assert criticalpath._quantile(samples, q) == slo.quantile(samples, q)


# -- federation (ISSUE 19) ---------------------------------------------


def test_wallclock_banned_in_federation_package(tmp_path):
    """federation/ is the multi-cluster control plane: liveness is
    judged by locally-observed payload movement on the injected Clock,
    routing must be reproducible, and the global-door ledgers ride the
    same token buckets as frontdoor/ — a bare time.time()/
    time.monotonic() anywhere under a federation/ directory is a lint
    error (package-scoped like resilience/analysis/frontdoor). The
    same code OUTSIDE federation/ stays quiet."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    pkg_dir = tmp_path / "federation"
    pkg_dir.mkdir()
    (pkg_dir / "mod.py").write_text(source)
    got = lint.lint_file(pkg_dir / "mod.py")
    assert codes(got) == {"wallclock-in-federation"}
    assert len(got) == 2  # both the time() and the monotonic() call
    # identical code outside federation/: no finding
    assert findings(tmp_path, source) == []
    # clock-disciplined federation code: no finding
    clean = (
        "def moved(clock, last, window):\n"
        "    return clock.monotonic() - last >= window\n"
    )
    (pkg_dir / "clean.py").write_text(clean)
    assert lint.lint_file(pkg_dir / "clean.py") == []


def test_federation_package_really_is_wallclock_free():
    """The gate, applied: the shipped federation/ package lints clean,
    and the ban actually covers its files (path-scoping regression
    guard, like the resilience/analysis/frontdoor twins)."""
    package = REPO / "activemonitor_tpu" / "federation"
    files = sorted(package.rglob("*.py"))
    assert files, "federation package missing?"
    for path in files:
        assert lint.lint_file(path) == []
        src = path.read_text()
        checker = lint.Checker(str(path), __import__("ast").parse(src), src)
        assert checker.ban_wallclock, path
        assert checker.wallclock_pkg == "federation", path


def test_federation_metric_families_are_pinned():
    """The ISSUE-19 families must stay in the exposition contract — the
    federation dashboard reads cluster health next to the per-cluster
    request counters, and a rename silently breaks the unhealthy-
    cluster alert."""
    spec = importlib.util.spec_from_file_location(
        "test_metrics_contract_federation", REPO / "tests" / "test_metrics.py"
    )
    contract = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(contract)
    families = (
        "healthcheck_federation_clusters",
        "healthcheck_federation_cluster_healthy",
        "healthcheck_federation_transitions_total",
        "healthcheck_federation_requests_total",
        "healthcheck_federation_refusals_total",
        "healthcheck_federation_routes_total",
        "healthcheck_federation_goodput_ratio",
    )
    for family in families:
        assert family in contract.PINNED_FAMILIES, family
    # and the operator docs register every family next to the runbook
    docs = (REPO / "docs" / "observability.md").read_text()
    for family in families:
        assert family in docs, f"{family} missing from docs/observability.md"
    ops_docs = (REPO / "docs" / "operations.md").read_text()
    assert "Federating clusters" in ops_docs
    assert "--federation-config" in ops_docs


def test_wallclock_banned_in_pools_module(tmp_path):
    """The ISSUE-20 pool split carries the injectable-clock contract:
    DisaggregatedScheduler takes every timestamp as an argument and the
    migration channel's seconds are alpha/B MODEL outputs, never
    measurements — so a bare wall-clock CALL in any pools.py is a lint
    error (same module-name keying as the serving/kv_cache bans)."""
    source = (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"
        "def tick():\n"
        "    return time.monotonic()\n"
    )
    got = findings(tmp_path, source, name="pools.py")
    assert codes(got) == {"wallclock-in-pools"}
    assert len(got) == 2
    # identical code under any other module name: no finding
    assert findings(tmp_path, source, name="topology.py") == []
    # the injectable default-timer idiom (referencing time.monotonic
    # WITHOUT calling it) stays quiet
    clean = (
        "import time\n"
        "def pump(timer=time.monotonic):\n"
        "    return timer()\n"
    )
    assert findings(tmp_path, clean, name="pools.py") == []


def test_pools_module_really_is_wallclock_free():
    """The gate, applied: the shipped pool-split module lints clean and
    the ban actually covers it (path-scoping regression guard)."""
    path = REPO / "activemonitor_tpu" / "scheduler" / "pools.py"
    assert path.exists(), "scheduler/pools.py missing?"
    assert lint.lint_file(path) == []
    src = path.read_text()
    checker = lint.Checker(str(path), __import__("ast").parse(src), src)
    assert checker.ban_wallclock
    assert checker.wallclock_pkg == "pools"


def test_serving_disagg_metric_names_are_pinned():
    """The ISSUE-20 names are contract spelling across the layers: the
    probe emits the per-pool/migration/prefix/speculation gauges,
    docs/probes.md + docs/serving.md register the spellings, bench.py
    stamps serving_disagg on BOTH paths, the matrix registry carries
    the variant-dimensioned op next to the config rows, and the
    spec-acceptance metric keeps the -fraction-of-rated suffix the
    detector's rated-fraction path keys on — a rename in any one layer
    silently orphans the others."""
    import ast

    docs = (REPO / "docs" / "probes.md").read_text()
    serving_docs = (REPO / "docs" / "serving.md").read_text()
    src = (REPO / "activemonitor_tpu" / "probes" / "serving.py").read_text()
    declared = {
        node.value
        for node in ast.walk(ast.parse(src))
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }
    pinned_metrics = (
        "serving-pool-prefill-ttft-p99-ms",
        "serving-pool-prefill-tokens-per-s",
        "serving-pool-decode-tokens-per-s",
        "serving-disagg-ttft-improvement",
        "serving-kv-migration-bytes",
        "serving-kv-migration-p99-ms",
        "serving-prefix-hit-ratio",
        "serving-prefix-evictions",
        "serving-disagg-consistency",
        "serving-spec-accept-fraction-of-rated",
    )
    for name in pinned_metrics:
        assert name in docs, f"{name} missing from docs/probes.md"
        assert name in declared, f"{name} not declared in probes/serving.py"
    # the acceptance export must keep the rated-fraction suffix so
    # analysis/detector.py judges it through the absolute-floor path
    from activemonitor_tpu.analysis.detector import is_rated_fraction_metric

    assert is_rated_fraction_metric("serving-spec-accept-fraction-of-rated")
    # the runtime pieces the docs describe, under the documented names
    for anchor in (
        "prefill pool",
        "decode pool",
        "migration",
        "prefix cache",
        "speculative",
        "acceptance",
    ):
        assert anchor.lower() in serving_docs.lower(), (
            f"docs/serving.md lost {anchor!r}"
        )
    # bench.py's disagg evidence block (both paths stamp it;
    # interpret-mode labeled, env-disableable)
    bench_src = (REPO / "bench.py").read_text()
    for key in (
        "serving_disagg",
        "_stamp_serving_disagg",
        "ACTIVEMONITOR_BENCH_SERVING_DISAGG",
        "ttft_improvement",
    ):
        assert key in bench_src, f"bench.py no longer records {key}"
    # the matrix registry: runner-backed op with the topology-variant
    # dimension, and the config rows that include the deficit mesh
    import json

    from activemonitor_tpu.analysis.matrix import OPS, _RUNNERS

    assert "serving-disagg" in OPS and "serving-disagg" in _RUNNERS
    assert OPS["serving-disagg"].variants == (
        "colo", "split", "split-prefix", "split-spec",
    )
    matrix_spec = json.loads(
        (REPO / "config" / "bench_matrix.json").read_text()
    )
    assert "serving-disagg" in matrix_spec["ops"]
    assert {"model": 16} in matrix_spec["meshes"]  # the deliberate deficit
    # CLI + battery registration
    cli_src = (REPO / "activemonitor_tpu" / "probes" / "cli.py").read_text()
    assert '"serving-disagg"' in cli_src
    assert "serving-disagg" in (
        REPO / "activemonitor_tpu" / "probes" / "suite.py"
    ).read_text()
