"""Straggler and host-transfer probes on the virtual CPU mesh."""

import json

from activemonitor_tpu.probes import straggler, transfer


def test_straggler_runs_across_virtual_devices():
    # virtual CPU devices share host cores, so timing spread is noise —
    # a loose threshold keeps this a wiring test, not a timing test
    result = straggler.run(dim=128, iters=2, threshold=100.0)
    assert result.ok
    assert result.details["devices"] == 8
    assert len(result.details["per_device_ms"]) == 8
    names = {m.name for m in result.metrics}
    assert names == {
        "straggler-worst-over-median",
        "straggler-slow-devices",
        "straggler-numeric-agreement",
    }


def test_straggler_numeric_agreement_on_identical_silicon():
    result = straggler.run(dim=128, iters=2, threshold=100.0)
    # 8 virtual devices on one host: bitwise-identical results required
    assert result.details["distinct_checksums"] == 1
    agreement = next(
        m for m in result.metrics if m.name == "straggler-numeric-agreement"
    )
    assert agreement.value == 1.0


def test_straggler_timing_spread_informational_off_tpu():
    # threshold ~1.0: any timing noise flags devices — but on virtual
    # CPU devices (shared host cores) the spread must not gate the
    # verdict, only the numerics do
    result = straggler.run(dim=128, iters=2, threshold=1.0000001)
    assert result.ok
    if result.details["slow_devices"]:
        assert "informational off-TPU" in result.summary


def test_straggler_contract_line():
    result = straggler.run(dim=128, iters=2, threshold=100.0)
    parsed = json.loads(result.contract_line())
    assert len(parsed["metrics"]) == 3


def test_transfer_reports_both_directions():
    result = transfer.run(size_mb=2.0, iters=2)
    assert result.ok  # informational without a floor
    names = {m.name for m in result.metrics}
    assert names == {"transfer-h2d-gbps", "transfer-d2h-gbps"}
    for m in result.metrics:
        assert m.value > 0


def test_transfer_floor_gates():
    result = transfer.run(size_mb=2.0, iters=2, min_gbps=1e9)  # absurd floor
    assert not result.ok
    assert result.details["min_gbps"] == 1e9


def test_transfer_payload_rounded_to_block():
    result = transfer.run(size_mb=2.0, iters=2)
    for key in ("h2d_payload_mb", "d2h_payload_mb"):
        payload = result.details[key] * 1e6
        assert payload % (4 * 1024) == 0


def test_transfer_noise_limited_fails_floor_only():
    from unittest import mock

    from activemonitor_tpu.probes import transfer as t

    # force every delta into the noise floor: unmeasurable must stay an
    # informational pass without a floor and fail closed with one
    with mock.patch.object(t, "_delta_gbps", return_value=(123.0, 2048, True)):
        assert t.run(size_mb=2.0, iters=1).ok
        gated = t.run(size_mb=2.0, iters=1, min_gbps=0.001)
        assert not gated.ok
        assert "noise-limited" in gated.summary
