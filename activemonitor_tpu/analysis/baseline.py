"""Per-(check, metric) rolling baselines.

Three estimators per metric, each covering the others' blind spot:

- **Welford** (count/mean/M2): numerically-stable lifetime mean and
  variance in O(1) memory — the long-run anchor.
- **EWMA** (``alpha`` = :data:`EWMA_ALPHA`): a recency-weighted level
  so dashboards can see where the metric is *heading*.
- **median/MAD over a bounded recent ring**: the robust center and
  scale the z-score detector divides by — one wild outlier moves a
  mean/std pair but barely moves median/MAD, so the detector keeps
  judging subsequent runs against a sane baseline.

Serialization is deliberately compact (:meth:`MetricBaseline.to_dict`
rounds to 6 significant digits): the whole per-check baseline set is
persisted into ``.status.analysis`` on every status write and replayed
through the merge-patch path, so it must stay a few hundred bytes, not
a history dump. :meth:`CheckBaselines.from_status` is defensive — a
corrupt or hand-edited blob yields a fresh baseline, never a crash in
the reconcile path.

The set is stamped on the injectable Clock (``updated_at`` rides the
durable blob) so fake-clock tests pin exact timestamps.
"""

from __future__ import annotations

import json
import math
import os
import statistics
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from activemonitor_tpu.utils.clock import Clock

# recent-ring length: enough for a stable median/MAD and the trend
# window, small enough that the serialized blob stays compact
RECENT_WINDOW = 32

EWMA_ALPHA = 0.2

# the MAD of a constant series is 0 and its std is 0 — a baseline fed
# identical readings (FakeEngine scripts, quantized counters) needs a
# floor or the first deviation divides by zero. Relative to the center
# so the floor scales with the metric's magnitude.
RELATIVE_SCALE_FLOOR = 0.05
ABSOLUTE_SCALE_FLOOR = 1e-9

# consistency constant: MAD * 1.4826 estimates the std of a normal
MAD_TO_SIGMA = 1.4826

# stat labels of the healthcheck_metric_baseline{stat=} family
BASELINE_STATS = ("mean", "std", "median", "mad", "count")


def _compact(value: float) -> float:
    """6 significant digits — keeps the serialized blob small without
    moving any z-score that matters."""
    if not math.isfinite(value):
        return 0.0
    return float(f"{value:.6g}")


class MetricBaseline:
    """Rolling statistics for one (check, metric) pair."""

    __slots__ = ("n", "mean", "m2", "ewma", "recent")

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.ewma = 0.0
        self.recent: Deque[float] = deque(maxlen=RECENT_WINDOW)

    # -- updates --------------------------------------------------------
    def observe(self, value: float) -> None:
        value = float(value)
        if not math.isfinite(value):
            return  # NaN/inf must never poison the accumulators
        self.n += 1
        delta = value - self.mean
        self.mean += delta / self.n
        self.m2 += delta * (value - self.mean)
        self.ewma = (
            value if self.n == 1 else EWMA_ALPHA * value + (1 - EWMA_ALPHA) * self.ewma
        )
        self.recent.append(value)

    # -- statistics -----------------------------------------------------
    @property
    def std(self) -> float:
        if self.n < 2:
            return 0.0
        return math.sqrt(max(0.0, self.m2 / (self.n - 1)))

    @property
    def median(self) -> float:
        if not self.recent:
            return self.mean
        return statistics.median(self.recent)

    @property
    def mad(self) -> float:
        if not self.recent:
            return 0.0
        center = self.median
        return statistics.median(abs(v - center) for v in self.recent)

    def scale(self) -> float:
        """The denominator for robust z-scores: MAD-derived sigma when
        the ring has spread; a zero MAD with a non-empty ring means the
        distribution is CONCENTRATED (most samples equal the median),
        so the relative floor applies — falling back to the lifetime
        std there would let one past outlier inflate the scale and mask
        the next one. The std is the fallback only for a baseline
        restored without its recent ring."""
        center = abs(self.median) or abs(self.mean)
        floor = max(ABSOLUTE_SCALE_FLOOR, RELATIVE_SCALE_FLOOR * center)
        robust = MAD_TO_SIGMA * self.mad
        if robust > 0:
            return max(floor, robust)
        if self.recent:
            return floor
        return max(floor, self.std)

    def zscore(self, value: float) -> float:
        """Robust z of a NEW sample against the CURRENT baseline (call
        before :meth:`observe`, or every sample judges itself)."""
        return (float(value) - self.median) / self.scale()

    # -- persistence ----------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "n": self.n,
            "mean": _compact(self.mean),
            "m2": _compact(self.m2),
            "ewma": _compact(self.ewma),
            "recent": [_compact(v) for v in self.recent],
        }

    @classmethod
    def from_dict(cls, data: dict) -> "MetricBaseline":
        baseline = cls()
        baseline.n = max(0, int(data.get("n", 0)))
        baseline.mean = float(data.get("mean", 0.0))
        baseline.m2 = max(0.0, float(data.get("m2", 0.0)))
        baseline.ewma = float(data.get("ewma", 0.0))
        for value in list(data.get("recent") or [])[-RECENT_WINDOW:]:
            baseline.recent.append(float(value))
        return baseline


class CheckBaselines:
    """All of one check's metric baselines plus the warm-up gate."""

    def __init__(self, clock: Optional[Clock] = None, warmup_runs: int = 5):
        self.clock = clock or Clock()
        self.warmup_runs = max(1, warmup_runs)
        self._metrics: Dict[str, MetricBaseline] = {}
        self.updated_at = None

    def baseline(self, metric: str) -> MetricBaseline:
        baseline = self._metrics.get(metric)
        if baseline is None:
            baseline = self._metrics[metric] = MetricBaseline()
        return baseline

    def peek(self, metric: str) -> Optional[MetricBaseline]:
        return self._metrics.get(metric)

    def observe(self, metric: str, value: float) -> MetricBaseline:
        baseline = self.baseline(metric)
        baseline.observe(value)
        self.updated_at = self.clock.now()
        return baseline

    def warmed(self, metric: str) -> bool:
        """Warm-up gate: statistical detectors stay silent until the
        baseline has seen ``warmup_runs`` samples — judging run 2
        against a baseline of run 1 manufactures anomalies."""
        baseline = self._metrics.get(metric)
        return baseline is not None and baseline.n >= self.warmup_runs

    def metrics(self) -> List[str]:
        return list(self._metrics.keys())

    # -- persistence ----------------------------------------------------
    def to_dict(self) -> dict:
        doc = {
            name: baseline.to_dict() for name, baseline in self._metrics.items()
        }
        return doc

    @classmethod
    def from_dict(
        cls, data: dict, clock: Optional[Clock] = None, warmup_runs: int = 5
    ) -> "CheckBaselines":
        """Defensive restore: any malformed metric entry is dropped (a
        hand-edited status must never crash the reconcile path)."""
        baselines = cls(clock, warmup_runs)
        if not isinstance(data, dict):
            return baselines
        for name, entry in data.items():
            if not isinstance(name, str) or not isinstance(entry, dict):
                continue
            try:
                baselines._metrics[name] = MetricBaseline.from_dict(entry)
            except (TypeError, ValueError):
                continue
        return baselines


# ---------------------------------------------------------------------
# durable sidecar blob (BENCH_BASELINES.json — the scenario matrix's
# cross-round persistence, analysis/matrix.py)
# ---------------------------------------------------------------------

# bump on any incompatible blob layout change: a version-skewed sidecar
# restores FRESH (with a structured warning), never half-parsed — the
# same discipline .status.analysis blobs follow (STATUS_VERSION)
BLOB_VERSION = 1


def save_blob(path: str, doc: dict) -> Optional[dict]:
    """Persist a versioned baseline sidecar atomically (tmp + replace —
    a crash mid-write must leave the previous round's blob intact, not
    a truncated JSON the next round then discards as corrupt). Returns
    a structured error dict on failure (never raises: persistence is
    evidence, not a gate on the round that produced it)."""
    payload = {"blob_version": BLOB_VERSION, **doc}
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as exc:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return {"reason": "write-failed", "detail": str(exc)[:200]}
    return None


def load_blob(path: str) -> Tuple[Optional[dict], Optional[dict]]:
    """Restore a sidecar written by :func:`save_blob`.

    Returns ``(doc, warning)`` where exactly one of the two carries
    information: a readable current-version blob yields ``(doc,
    None)``; a missing file yields ``(None, None)`` (first round —
    nothing to warn about); anything else — unreadable file, corrupt
    JSON, non-dict top level, or a version the reader doesn't speak —
    yields ``(None, warning)`` with a structured reason so the caller
    starts a FRESH baseline and surfaces WHY instead of crashing or
    silently judging against half-parsed statistics."""
    try:
        with open(path) as fh:
            raw = fh.read()
    except FileNotFoundError:
        return None, None
    except OSError as exc:
        return None, {"reason": "unreadable", "detail": str(exc)[:200]}
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as exc:
        return None, {"reason": "corrupt-json", "detail": str(exc)[:200]}
    if not isinstance(doc, dict):
        return None, {
            "reason": "corrupt-shape",
            "detail": f"top level is {type(doc).__name__}, expected object",
        }
    version = doc.get("blob_version")
    if version != BLOB_VERSION:
        return None, {
            "reason": "version-skew",
            "detail": f"blob_version {version!r}, reader speaks {BLOB_VERSION}",
        }
    return doc, None
