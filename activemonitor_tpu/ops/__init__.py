"""TPU kernels (Pallas) used by probes."""

from activemonitor_tpu.ops.stream import stream_scale_pallas, stream_scale_xla

__all__ = ["stream_scale_pallas", "stream_scale_xla"]
