"""Ring-attention (sequence-parallel) probe — the long-context canary.

Three verdicts in one probe:

1. correctness — sequence-parallel ring attention over the mesh must
   match single-device attention (a wrong answer here means broken
   collectives/permutes, the scariest failure mode for long-context
   training), for BOTH the overlapped and bidirectional schedules; the
   overlapped schedule must additionally be bit-identical to the serial
   reference (same blocks merged in the same order — any divergence is
   a scheduling bug, not rounding);
2. throughput — attended tokens/s for a sequence n× longer than one
   device could hold, exported as gauges;
3. overlap efficiency — the serial schedule (attend THEN hop) is timed
   against the requested schedule and the ratio exported as
   ``ring-overlap-efficiency``: >1 means the double-buffered/
   bidirectional rotation actually hides ICI time under attention
   math. Alongside it, ``ring-attention-busbw-gbps`` reports the K/V
   bytes the ring moved per second of step time, and on rated TPU
   hardware ``ring-attention-busbw-fraction-of-rated`` compares that
   against the schedule's link ceiling (1x unidirectional link for
   serial/overlap, 2x for bidir) — the fraction of rated ICI ring
   bandwidth the op sustains while computing, the bench north star
   applied to the attention hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.ops.ring_attention import (
    VARIANTS,
    reference_attention,
    ring_attention,
)
from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    batch: int = 1,
    seq_per_device: int = 1024,
    heads: int = 8,
    head_dim: int = 128,
    iters: int = 5,
    tolerance: float = 2e-2,
    use_flash: bool = False,
    variant: str = "overlap",
    overlap_metrics: bool = True,
    roofline: bool = True,
) -> ProbeResult:
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, got {variant!r}")
    mesh = make_1d_mesh("sp")
    n = mesh.devices.size
    seq = seq_per_device * n
    dtype = jnp.bfloat16
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (
        jax.random.normal(kk, (batch, seq, heads, head_dim), dtype) for kk in keys
    )

    # correctness on a small slice (full reference attention is O(S^2)
    # on one device — keep it tractable)
    small = min(seq, 64 * n)
    qs, ks, vs = q[:, :small], k[:, :small], v[:, :small]
    got = ring_attention(qs, ks, vs, mesh, "sp", use_flash=use_flash, variant=variant)
    want = reference_attention(qs, ks, vs)
    max_err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)))
    )
    # schedule cross-checks (bundled with the overlap telemetry — each
    # is an extra compile, so the cheap overlap_metrics=False mode
    # skips them): overlapped must be BITWISE serial (same merges,
    # different transfer timing); bidir merges halves in a different
    # order, so it gets the reference tolerance
    correct = max_err <= tolerance
    overlap_vs_serial = bidir_err = None
    if overlap_metrics:
        serial_small = ring_attention(
            qs, ks, vs, mesh, "sp", use_flash=use_flash, variant="serial"
        )
        overlap_small = (
            got
            if variant == "overlap"
            else ring_attention(
                qs, ks, vs, mesh, "sp", use_flash=use_flash, variant="overlap"
            )
        )
        overlap_vs_serial = float(
            jnp.max(
                jnp.abs(
                    overlap_small.astype(jnp.float32)
                    - serial_small.astype(jnp.float32)
                )
            )
        )
        bidir_small = (
            got
            if variant == "bidir"
            else ring_attention(
                qs, ks, vs, mesh, "sp", use_flash=use_flash, variant="bidir"
            )
        )
        bidir_err = float(
            jnp.max(
                jnp.abs(
                    bidir_small.astype(jnp.float32) - want.astype(jnp.float32)
                )
            )
        )
        # overlap-vs-serial is a bit-compat contract (identical merges)
        # — but the verdict bound leaves room for a backend's fusion
        # quirks: bf16 outputs quantize to ~2^-8 steps, so any REAL
        # schedule bug clears 1e-6 by orders of magnitude (CPU tier-1
        # asserts exact 0)
        correct = (
            correct and overlap_vs_serial <= 1e-6 and bidir_err <= tolerance
        )

    # throughput: chained ring attentions (output feeds next Q)
    def make_chain(chain_variant):
        def make(kreps):
            @jax.jit
            def chain(q, k, v):
                x = q
                for _ in range(kreps):
                    x = ring_attention(
                        x, k, v, mesh, "sp",
                        use_flash=use_flash, variant=chain_variant,
                    )
                return x.astype(jnp.float32).sum()

            return chain

        return make

    seconds = chain_delta_seconds(
        make_chain(variant), q, k, v, k1=1, k2=3, iters=iters
    )
    tokens_per_second = batch * seq / seconds
    # attention FLOPs: 2 matmuls of [S, S] x head_dim per head, causal halves it
    flops = 2 * 2 * batch * heads * seq * seq * head_dim / 2
    tflops = flops / seconds / 1e12

    metrics = [
        ProbeMetric(
            "ring-attention-max-error",
            max_err,
            help="Max abs error of sequence-parallel vs single-device attention",
        ),
        ProbeMetric(
            "ring-attention-tokens-per-second",
            tokens_per_second,
            help="Ring-attention throughput over the sequence-parallel mesh",
        ),
        ProbeMetric(
            "ring-attention-tflops", tflops, help="Achieved attention TFLOP/s"
        ),
    ]
    details = {
        "devices": n,
        "block_compute": "flash" if use_flash else "xla",
        "variant": variant,
        "seq": seq,
        "seq_per_device": seq_per_device,
        "heads": heads,
        "head_dim": head_dim,
        "seconds_per_op": seconds,
        "max_error": max_err,
    }
    if overlap_vs_serial is not None:
        details["overlap_vs_serial_max_error"] = overlap_vs_serial
        details["bidir_max_error"] = bidir_err

    devices = jax.devices()
    if overlap_metrics and n > 1:
        # measured serial-vs-overlapped step time: the driver-evidenced
        # win of issuing the K/V hop before the block attend
        serial_seconds = (
            seconds
            if variant == "serial"
            else chain_delta_seconds(
                make_chain("serial"), q, k, v, k1=1, k2=3, iters=iters
            )
        )
        efficiency = serial_seconds / max(seconds, 1e-12)
        metrics.append(
            ProbeMetric(
                "ring-overlap-efficiency",
                efficiency,
                help="Serial-schedule step time / measured schedule step "
                "time (>1 = ICI hops hidden under attention math)",
            )
        )
        # K/V wire bytes per device per call: both tensors make n-1
        # hops of one [B, S/n, Hkv, D] block in the ring dtype
        hop_bytes = (
            2 * batch * seq_per_device * heads * head_dim * jnp.dtype(dtype).itemsize
        )
        wire_bytes = hop_bytes * (n - 1)
        busbw = wire_bytes / seconds / 1e9
        metrics.append(
            ProbeMetric(
                "ring-attention-busbw-gbps",
                busbw,
                help="K/V ring bytes moved per second of step time, GB/s "
                "(per device; compute-bound runs sit well below link rate)",
            )
        )
        details["serial_seconds_per_op"] = serial_seconds
        details["overlap_efficiency"] = round(efficiency, 3)
        details["busbw_gbps"] = round(busbw, 3)
        rated = rated_for(devices[0].device_kind)
        if rated is not None and devices[0].platform == "tpu":
            # the schedule's link ceiling: one direction per hop for
            # serial/overlap, both directions (full duplex) for bidir —
            # same model as probes/ici.py's ring comparator
            ceiling = rated.ici_unidir_gbps * (2 if variant == "bidir" else 1)
            metrics.append(
                ProbeMetric(
                    "ring-attention-busbw-fraction-of-rated",
                    busbw / ceiling,
                    help="Ring-attention sustained busbw / rated link "
                    "ceiling for the schedule (1x unidir link; 2x for bidir)",
                )
            )
            details["busbw_fraction_of_rated"] = round(busbw / ceiling, 4)

    summary = (
        f"ring attention ({variant}) over {n} devices: err {max_err:.1e} "
        f"({'OK' if correct else 'MISMATCH'}), "
        f"{tokens_per_second:,.0f} tok/s @ seq {seq}"
    )
    if "overlap_efficiency" in details:
        summary += f", overlap {details['overlap_efficiency']:.2f}x serial"
    result = ProbeResult(
        ok=correct,
        metrics=metrics,
        summary=summary,
        details=details,
    )
    # compute-roofline verdict per device (obs/roofline.py): big
    # sequences put attention right of the ridge (compute-bound —
    # roughly seq/2 FLOPs per byte), so a low roofline fraction here
    # reads "MXU underused", while a healthy compute-bound verdict next
    # to a low busbw fraction says the overlap is doing its job.
    # Analytic cost model only: the collective-carrying shard_map chain
    # has no meaningful single-op XLA cost.
    block_bytes = (
        batch * seq_per_device * heads * head_dim * jnp.dtype(dtype).itemsize
    )
    roofline_model.apply(
        result,
        roofline_model.capture(
            "ring-attention",
            seconds=seconds,
            model_flops=flops / n,  # per device, like the timing
            # per ring round each device streams its Q block plus the
            # visiting K/V block and maintains the output accumulator
            model_bytes=float((3 * n + 1) * block_bytes),
            enabled=roofline,
        ),
    )
    return result
