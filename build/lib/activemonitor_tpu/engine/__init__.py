"""Workflow execution engines (submit/poll boundary)."""

from activemonitor_tpu.engine.base import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    WF_API_VERSION,
    WF_KIND,
    WorkflowEngine,
    generate_name,
)
from activemonitor_tpu.engine.fake import (
    FakeWorkflowEngine,
    fail_after,
    never_complete,
    succeed_after,
)
from activemonitor_tpu.engine.local import LocalProcessEngine

__all__ = [
    "FakeWorkflowEngine",
    "LocalProcessEngine",
    "PHASE_FAILED",
    "PHASE_PENDING",
    "PHASE_RUNNING",
    "PHASE_SUCCEEDED",
    "WF_API_VERSION",
    "WF_KIND",
    "WorkflowEngine",
    "fail_after",
    "generate_name",
    "never_complete",
    "succeed_after",
]
