"""kubectl-verb CLI parity in cluster mode: apply/get/describe/delete
route through the Kubernetes client when --client k8s, with describe
reading the Events API (VERDICT r1 item 6)."""

import yaml

import pytest

from activemonitor_tpu.__main__ import _apply, _delete, _describe, _get, build_parser

from tests.kube_harness import stub_env

GROUP, VERSION, PLURAL = "activemonitor.keikoproj.io", "v1alpha1", "healthchecks"

HC_YAML = """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata:
  name: cli-hc
  namespace: default
spec:
  repeatAfterSec: 60
  level: cluster
  workflow:
    generateName: cli-
    workflowtimeout: 10
    resource:
      namespace: default
      serviceAccount: cli-sa
      source:
        inline: |
          apiVersion: argoproj.io/v1alpha1
          kind: Workflow
          spec:
            entrypoint: main
"""


def write_kubeconfig(tmp_path, server_url):
    path = tmp_path / "kubeconfig"
    path.write_text(
        yaml.safe_dump(
            {
                "current-context": "stub",
                "contexts": [
                    {"name": "stub", "context": {"cluster": "c", "user": "u"}}
                ],
                "clusters": [{"name": "c", "cluster": {"server": server_url}}],
                "users": [{"name": "u", "user": {"token": ""}}],
            }
        )
    )
    return str(path)


def parse(argv):
    return build_parser().parse_args(argv)


@pytest.mark.asyncio
async def test_cli_apply_get_delete_roundtrip_k8s(tmp_path, capsys):
    async with stub_env() as (server, _):
        kubeconfig = write_kubeconfig(tmp_path, server.url)
        manifest = tmp_path / "hc.yaml"
        manifest.write_text(HC_YAML)

        rc = await _apply(
            parse(["apply", "--client", "k8s", "--kubeconfig", kubeconfig,
                   "-f", str(manifest)])
        )
        assert rc == 0
        assert server.obj(GROUP, VERSION, PLURAL, "default", "cli-hc") is not None

        rc = await _get(
            parse(["get", "hc", "cli-hc", "--client", "k8s",
                   "--kubeconfig", kubeconfig, "-o", "yaml"])
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "cli-hc" in out and "repeatAfterSec: 60" in out

        rc = await _delete(
            parse(["delete", "cli-hc", "--client", "k8s",
                   "--kubeconfig", kubeconfig])
        )
        assert rc == 0
        assert server.obj(GROUP, VERSION, PLURAL, "default", "cli-hc") is None

        rc = await _delete(
            parse(["delete", "cli-hc", "--client", "k8s",
                   "--kubeconfig", kubeconfig])
        )
        assert rc == 1  # not found


@pytest.mark.asyncio
async def test_cli_describe_reads_events_api(tmp_path, capsys):
    async with stub_env() as (server, api):
        kubeconfig = write_kubeconfig(tmp_path, server.url)
        server.seed(GROUP, VERSION, PLURAL, yaml.safe_load(HC_YAML))
        # events as the controller would post them
        for reason, message in [
            ("Normal", "Successfully created workflow"),
            ("Warning", "Workflow timed out"),
        ]:
            server.seed(
                "",
                "v1",
                "events",
                {
                    "metadata": {"name": f"cli-hc.{reason.lower()}", "namespace": "default"},
                    "involvedObject": {"kind": "HealthCheck", "name": "cli-hc"},
                    "type": reason,
                    "reason": reason,
                    "message": message,
                    "lastTimestamp": "2026-07-29T00:00:00Z",
                },
            )
        # noise from another object must not show up
        server.seed(
            "",
            "v1",
            "events",
            {
                "metadata": {"name": "other.1", "namespace": "default"},
                "involvedObject": {"kind": "Pod", "name": "other"},
                "type": "Normal",
                "message": "irrelevant",
            },
        )

        rc = await _describe(
            parse(["describe", "cli-hc", "--client", "k8s",
                   "--kubeconfig", kubeconfig])
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "Name:       cli-hc" in out
        assert "Successfully created workflow" in out
        assert "Workflow timed out" in out
        assert "irrelevant" not in out
        assert "Events (2 recorded):" in out


@pytest.mark.asyncio
async def test_cli_get_table_lists_k8s_checks(tmp_path, capsys):
    async with stub_env() as (server, _):
        kubeconfig = write_kubeconfig(tmp_path, server.url)
        server.seed(GROUP, VERSION, PLURAL, yaml.safe_load(HC_YAML))
        rc = await _get(
            parse(["get", "--client", "k8s", "--kubeconfig", kubeconfig])
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "cli-hc" in out
