"""The native Kubernetes REST layer against the stub API server.

This pair (KubeApi ↔ StubApiServer) is the foundation of the cluster-
mode test tier — the analogue of the reference's envtest harness
(reference: internal/controllers/suite_test.go:67-134), so its own
semantics (conflicts, watch, subresources) are pinned down here first.
"""

import asyncio

import pytest

from activemonitor_tpu.kube import ApiError, KubeApi, KubeConfig, api_path, core_path
from activemonitor_tpu.kube.stub import merge_patch

from tests.kube_harness import stub_env

GROUP, VERSION, PLURAL = "activemonitor.keikoproj.io", "v1alpha1", "healthchecks"


def hc_body(name="hc-a", namespace="health"):
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "HealthCheck",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"repeatAfterSec": 60},
    }


@pytest.mark.asyncio
async def test_crud_roundtrip():
    async with stub_env() as (_, api):
        path = api_path(GROUP, VERSION, PLURAL, namespace="health")
        created = await api.create(path, hc_body())
        assert created["metadata"]["resourceVersion"]
        assert created["metadata"]["uid"]

        got = await api.get(api_path(GROUP, VERSION, PLURAL, "health", "hc-a"))
        assert got["spec"]["repeatAfterSec"] == 60

        listed = await api.get(path)
        assert len(listed["items"]) == 1

        await api.delete(api_path(GROUP, VERSION, PLURAL, "health", "hc-a"))
        with pytest.raises(ApiError) as e:
            await api.get(api_path(GROUP, VERSION, PLURAL, "health", "hc-a"))
        assert e.value.not_found


@pytest.mark.asyncio
async def test_create_existing_conflicts():
    async with stub_env() as (_, api):
        path = api_path(GROUP, VERSION, PLURAL, namespace="health")
        await api.create(path, hc_body())
        with pytest.raises(ApiError) as e:
            await api.create(path, hc_body())
        assert e.value.conflict


@pytest.mark.asyncio
async def test_generate_name():
    async with stub_env() as (_, api):
        path = api_path("argoproj.io", "v1alpha1", "workflows", namespace="health")
        body = {"metadata": {"generateName": "check-"}, "spec": {}}
        created = await api.create(path, body)
        assert created["metadata"]["name"].startswith("check-")
        assert len(created["metadata"]["name"]) > len("check-")


@pytest.mark.asyncio
async def test_stale_resource_version_conflicts():
    async with stub_env() as (_, api):
        col = api_path(GROUP, VERSION, PLURAL, namespace="health")
        created = await api.create(col, hc_body())
        obj_path = api_path(GROUP, VERSION, PLURAL, "health", "hc-a")
        stale_rv = created["metadata"]["resourceVersion"]

        created["spec"]["repeatAfterSec"] = 30
        updated = await api.replace(obj_path, created)
        assert updated["metadata"]["resourceVersion"] != stale_rv

        # replay with the stale rv -> 409
        created["metadata"]["resourceVersion"] = stale_rv
        with pytest.raises(ApiError) as e:
            await api.replace(obj_path, created)
        assert e.value.conflict


@pytest.mark.asyncio
async def test_status_subresource_is_isolated():
    async with stub_env() as (_, api):
        col = api_path(GROUP, VERSION, PLURAL, namespace="health")
        await api.create(col, hc_body())
        status_path = api_path(GROUP, VERSION, PLURAL, "health", "hc-a", "status")
        await api.merge_patch(
            status_path, {"status": {"status": "Succeeded"}, "spec": "x"}
        )
        got = await api.get(api_path(GROUP, VERSION, PLURAL, "health", "hc-a"))
        # spec untouched by a status write; status landed
        assert got["spec"]["repeatAfterSec"] == 60
        assert got["status"]["status"] == "Succeeded"


def test_merge_patch_deletes_on_null():
    assert merge_patch({"a": 1, "b": {"c": 2, "d": 3}}, {"b": {"c": None}, "e": 4}) == {
        "a": 1,
        "b": {"d": 3},
        "e": 4,
    }


@pytest.mark.asyncio
async def test_watch_sees_existing_then_live_events():
    async with stub_env() as (_, api):
        col = api_path(GROUP, VERSION, PLURAL, namespace="health")
        await api.create(col, hc_body("hc-pre"))

        events = []
        got_two = asyncio.Event()

        async def consume():
            async for ev in api.watch(api_path(GROUP, VERSION, PLURAL)):
                events.append((ev["type"], ev["object"]["metadata"]["name"]))
                if len(events) >= 2:
                    got_two.set()
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.1)  # watch established (synthetic ADDED delivered)
        await api.create(col, hc_body("hc-live"))
        await asyncio.wait_for(got_two.wait(), 5)
        task.cancel()
        assert events == [("ADDED", "hc-pre"), ("ADDED", "hc-live")]


@pytest.mark.asyncio
async def test_watch_survives_large_objects():
    """A watch event bigger than aiohttp's default 64 KiB line buffer
    must not kill the stream (etcd allows ~1.5 MiB objects)."""
    async with stub_env() as (_, api):
        col = api_path(GROUP, VERSION, PLURAL, namespace="health")
        big = hc_body("hc-big")
        # a schema'd string field (unknown keys would be pruned)
        big["spec"]["description"] = "x" * (1 << 20)  # ~1 MiB
        await api.create(col, big)
        async for ev in api.watch(api_path(GROUP, VERSION, PLURAL), timeout_seconds=5):
            assert ev["object"]["metadata"]["name"] == "hc-big"
            assert len(ev["object"]["spec"]["description"]) == 1 << 20
            break


@pytest.mark.asyncio
async def test_watch_resume_and_410():
    async with stub_env() as (server, api):
        col = api_path(GROUP, VERSION, PLURAL, namespace="health")
        created = await api.create(col, hc_body("hc-a"))
        rv = created["metadata"]["resourceVersion"]
        await api.delete(api_path(GROUP, VERSION, PLURAL, "health", "hc-a"))

        # resume from rv: only the DELETED event replays
        events = []
        async for ev in api.watch(
            api_path(GROUP, VERSION, PLURAL), resource_version=rv, timeout_seconds=1
        ):
            events.append(ev["type"])
            break
        assert events == ["DELETED"]

        # evict history -> too-old rv surfaces as 410
        for _ in range(3):
            await api.create(col, hc_body("hc-churn"))
            await api.delete(api_path(GROUP, VERSION, PLURAL, "health", "hc-churn"))
        server._history[:] = server._history[-1:]
        with pytest.raises(ApiError) as e:
            async for _ in api.watch(
                api_path(GROUP, VERSION, PLURAL), resource_version=rv
            ):
                pass
        assert e.value.status == 410


@pytest.mark.asyncio
async def test_bearer_token_auth():
    async with stub_env(token="sekret") as (server, good):
        bad = KubeApi(KubeConfig(server=server.url))
        try:
            with pytest.raises(ApiError) as e:
                await bad.get(core_path("serviceaccounts", "health"))
            assert e.value.status == 401
        finally:
            await bad.close()

        listed = await good.get(core_path("serviceaccounts", "health"))
        assert listed["items"] == []


def test_server_url_with_path_prefix_is_preserved():
    """Proxied clusters (Rancher etc.) serve the API under a path prefix;
    it must survive in front of /api|/apis (an RFC 3986 join would
    replace it)."""
    api = KubeApi(KubeConfig(server="https://host/k8s/clusters/c-abc"))
    assert api._url("/api/v1/pods") == "https://host/k8s/clusters/c-abc/api/v1/pods"
    api2 = KubeApi(KubeConfig(server="https://host/k8s/clusters/c-abc/"))
    assert api2._url("/apis/x/v1/y") == "https://host/k8s/clusters/c-abc/apis/x/v1/y"


def test_bearer_token_rotates_from_file(tmp_path):
    """Bound SA tokens rotate; a file-backed config must pick up the
    new token after the TTL instead of caching the boot-time one."""
    from activemonitor_tpu.kube import KubeConfig

    tok = tmp_path / "token"
    tok.write_text("token-v1\n")
    cfg = KubeConfig(server="https://api", token="token-v1", token_file=str(tok))
    assert cfg.bearer_token() == "token-v1"
    tok.write_text("token-v2\n")
    assert cfg.bearer_token() == "token-v1"  # inside the TTL: cached
    cfg._file_token.expire()  # TTL elapsed
    assert cfg.bearer_token() == "token-v2"


def test_exec_plugin_credentials(tmp_path):
    """kubeconfig user.exec plugins (gke-gcloud-auth-plugin shape): run
    the command, parse ExecCredential, cache until expirationTimestamp."""
    import stat

    from activemonitor_tpu.kube import KubeConfig

    plugin = tmp_path / "fake-auth-plugin"
    counter = tmp_path / "calls"
    plugin.write_text(
        "#!/bin/sh\n"
        f"echo x >> {counter}\n"
        'echo \'{"apiVersion": "client.authentication.k8s.io/v1beta1",'
        ' "kind": "ExecCredential", "status": {"token": "exec-token-1",'
        ' "expirationTimestamp": "2999-01-01T00:00:00Z"}}\'\n'
    )
    plugin.chmod(plugin.stat().st_mode | stat.S_IEXEC)
    cfg = KubeConfig(server="https://api", exec_spec={"command": str(plugin)})
    assert cfg.bearer_token() == "exec-token-1"
    assert cfg.bearer_token() == "exec-token-1"  # cached: far-future expiry
    assert counter.read_text().count("x") == 1


def test_exec_plugin_failures_are_explained(tmp_path):
    import stat

    from activemonitor_tpu.kube import KubeConfig
    from activemonitor_tpu.kube.config import KubeConfigError

    bad = tmp_path / "broken-plugin"
    bad.write_text("#!/bin/sh\necho nope >&2\nexit 3\n")
    bad.chmod(bad.stat().st_mode | stat.S_IEXEC)
    cfg = KubeConfig(server="https://api", exec_spec={"command": str(bad)})
    with pytest.raises(KubeConfigError, match="exited 3"):
        cfg.bearer_token()


def test_kubeconfig_with_exec_user_loads(tmp_path):
    import yaml

    from activemonitor_tpu.kube.config import kubeconfig_file_config

    path = tmp_path / "config"
    path.write_text(
        yaml.safe_dump(
            {
                "current-context": "gke",
                "contexts": [{"name": "gke", "context": {"cluster": "c", "user": "u"}}],
                "clusters": [{"name": "c", "cluster": {"server": "https://1.2.3.4"}}],
                "users": [
                    {
                        "name": "u",
                        "user": {
                            "exec": {
                                "apiVersion": "client.authentication.k8s.io/v1beta1",
                                "command": "gke-gcloud-auth-plugin",
                            }
                        },
                    }
                ],
            }
        )
    )
    cfg = kubeconfig_file_config(str(path))
    assert cfg is not None and cfg.exec_spec["command"] == "gke-gcloud-auth-plugin"


def test_kubeconfig_unsupported_auth_provider_is_explained(tmp_path):
    import yaml

    from activemonitor_tpu.kube.config import KubeConfigError, kubeconfig_file_config

    path = tmp_path / "config"
    path.write_text(
        yaml.safe_dump(
            {
                "current-context": "old",
                "contexts": [{"name": "old", "context": {"cluster": "c", "user": "u"}}],
                "clusters": [{"name": "c", "cluster": {"server": "https://1.2.3.4"}}],
                "users": [{"name": "u", "user": {"auth-provider": {"name": "gcp"}}}],
            }
        )
    )
    with pytest.raises(KubeConfigError, match="gcp"):
        kubeconfig_file_config(str(path))


def test_kubeconfig_env_is_a_colon_separated_list(tmp_path, monkeypatch):
    """kubectl semantics: $KUBECONFIG may list several files; the first
    with a usable current-context wins."""
    import yaml

    from activemonitor_tpu.kube.config import kubeconfig_file_config

    empty = tmp_path / "empty"
    empty.write_text("{}")
    good = tmp_path / "good"
    good.write_text(
        yaml.safe_dump(
            {
                "current-context": "c",
                "contexts": [{"name": "c", "context": {"cluster": "c", "user": "u"}}],
                "clusters": [{"name": "c", "cluster": {"server": "http://127.0.0.1:1"}}],
                "users": [{"name": "u", "user": {"token": "t"}}],
            }
        )
    )
    import os

    monkeypatch.setenv("KUBECONFIG", f"{empty}{os.pathsep}{good}")
    cfg = kubeconfig_file_config()
    assert cfg is not None and cfg.token == "t"


def test_malformed_kubeconfig_is_a_loud_error(tmp_path, monkeypatch):
    """A named-but-broken kubeconfig must error, never silently fall
    through to other credential sources (wrong-cluster hazard)."""
    from activemonitor_tpu.kube.config import (
        KubeConfigError,
        kubeconfig_file_config,
        load_kube_config,
    )

    path = tmp_path / "config"
    path.write_text("just a string")
    with pytest.raises(KubeConfigError, match="malformed"):
        kubeconfig_file_config(str(path))
    # ...including via $KUBECONFIG discovery
    monkeypatch.setenv("KUBECONFIG", str(path))
    with pytest.raises(KubeConfigError, match="malformed"):
        load_kube_config()
    # a MISSING file is not an error (fall through to other sources)
    assert kubeconfig_file_config(str(tmp_path / "nope")) is None


@pytest.mark.asyncio
async def test_core_and_cluster_scoped_paths():
    async with stub_env() as (_, api):
        # core v1 namespaced (serviceaccounts) and rbac cluster-scoped
        sa = await api.create(
            core_path("serviceaccounts", "health"),
            {"metadata": {"name": "probe-sa"}},
        )
        assert sa["metadata"]["namespace"] == "health"
        role = await api.create(
            api_path("rbac.authorization.k8s.io", "v1", "clusterroles"),
            {"metadata": {"name": "probe-role"}, "rules": []},
        )
        assert "namespace" not in role["metadata"]
        got = await api.get(
            api_path(
                "rbac.authorization.k8s.io", "v1", "clusterroles", name="probe-role"
            )
        )
        assert got["metadata"]["name"] == "probe-role"


@pytest.mark.asyncio
async def test_owner_reference_cascade_delete():
    """Deleting an owner garbage-collects everything carrying its uid
    in ownerReferences — transitively — and the deletions travel as
    watch DELETED events (the apiserver GC behavior the controller's
    None-workflow path anticipates on HealthCheck delete)."""
    async with stub_env() as (server, api):
        hc_path = api_path(
            "activemonitor.keikoproj.io", "v1alpha1", "healthchecks", "health"
        )
        hc = await api.create(
            hc_path,
            {
                "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
                "kind": "HealthCheck",
                "metadata": {"name": "owner", "namespace": "health"},
                "spec": {"repeatAfterSec": 60},
            },
        )
        uid = hc["metadata"]["uid"]
        wf = await api.create(
            api_path("argoproj.io", "v1alpha1", "workflows", "health"),
            {
                "kind": "Workflow",
                "metadata": {
                    "generateName": "owned-",
                    "ownerReferences": [
                        {"kind": "HealthCheck", "name": "owner", "uid": uid}
                    ],
                },
            },
        )
        # a grandchild owned by the workflow cascades too
        await api.create(
            core_path("pods", "health"),
            {
                "kind": "Pod",
                "metadata": {
                    "name": "owned-pod",
                    "ownerReferences": [
                        {"kind": "Workflow", "uid": wf["metadata"]["uid"]}
                    ],
                },
            },
        )
        # an unrelated object with a DIFFERENT owner uid survives
        await api.create(
            api_path("argoproj.io", "v1alpha1", "workflows", "health"),
            {
                "kind": "Workflow",
                "metadata": {
                    "name": "unowned",
                    "ownerReferences": [{"kind": "HealthCheck", "uid": "other"}],
                },
            },
        )

        events = []

        async def watch_workflows():
            async for ev in api.watch(
                api_path("argoproj.io", "v1alpha1", "workflows"),
                timeout_seconds=5,
            ):
                events.append((ev["type"], ev["object"]["metadata"].get("name")))
                if ev["type"] == "DELETED":
                    return

        task = asyncio.ensure_future(watch_workflows())
        await asyncio.sleep(0.05)
        await api.delete(f"{hc_path}/owner")
        await asyncio.wait_for(task, timeout=5)

        remaining = {
            o["metadata"].get("name")
            for o in server.objs("argoproj.io", "v1alpha1", "workflows")
        }
        assert remaining == {"unowned"}
        assert server.objs("", "v1", "pods") == []  # grandchild GC'd
        assert ("DELETED", wf["metadata"]["name"]) in events


@pytest.mark.asyncio
async def test_cascade_delete_with_multiple_owners():
    """Multiple ownerReferences are legal: an object reachable through
    TWO owners in one cascade must be deleted exactly once, not crash
    the DELETE with a double-remove."""
    async with stub_env() as (server, api):
        p = api_path("argoproj.io", "v1alpha1", "workflows", "health")
        a = await api.create(p, {"kind": "Workflow", "metadata": {"name": "a"}})
        b = await api.create(
            p,
            {
                "kind": "Workflow",
                "metadata": {
                    "name": "b",
                    "ownerReferences": [{"uid": a["metadata"]["uid"]}],
                },
            },
        )
        await api.create(
            p,
            {
                "kind": "Workflow",
                "metadata": {
                    "name": "c",
                    "ownerReferences": [
                        {"uid": a["metadata"]["uid"]},
                        {"uid": b["metadata"]["uid"]},
                    ],
                },
            },
        )
        await api.delete(f"{p}/a")
        assert server.objs("argoproj.io", "v1alpha1", "workflows") == []


@pytest.mark.asyncio
async def test_lease_non_canonical_microtime_rejected():
    """The stub plays the STRICT RFC3339Micro parser old apiservers
    shipped: a Lease write whose renewTime omits the six fractional
    digits (datetime.isoformat at microsecond 0) is a 400 decode
    error, while the canonical utils.clock.micro_time form is stored.
    This pins the hardening docs/conformance.md could previously only
    describe."""
    import datetime

    from activemonitor_tpu.utils.clock import micro_time

    async with stub_env() as (_, api):
        path = api_path("coordination.k8s.io", "v1", "leases", "kube-system")
        now = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
        with pytest.raises(ApiError) as exc:
            await api.create(
                path,
                {
                    "kind": "Lease",
                    "metadata": {"name": "am-leader"},
                    # microsecond == 0: isoformat drops the fraction
                    "spec": {"holderIdentity": "a", "renewTime": now.isoformat()},
                },
            )
        assert exc.value.status == 400
        assert "RFC3339Micro" in str(exc.value)
        created = await api.create(
            path,
            {
                "kind": "Lease",
                "metadata": {"name": "am-leader"},
                "spec": {"holderIdentity": "a", "renewTime": micro_time(now)},
            },
        )
        # a PATCH smuggling a non-canonical time is rejected the same way
        with pytest.raises(ApiError) as exc:
            await api.merge_patch(
                f"{path}/am-leader",
                {"spec": {"acquireTime": "2026-01-01T00:00:00Z"}},
            )
        assert exc.value.status == 400
        assert created["spec"]["renewTime"] == "2026-01-01T00:00:00.000000Z"


@pytest.mark.asyncio
async def test_schema_registered_resource_prunes_unknown_fields():
    """Structural-schema pruning: unknown fields vanish at decode time
    (create AND post-merge patch), schema'd siblings survive, and
    untyped subtrees (metadata, free-form maps) keep everything — so a
    controller relying on an unschema'd field loses it in tests the
    same way it would against a real apiserver."""
    async with stub_env() as (server, api):
        path = api_path(
            "activemonitor.keikoproj.io", "v1alpha1", "healthchecks", "health"
        )
        created = await api.create(
            path,
            {
                "apiVersion": "activemonitor.keikoproj.io/v1alpha1",
                "kind": "HealthCheck",
                "metadata": {
                    "name": "pruned",
                    "namespace": "health",
                    "labels": {"free": "form"},  # untyped: preserved
                },
                "spec": {
                    "repeatAfterSec": 60,
                    "bogus": "dropped",
                    "workflow": {
                        "generateName": "p-",
                        "extraneous": {"x": 1},
                    },
                },
            },
        )
        assert "bogus" not in created["spec"]
        assert "extraneous" not in created["spec"]["workflow"]
        assert created["spec"]["repeatAfterSec"] == 60
        assert created["metadata"]["labels"] == {"free": "form"}
        stored = server.obj(
            "activemonitor.keikoproj.io", "v1alpha1", "healthchecks",
            "health", "pruned",
        )
        assert "bogus" not in stored["spec"]
        patched = await api.merge_patch(
            f"{path}/pruned", {"spec": {"smuggled": True, "repeatAfterSec": 90}}
        )
        assert "smuggled" not in patched["spec"]
        assert patched["spec"]["repeatAfterSec"] == 90
