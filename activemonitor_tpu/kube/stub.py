"""In-process stub Kubernetes API server.

The reference's integration tier runs a real kube-apiserver via envtest
(reference: internal/controllers/suite_test.go:67-134) — the data model
is real, no controllers run. This module is that tier for this
framework: a generic aiohttp server speaking enough of the Kubernetes
REST dialect for every cluster-mode component to run against it for
real — CRUD + generateName, resourceVersion conflict semantics, the
status subresource, JSON merge patch, list + streaming watch, and
optional bearer-token auth. Resource-agnostic by design: HealthChecks,
Argo Workflows, RBAC objects, Leases and Events all flow through the
same store, like an API server with ``x-kubernetes-preserve-unknown-
fields`` CRDs installed (the reference's trick for Argo Workflows,
config/crd/bases/argoproj_v1alpha1_workflows.yaml).
"""

from __future__ import annotations

import asyncio
import copy
import json
import secrets
from typing import Dict, List, Tuple

Key = Tuple[str, str, str]  # (group, version, plural); core v1 -> ("", "v1", ...)


def _match_selector(obj: dict, selector: str) -> bool:
    """Equality-based labelSelector (``k=v,k2=v2``) — the subset the
    framework's clients use."""
    if not selector:
        return True
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        k, _, v = clause.partition("=")
        if labels.get(k) != v:
            return False
    return True


def merge_patch(target, patch):
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    result = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = merge_patch(result.get(k), v)
    return result


class StubApiServer:
    """Start with :meth:`start`, point a :class:`KubeApi` at ``.url``."""

    def __init__(self, token: str = ""):
        self._token = token
        self._objects: Dict[Key, Dict[Tuple[str, str], dict]] = {}
        self._rv = 0
        # bounded event history for watch resume; (rv, key, event)
        self._history: List[Tuple[int, Key, str, dict]] = []
        self._watchers: List[Tuple[Key, str, str, asyncio.Queue]] = []
        self._runner = None
        self.url = ""
        self.requests: List[Tuple[str, str]] = []  # (method, path) log
        # chaos injection (see inject_fault / drop_watches / latency)
        self.faults: List[dict] = []
        self.latency = 0.0
        # TokenReview/SubjectAccessReview tables (kube-native scrape
        # auth tests): token -> username it authenticates as, and the
        # set of usernames allowed to GET non-resource /metrics
        self.scrape_tokens: Dict[str, str] = {}
        self.metrics_allowed_users: set = set()

    # -- store ----------------------------------------------------------
    def _bump(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _bucket(self, key: Key) -> Dict[Tuple[str, str], dict]:
        return self._objects.setdefault(key, {})

    def _broadcast(self, key: Key, namespace: str, type_: str, obj: dict) -> None:
        event = {"type": type_, "object": copy.deepcopy(obj)}
        self._history.append((self._rv, key, namespace, event))
        del self._history[:-1000]
        for wkey, wns, selector, queue in self._watchers:
            if (
                wkey == key
                and (not wns or wns == namespace)
                and _match_selector(obj, selector)
            ):
                queue.put_nowait(event)

    # test-visible accessors -------------------------------------------
    def obj(self, group: str, version: str, plural: str, namespace: str, name: str):
        return self._bucket((group, version, plural)).get((namespace, name))

    def objs(self, group: str, version: str, plural: str) -> List[dict]:
        return list(self._bucket((group, version, plural)).values())

    def seed(self, group: str, version: str, plural: str, obj: dict) -> dict:
        """Directly place an object (test fixture setup)."""
        meta = obj.setdefault("metadata", {})
        meta.setdefault("resourceVersion", self._bump())
        meta.setdefault("uid", secrets.token_hex(8))
        key = (group, version, plural)
        namespace = meta.get("namespace", "")
        self._bucket(key)[(namespace, meta["name"])] = obj
        self._broadcast(key, namespace, "ADDED", obj)
        return obj

    # -- chaos injection (the fault-injection tier: SURVEY.md §5.3) ----
    def inject_fault(
        self,
        path_substr: str,
        *,
        status: int = 500,
        times: int = 1,
        method: str = "",
    ) -> None:
        """The next ``times`` requests whose path contains
        ``path_substr`` (and match ``method``, if given) fail with
        ``status``. Faults are consumed in registration order."""
        self.faults.append(
            {
                "path_substr": path_substr,
                "status": status,
                "remaining": times,
                "method": method.upper(),
            }
        )

    def _consume_fault(self, request):
        for fault in self.faults:
            if fault["remaining"] <= 0:
                continue
            if fault["method"] and fault["method"] != request.method:
                continue
            if fault["path_substr"] not in request.path:
                continue
            fault["remaining"] -= 1
            return self._error(
                fault["status"], f"chaos: injected {fault['status']}"
            )
        return None

    def drop_watches(self) -> int:
        """Abruptly end every live watch stream (the client sees EOF and
        must reconnect). Returns how many streams were dropped."""
        dropped = 0
        for _, _, _, queue in list(self._watchers):
            queue.put_nowait(None)  # sentinel: close the stream
            dropped += 1
        return dropped

    # -- lifecycle ------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        from aiohttp import web

        # accept bodies up to what etcd would (default 1 MiB is too small)
        app = web.Application(
            middlewares=[self._auth_middleware], client_max_size=4 * 1024**2
        )
        # longest patterns first: aiohttp resolves dynamic routes in
        # registration order, and /apis/{g}/{v}/{plural}/{name} would
        # otherwise swallow /apis/{g}/{v}/namespaces/{ns}/{plural}
        patterns = [
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}/status", True),
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}/{name}", False),
            ("/apis/{group}/{version}/namespaces/{namespace}/{plural}", None),
            ("/apis/{group}/{version}/{plural}/{name}/status", True),
            ("/apis/{group}/{version}/{plural}/{name}", False),
            ("/apis/{group}/{version}/{plural}", None),
            ("/api/v1/namespaces/{namespace}/{plural}/{name}", False),
            ("/api/v1/namespaces/{namespace}/{plural}", None),
            ("/api/v1/{plural}/{name}", False),
            ("/api/v1/{plural}", None),
        ]
        for pattern, status_sub in patterns:
            if status_sub is None:  # collection
                app.router.add_get(pattern, self._handle_list_or_watch)
                app.router.add_post(pattern, self._handle_create)
            else:
                handler = self._handle_status if status_sub else self._handle_object
                app.router.add_get(pattern, handler)
                app.router.add_put(pattern, handler)
                app.router.add_patch(pattern, handler)
                if not status_sub:
                    app.router.add_delete(pattern, handler)
        # don't wait out live watch streams on cleanup (default 60 s)
        self._runner = web.AppRunner(app, shutdown_timeout=0.25)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual_port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://{host}:{actual_port}"
        return self.url

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- request plumbing ----------------------------------------------
    @staticmethod
    def _parse(request) -> Tuple[Key, str, str]:
        info = request.match_info
        group = info.get("group", "")
        version = info.get("version", "v1")
        return (
            (group, version, info["plural"]),
            info.get("namespace", ""),
            info.get("name", ""),
        )

    # default StatusReason per HTTP code, mirroring apimachinery's
    # reasonAndCodeForError mapping — the conformance fixtures
    # (tests/fixtures/apiserver/) pin these against the real wire shape
    _REASONS = {
        400: "BadRequest",
        401: "Unauthorized",
        403: "Forbidden",
        404: "NotFound",
        405: "MethodNotAllowed",
        409: "Conflict",
        410: "Expired",
        422: "Invalid",
        500: "InternalError",
        503: "ServiceUnavailable",
    }

    @staticmethod
    def _qualified(key: Key) -> str:
        """Resource rendering in real Status messages: grouped resources
        as ``plural.group``, core (empty-group) resources as bare
        ``plural`` — never a trailing dot."""
        return f"{key[2]}.{key[0]}" if key[0] else key[2]

    @classmethod
    def _status_body(
        cls, status: int, message: str, reason: str = "", details: dict | None = None
    ) -> dict:
        body = {
            "kind": "Status",
            "apiVersion": "v1",
            "metadata": {},
            "status": "Failure",
            "message": message,
            "reason": reason or cls._REASONS.get(status, ""),
            "code": status,
        }
        if details:
            body["details"] = details
        return body

    @classmethod
    def _error(
        cls, status: int, message: str, reason: str = "", details: dict | None = None
    ):
        from aiohttp import web

        return web.json_response(
            cls._status_body(status, message, reason, details), status=status
        )

    from aiohttp import web as _web  # for the middleware decorator

    @_web.middleware
    async def _auth_middleware(self, request, handler):
        self.requests.append((request.method, request.path))
        if self._token:
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {self._token}":
                return self._error(401, "Unauthorized")
        if self.latency:
            await asyncio.sleep(self.latency)
        injected = self._consume_fault(request)
        if injected is not None:
            return injected
        return await handler(request)

    # -- handlers -------------------------------------------------------
    async def _handle_list_or_watch(self, request):
        from aiohttp import web

        key, namespace, _ = self._parse(request)
        if request.query.get("watch") == "true":
            return await self._serve_watch(request, key, namespace)
        selector = request.query.get("labelSelector", "")
        items = [
            copy.deepcopy(obj)
            for (ns, _), obj in self._bucket(key).items()
            if (not namespace or ns == namespace)
            and _match_selector(obj, selector)
        ]
        return web.json_response(
            {
                "kind": "List",
                "items": items,
                "metadata": {"resourceVersion": str(self._rv)},
            }
        )

    async def _serve_watch(self, request, key: Key, namespace: str):
        from aiohttp import web

        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()

        selector = request.query.get("labelSelector", "")
        start_rv = request.query.get("resourceVersion", "")
        if start_rv:
            oldest = self._history[0][0] if self._history else self._rv + 1
            if int(start_rv) + 1 < oldest and int(start_rv) < self._rv:
                # requested window already evicted — real apiserver
                # sends an ERROR event whose object is a full Status
                # with reason Expired
                line = json.dumps(
                    {
                        "type": "ERROR",
                        "object": self._status_body(
                            410,
                            f"too old resource version: {start_rv} ({self._rv})",
                            reason="Expired",
                        ),
                    }
                )
                await resp.write(line.encode() + b"\n")
                return resp
            backlog = [
                ev
                for rv, k, ns, ev in self._history
                if k == key
                and (not namespace or ns == namespace)
                and rv > int(start_rv)
                and _match_selector(ev.get("object", {}), selector)
            ]
        else:
            # no resourceVersion: synthesize ADDED for current state
            backlog = [
                {"type": "ADDED", "object": copy.deepcopy(obj)}
                for (ns, _), obj in self._bucket(key).items()
                if (not namespace or ns == namespace)
                and _match_selector(obj, selector)
            ]
        entry = (key, namespace, selector, queue)
        self._watchers.append(entry)
        try:
            for ev in backlog:
                await resp.write(json.dumps(ev).encode() + b"\n")
            timeout = float(request.query.get("timeoutSeconds", "300"))
            loop = asyncio.get_event_loop()
            deadline = loop.time() + timeout
            while True:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    ev = await asyncio.wait_for(queue.get(), timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if ev is None:  # drop_watches sentinel: abrupt stream end
                    break
                await resp.write(json.dumps(ev).encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self._watchers.remove(entry)
        return resp

    async def _handle_create(self, request):
        from aiohttp import web

        key, namespace, _ = self._parse(request)
        body = await request.json()
        if key[2] in ("tokenreviews", "subjectaccessreviews"):
            # review APIs evaluate and answer — nothing is stored
            return web.json_response(self._evaluate_review(key[2], body), status=201)
        meta = body.setdefault("metadata", {})
        if namespace:
            meta["namespace"] = namespace
        name = meta.get("name", "")
        if not name:
            generate = meta.get("generateName")
            if not generate:
                return self._error(422, "name or generateName is required")
            name = generate + secrets.token_hex(3)[:5]
            meta["name"] = name
        if (namespace, name) in self._bucket(key):
            # real apiserver: 409 with reason AlreadyExists (distinct
            # from optimistic-concurrency Conflict at the same code)
            return self._error(
                409,
                f'{self._qualified(key)} "{name}" already exists',
                reason="AlreadyExists",
                details={"name": name, "group": key[0], "kind": key[2]},
            )
        meta["resourceVersion"] = self._bump()
        meta["uid"] = secrets.token_hex(8)
        meta.setdefault("creationTimestamp", _now_iso())
        self._bucket(key)[(namespace, name)] = body
        self._broadcast(key, namespace, "ADDED", body)
        return web.json_response(copy.deepcopy(body), status=201)

    def _evaluate_review(self, plural: str, body: dict) -> dict:
        """The authentication/authorization review APIs, table-driven:
        ``scrape_tokens`` authenticates, ``metrics_allowed_users``
        authorizes GETs of the non-resource /metrics path."""
        spec = body.get("spec") or {}
        if plural == "tokenreviews":
            username = self.scrape_tokens.get(spec.get("token", ""))
            status = (
                {"authenticated": True, "user": {"username": username, "groups": []}}
                if username
                else {"authenticated": False}
            )
        else:
            attrs = spec.get("nonResourceAttributes") or {}
            status = {
                "allowed": (
                    spec.get("user", "") in self.metrics_allowed_users
                    and attrs.get("path") == "/metrics"
                    and attrs.get("verb") == "get"
                )
            }
        return {**body, "status": status}

    async def _handle_object(self, request):
        return await self._object_rw(request, status_only=False)

    async def _handle_status(self, request):
        if request.method == "GET":
            return self._error(405, "GET on status subresource not supported")
        return await self._object_rw(request, status_only=True)

    async def _object_rw(self, request, status_only: bool):
        from aiohttp import web

        key, namespace, name = self._parse(request)
        existing = self._bucket(key).get((namespace, name))
        if existing is None:
            return self._error(
                404,
                f'{self._qualified(key)} "{name}" not found',
                details={"name": name, "group": key[0], "kind": key[2]},
            )

        if request.method == "GET":
            return web.json_response(copy.deepcopy(existing))

        if request.method == "DELETE":
            del self._bucket(key)[(namespace, name)]
            self._bump()
            self._broadcast(key, namespace, "DELETED", existing)
            return web.json_response(
                {
                    "kind": "Status",
                    "apiVersion": "v1",
                    "metadata": {},
                    "status": "Success",
                    "details": {
                        "name": name,
                        "group": key[0],
                        "kind": key[2],
                        "uid": existing["metadata"].get("uid", ""),
                    },
                }
            )

        body = await request.json()
        # optimistic concurrency: a stale resourceVersion in the payload
        # is a conflict (this is what RetryOnConflict paths exercise)
        claimed = (body.get("metadata") or {}).get("resourceVersion")
        if claimed and claimed != existing["metadata"]["resourceVersion"]:
            return self._error(
                409,
                f'Operation cannot be fulfilled on {self._qualified(key)} "{name}": '
                "the object has been modified; please apply your changes to "
                "the latest version and try again",
                reason="Conflict",
                details={"name": name, "group": key[0], "kind": key[2]},
            )

        if request.method == "PUT":
            updated = body
            if status_only:
                updated = copy.deepcopy(existing)
                updated["status"] = body.get("status")
            else:
                # status is a subresource: a main-resource replace never
                # touches it (real API-server behavior for CRDs with the
                # status subresource enabled)
                updated.pop("status", None)
                if "status" in existing:
                    updated["status"] = existing["status"]
        else:  # PATCH (JSON merge patch)
            patch = {"status": body.get("status")} if status_only else body
            updated = merge_patch(existing, patch)
        meta = updated.setdefault("metadata", {})
        meta["name"] = name
        if namespace:
            meta["namespace"] = namespace
        meta["uid"] = existing["metadata"].get("uid", secrets.token_hex(8))
        meta["resourceVersion"] = self._bump()
        self._bucket(key)[(namespace, name)] = updated
        self._broadcast(key, namespace, "MODIFIED", updated)
        return web.json_response(copy.deepcopy(updated))


def _now_iso() -> str:
    import datetime

    return (
        datetime.datetime.now(datetime.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )
