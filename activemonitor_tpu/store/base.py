"""ArtifactReader protocol and dispatch (reference: internal/store/store.go:10-22)."""

from __future__ import annotations

from typing import Protocol, runtime_checkable

from activemonitor_tpu.api.types import ArtifactLocation


class UnknownArtifactLocation(ValueError):
    """No reader exists for the given artifact location."""


@runtime_checkable
class ArtifactReader(Protocol):
    """Reads a workflow manifest from some source."""

    def read(self) -> bytes:  # pragma: no cover - protocol
        ...


def get_artifact_reader(loc: ArtifactLocation) -> ArtifactReader:
    """Return the reader for a location.

    Dispatch order matches the reference (inline, then URL;
    store/store.go:15-21) with file support added after, so existing
    specs resolve identically.
    """
    from activemonitor_tpu.store.file import FileReader
    from activemonitor_tpu.store.inline import InlineReader
    from activemonitor_tpu.store.url import URLReader

    if loc.inline is not None:
        return InlineReader(loc.inline)
    if loc.url is not None:
        return URLReader(loc.url)
    if loc.file is not None:
        return FileReader(loc.file)
    raise UnknownArtifactLocation(f"unknown artifact location: {loc!r}")


def is_blocking_source(loc) -> bool:
    """True when reading this location performs real I/O (an HTTP
    fetch, a disk/NFS read) — callers on an event loop should move the
    read to a worker thread. Lives NEXT TO the dispatch above so the
    two can never disagree about which reader a spec resolves to:
    inline wins over everything and does zero I/O; every other reader
    blocks."""
    if loc is None or getattr(loc, "inline", None) is not None:
        return False
    return (
        getattr(loc, "url", None) is not None
        or getattr(loc, "file", None) is not None
    )
