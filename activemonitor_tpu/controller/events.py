"""Event recording.

The reference emits a Kubernetes Event on every significant transition
(~40 call sites; reference: healthcheck_controller.go:135 recorder,
SURVEY.md §5.5). Here events always land in structured logs and an
in-memory ring (queryable by tests and the CLI); a Kubernetes-backed
recorder can wrap this one in cluster mode.
"""

from __future__ import annotations

import collections
import datetime
import logging
from dataclasses import dataclass, field
from typing import Deque, List

from activemonitor_tpu.api.types import HealthCheck

log = logging.getLogger("activemonitor.events")

EVENT_NORMAL = "Normal"
EVENT_WARNING = "Warning"

# Declared reason vocabulary — every EventRecorder.event() call site
# must draw its reason from this table (the reference free-hands reason
# strings at ~40 call sites; dashboards grouping on reason then break
# on typos). tests/test_lint.py walks the AST of the whole package and
# rejects any reason literal not listed here.
REASON_NORMAL = "Normal"
REASON_WARNING = "Warning"
EVENT_REASONS = frozenset({REASON_NORMAL, REASON_WARNING})


@dataclass
class Event:
    type: str
    reason: str
    message: str
    namespace: str
    name: str
    timestamp: datetime.datetime = field(
        default_factory=lambda: datetime.datetime.now(datetime.timezone.utc)
    )
    # trace of the reconcile cycle that emitted this event ("" outside
    # any span) — the correlation key shared with JSON log lines and
    # /debug/traces
    trace_id: str = ""

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "namespace": self.namespace,
            "name": self.name,
            "timestamp": self.timestamp.isoformat(),
            "trace_id": self.trace_id,
        }


class EventRecorder:
    def __init__(self, capacity: int = 1000):
        self._events: Deque[Event] = collections.deque(maxlen=capacity)

    def event(self, hc: HealthCheck, type_: str, reason: str, message: str) -> None:
        from activemonitor_tpu.obs.trace import current_trace_id

        ev = Event(
            type=type_,
            reason=reason,
            message=message,
            namespace=hc.metadata.namespace,
            name=hc.metadata.name,
            trace_id=current_trace_id(),
        )
        self._events.append(ev)
        level = logging.WARNING if type_ == EVENT_WARNING else logging.INFO
        log.log(level, "%s/%s: %s: %s", ev.namespace, ev.name, reason, message)

    def events_for(self, namespace: str, name: str) -> List[Event]:
        return [e for e in self._events if e.namespace == namespace and e.name == name]

    @property
    def all(self) -> List[Event]:
        return list(self._events)

    def close(self) -> None:
        """Release any transport resources (no-op for the in-memory ring)."""


class FileEventRecorder(EventRecorder):
    """Also appends events to JSONL sidecars under ``<dir>/.events/`` so
    the ``describe`` CLI (a separate process) can show a check's recent
    history — the local-mode analogue of Events in ``kubectl describe``.
    Files are capped by line count to bound disk use."""

    def __init__(self, directory: str, capacity: int = 1000, max_lines: int = 200):
        super().__init__(capacity=capacity)
        import pathlib

        self._dir = pathlib.Path(directory) / ".events"
        self._dir.mkdir(parents=True, exist_ok=True)
        self._max_lines = max_lines
        # we are the only writer: line counts are cached so the steady
        # state is a pure append — the file is re-read only when the
        # cached count hits the cap (then trimmed in one rewrite)
        self._line_counts: dict = {}

    def _path(self, namespace: str, name: str):
        return self._dir / f"{namespace}__{name}.jsonl"

    def event(self, hc: HealthCheck, type_: str, reason: str, message: str) -> None:
        super().event(hc, type_, reason, message)
        import json

        path = self._path(hc.metadata.namespace or "default", hc.metadata.name)
        line = json.dumps(
            {
                "time": datetime.datetime.now(datetime.timezone.utc).isoformat(),
                "type": type_,
                "reason": reason,
                "message": message,
            }
        )
        try:
            count = self._line_counts.get(path)
            if count is None:
                count = len(path.read_text().splitlines()) if path.exists() else 0
            if count >= self._max_lines:
                # trim to a low watermark so the cap is hit (and the
                # file rewritten) once per max_lines/2 events, not on
                # every append thereafter
                keep = self._max_lines // 2
                lines = path.read_text().splitlines()[-keep:]
                path.write_text("\n".join(lines) + "\n")
                count = len(lines)
            with path.open("a") as f:
                f.write(line + "\n")
            self._line_counts[path] = count + 1
        except OSError:
            log.exception("failed to persist event for %s", hc.key)

    @staticmethod
    def read_events(directory: str, namespace: str, name: str) -> List[dict]:
        import json
        import pathlib

        path = pathlib.Path(directory) / ".events" / f"{namespace}__{name}.jsonl"
        if not path.exists():
            return []
        out = []
        for line in path.read_text().splitlines():
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out


class KubernetesEventRecorder(EventRecorder):
    """Also posts core/v1 Events against the HealthCheck object, like the
    reference's record.EventRecorder (reference: healthcheck_controller.go:135,
    ~40 call sites). Built on the native REST layer; failures to post are
    logged, never raised — events are best-effort."""

    def __init__(self, api=None, component: str = "active-monitor-tpu"):
        super().__init__()
        if api is None:
            from activemonitor_tpu.kube import KubeApi

            api = KubeApi.from_default_config()
        self._api = api
        self._component = component
        # posts are serialized through a bounded queue drained by one
        # task: recorder.event() is a sync call on async reconcile paths
        # and must never block on the API server
        import asyncio

        self._queue: asyncio.Queue = asyncio.Queue(maxsize=1000)
        self._worker: asyncio.Task | None = None

    def event(self, hc: HealthCheck, type_: str, reason: str, message: str) -> None:
        super().event(hc, type_, reason, message)
        import asyncio
        import uuid

        namespace = hc.metadata.namespace or "default"
        now = datetime.datetime.now(datetime.timezone.utc).isoformat()
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{hc.metadata.name}.{uuid.uuid4().hex[:12]}",
                "namespace": namespace,
            },
            "involvedObject": {
                "apiVersion": hc.api_version,
                "kind": hc.kind,
                "name": hc.metadata.name,
                "namespace": namespace,  # must match the event's namespace
                "uid": hc.metadata.uid or None,
            },
            "reason": reason,
            "message": message,
            "type": type_,
            "source": {"component": self._component},
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
        }
        try:
            self._queue.put_nowait((namespace, body, hc.key))
        except asyncio.QueueFull:
            log.warning("event queue full; dropping event for %s", hc.key)
            return
        if self._worker is None or self._worker.done():
            try:
                self._worker = asyncio.get_running_loop().create_task(self._drain())
            except RuntimeError:
                pass  # no loop (sync CLI context) — events stay local

    async def _drain(self) -> None:
        from activemonitor_tpu.kube import core_path

        while True:
            namespace, body, key = await self._queue.get()
            try:
                await self._api.request(
                    "POST", core_path("events", namespace), body=body, timeout=10
                )
            except Exception:
                log.exception("failed to post event for %s", key)
            finally:
                self._queue.task_done()

    async def flush(self) -> None:
        """Wait until every queued event has been posted (tests and
        orderly shutdown)."""
        await self._queue.join()

    def close(self) -> None:
        """Drop pending posts and release the drain task (called on
        manager shutdown)."""
        if self._worker is not None:
            self._worker.cancel()
            self._worker = None
