"""Ring attention — sequence-parallel attention over the device mesh.

The long-context path of the framework: the sequence axis is sharded
across devices, K/V blocks rotate around the ring via ``ppermute``
while each device accumulates attention for its resident Q block with
an online (flash-style) softmax — peak memory stays O(S/n) per device
and all communication is neighbor-hop ICI traffic that overlaps with
block compute under XLA's scheduler.

TRAINING-GRADE: the op carries a ``jax.custom_vjp``. The forward scan
also produces the GLOBAL logsumexp per query row; the backward runs a
second ring pass that rotates K/V again and recomputes each block's
probabilities as ``p = exp(s − lse_global)`` — exact global attention
probabilities, so per-block dK/dV contributions sum exactly. The dK/dV
accumulators rotate WITH their K/V blocks (the accumulator for block j
starts at home, visits every device collecting that device's Q-block
contribution, and lands home after n hops), keeping backward memory
O(S/n) per device too — the sequence-parallel axis can appear in a
differentiated train step (build_sharded_train_step(attention="ring")).

Used by the ``ring-attention`` probe both as a correctness check
(sequence-parallel result must match single-device attention) and as a
sequence-parallelism bandwidth/throughput canary for long-context
workloads.

Shapes inside ``shard_map`` (per device): q, k, v are
``[batch, seq_local, heads, head_dim]``; the global sequence is
``seq_local × n_devices`` with device i owning the i-th contiguous
block. Causality is enforced blockwise: a KV block strictly after the
Q block is skipped entirely, the diagonal block gets the triangular
mask, earlier blocks attend fully.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, mask):
    """Scores for one (Q-block, KV-block) pair.

    Returns (scores_max, exp_scores @ v, exp_scores row sums) for the
    online-softmax accumulation. q: [B,Sq,H,D]; k,v: [B,Sk,Hkv,D] with
    Hkv dividing H (GQA: each group of H//Hkv query heads shares a K/V
    head); mask: [Sq,Sk] bool (True = attend) or None. The merge state
    comes back q-head-indexed ([B,H,Sq]) regardless of grouping.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    batch, seq_q, heads, head_dim = q.shape
    heads_kv = k.shape[2]
    group = heads // heads_kv
    # upcast K/V here, not before the ring rotation: ppermute moves the
    # input-dtype blocks, so bf16 inputs cost bf16 (not f32) ICI traffic
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    qg = q.reshape(batch, seq_q, heads_kv, group, head_dim)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, None, :, :], scores, _NEG_INF)
    block_max = jnp.max(scores, axis=-1)  # [B,Hkv,G,Sq]
    exp = jnp.exp(scores - block_max[..., None])
    if mask is not None:
        # rows with no visible keys: exp(NEG_INF - NEG_INF) = 1 — zero them
        any_visible = jnp.any(mask, axis=-1)  # [Sq]
        exp = exp * any_visible[None, None, None, :, None]
    out = jnp.einsum("bhgqk,bkhd->bqhgd", exp, v).reshape(
        batch, seq_q, heads, head_dim
    )
    denom = jnp.sum(exp, axis=-1)  # [B,Hkv,G,Sq]
    return (
        block_max.reshape(batch, heads, seq_q),
        out,
        denom.reshape(batch, heads, seq_q),
    )


def _ring_attention_sharded(
    q, k, v, *, axis_name: str, n_devices: int, causal: bool, use_flash: bool
):
    """Body run per device inside shard_map; returns ``(out, lse)``
    where ``lse`` is the GLOBAL logsumexp per query row (the backward
    pass's residual). The ring rotation is a ``lax.scan`` — one traced
    step regardless of ring size, so compile time and HLO size stay
    flat as slices grow. With ``use_flash`` the per-step block compute
    runs the fused Pallas kernel (ops/flash_attention.py partial mode)
    instead of XLA einsums — same (max, unnormalized out, denom) merge
    contract, but the local score matrix stays in VMEM."""
    my_idx = jax.lax.axis_index(axis_name)
    batch, seq_local, heads, head_dim = q.shape

    causal_mask = jnp.tril(jnp.ones((seq_local, seq_local), jnp.bool_))
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    if use_flash:
        from activemonitor_tpu.ops.flash_attention import flash_attention_partial

    qf = q.astype(jnp.float32)
    init = (
        k,  # rotated in input dtype — bf16 inputs keep bf16 ICI traffic
        v,
        jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),  # acc
        jnp.zeros((batch, heads, seq_local), jnp.float32),  # denom
        jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),  # running max
    )

    def step_fn(carry, step):
        kf, vf, acc, denom, running_max = carry
        kv_idx = (my_idx - step) % n_devices  # owner of the current K/V block
        def skip(_q_in, _kf, _vf):
            # one skip state for every branch construct below: a
            # (NEG_INF max, zero acc, zero denom) triple the merge
            # treats as an empty block (operands arrive because every
            # lax.cond branch shares the signature)
            return (
                jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),
                jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),
                jnp.zeros((batch, heads, seq_local), jnp.float32),
            )

        if use_flash:
            # fused path: diagonal block runs the causal kernel, earlier
            # blocks the unmasked one — two pallas variants under
            # lax.switch so each step's compute stays in VMEM. The
            # kernel upcasts internally, so it gets the ORIGINAL-dtype q
            # (bf16 inputs keep bf16 Q-block HBM traffic; the f32 qf
            # exists for the XLA einsum path)
            def attend_full(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=False)

            def attend_diag(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=True)

            if causal:
                branch = (
                    (kv_idx < my_idx).astype(jnp.int32)
                    + 2 * (kv_idx == my_idx).astype(jnp.int32)
                )  # 0 = skip (kv after us), 1 = full, 2 = diagonal
                block_max, block_out, block_denom = jax.lax.switch(
                    branch, (skip, attend_full, attend_diag), q, kf, vf
                )
            else:
                block_max, block_out, block_denom = attend_full(q, kf, vf)
        elif causal:
            # kv block strictly after our q block ⇒ nothing to attend:
            # skip the einsums entirely (lax.cond, so the dead ~half of
            # the causal grid costs nothing at runtime); diagonal block
            # gets the triangular mask, earlier blocks attend fully
            def attend(qf, kf, vf):
                mask = jnp.where(
                    kv_idx == my_idx, causal_mask, jnp.ones_like(causal_mask)
                )
                return _block_attend(qf, kf, vf, mask)

            block_max, block_out, block_denom = jax.lax.cond(
                kv_idx > my_idx, skip, attend, qf, kf, vf
            )
        else:
            block_max, block_out, block_denom = _block_attend(qf, kf, vf, None)
        new_max = jnp.maximum(running_max, block_max)
        old_scale = jnp.exp(running_max - new_max)
        blk_scale = jnp.exp(block_max - new_max)
        acc = acc * old_scale.transpose(0, 2, 1)[..., None] + block_out * (
            blk_scale.transpose(0, 2, 1)[..., None]
        )
        denom = denom * old_scale + block_denom * blk_scale
        # rotate K/V to the next neighbor (the final rotation returns
        # them home — a no-op cost-wise next to n-1 real hops)
        kf = jax.lax.ppermute(kf, axis_name, perm)
        vf = jax.lax.ppermute(vf, axis_name, perm)
        return (kf, vf, acc, denom, new_max), None

    (_, _, acc, denom, running_max), _ = jax.lax.scan(
        step_fn, init, jnp.arange(n_devices)
    )
    out = acc / jnp.maximum(denom.transpose(0, 2, 1)[..., None], 1e-30)
    # global logsumexp per query row — the backward pass reconstructs
    # exact global probabilities from this (p = exp(s - lse)); clamped
    # like the flash kernel so fully-masked rows stay finite
    lse = jnp.maximum(running_max, _NEG_INF / 2) + jnp.log(
        jnp.maximum(denom, 1e-30)
    )  # [B, H, Sq] float32
    return out.astype(q.dtype), lse


def _ring_attention_bwd_sharded(
    q, k, v, out, lse, dout, *, axis_name: str, n_devices: int,
    causal: bool, use_flash: bool,
):
    """Second ring pass: dQ/dK/dV per device.

    K/V rotate around the ring exactly as in the forward; the float32
    dK/dV accumulators rotate IN LOCKSTEP, so the accumulator for block
    j is always resident with block j itself — each device adds its
    Q-block's contribution to whatever block is visiting, and after n
    hops every accumulator has collected all contributions and sits on
    its home device. dQ accumulates locally. With ``use_flash`` the
    per-block gradient math runs the fused backward kernels against the
    global statistics (flash_attention_backward_block); otherwise XLA
    einsums recompute s and p = exp(s − lse_global) directly."""
    my_idx = jax.lax.axis_index(axis_name)
    batch, seq_local, heads, head_dim = q.shape
    heads_kv = k.shape[2]
    group = heads // heads_kv  # GQA: grouped heads share a K/V head
    scale = 1.0 / (head_dim ** 0.5)
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]
    causal_mask = jnp.tril(jnp.ones((seq_local, seq_local), jnp.bool_))

    qf = q.astype(jnp.float32)
    dof = dout.astype(jnp.float32)
    # per-row correction Δ = rowsum(dO ∘ O), same as the single-chip
    # backward kernels (ops/flash_attention.py _backward_bhsd)
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, out.astype(jnp.float32))

    if use_flash:
        from activemonitor_tpu.ops.flash_attention import (
            flash_attention_backward_block,
        )

        def attend_full(q_in, kf, vf):
            return flash_attention_backward_block(
                q_in, kf, vf, lse, delta, dout, causal=False
            )

        def attend_diag(q_in, kf, vf):
            return flash_attention_backward_block(
                q_in, kf, vf, lse, delta, dout, causal=True
            )
    else:
        # grouped views: head index h = hkv*group + g, matching the
        # forward's reshape; dK/dV einsums sum over the group axis
        qg = qf.reshape(batch, seq_local, heads_kv, group, head_dim)
        dog = dof.reshape(batch, seq_local, heads_kv, group, head_dim)
        lse_g = lse.reshape(batch, heads_kv, group, seq_local)
        delta_g = delta.reshape(batch, heads_kv, group, seq_local)

        def _attend(_q_in, kf, vf, diagonal):
            kff = kf.astype(jnp.float32)
            vff = vf.astype(jnp.float32)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kff) * scale
            if diagonal:
                s = jnp.where(causal_mask[None, None, None], s, _NEG_INF)
            p = jnp.exp(s - lse_g[..., None])  # exact global probabilities
            dv_blk = jnp.einsum("bhgqk,bqhgd->bkhd", p, dog)
            dp = jnp.einsum("bqhgd,bkhd->bhgqk", dog, vff)
            ds = p * (dp - delta_g[..., None]) * scale
            dq_blk = jnp.einsum("bhgqk,bkhd->bqhgd", ds, kff).reshape(
                batch, seq_local, heads, head_dim
            )
            dk_blk = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qg)
            return dq_blk, dk_blk, dv_blk

        def attend_full(q_in, kf, vf):
            return _attend(q_in, kf, vf, diagonal=False)

        def attend_diag(q_in, kf, vf):
            return _attend(q_in, kf, vf, diagonal=True)

    def skip(_q_in, _kf, _vf):
        # lax.cond-branch signature parity; an out-of-window block
        # contributes zero to every gradient
        zq = jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32)
        zkv = jnp.zeros((batch, seq_local, heads_kv, head_dim), jnp.float32)
        return zq, zkv, zkv

    init = (
        k,  # rotates in input dtype, like the forward
        v,
        jnp.zeros((batch, seq_local, heads_kv, head_dim), jnp.float32),  # dk
        jnp.zeros((batch, seq_local, heads_kv, head_dim), jnp.float32),  # dv
        jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),  # dq
    )

    def step_fn(carry, step):
        kf, vf, dk, dv, dq = carry
        kv_idx = (my_idx - step) % n_devices
        if causal:
            branch = (
                (kv_idx < my_idx).astype(jnp.int32)
                + 2 * (kv_idx == my_idx).astype(jnp.int32)
            )  # 0 = skip (kv after us), 1 = full, 2 = diagonal
            dq_blk, dk_blk, dv_blk = jax.lax.switch(
                branch, (skip, attend_full, attend_diag), q, kf, vf
            )
        else:
            dq_blk, dk_blk, dv_blk = attend_full(q, kf, vf)
        dq = dq + dq_blk
        dk = dk + dk_blk
        dv = dv + dv_blk
        kf = jax.lax.ppermute(kf, axis_name, perm)
        vf = jax.lax.ppermute(vf, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
        return (kf, vf, dk, dv, dq), None

    (_, _, dk, dv, dq), _ = jax.lax.scan(step_fn, init, jnp.arange(n_devices))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_diff(q, k, v, axis_name, n_devices, causal, use_flash):
    out, _ = _ring_attention_sharded(
        q, k, v, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash,
    )
    return out


def _ring_diff_fwd(q, k, v, axis_name, n_devices, causal, use_flash):
    out, lse = _ring_attention_sharded(
        q, k, v, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash,
    )
    return out, (q, k, v, out, lse)


def _ring_diff_bwd(axis_name, n_devices, causal, use_flash, residuals, dout):
    q, k, v, out, lse = residuals
    return _ring_attention_bwd_sharded(
        q, k, v, out, lse, dout, axis_name=axis_name, n_devices=n_devices,
        causal=causal, use_flash=use_flash,
    )


_ring_diff.defvjp(_ring_diff_fwd, _ring_diff_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    use_flash: bool = False,
    in_spec: P | None = None,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh[axis]``, differentiable
    (custom VJP: the backward is a second K/V ring pass recomputing
    block probabilities from the saved global logsumexp).

    q, k, v: global ``[batch, seq, heads, head_dim]`` arrays; the seq
    dim is sharded over the axis. K/V may carry FEWER heads (GQA — any
    divisor of q's heads, down to 1 for MQA): the narrow K/V blocks are
    what rotates, so grouped heads shrink ICI traffic by the group
    factor, and dK/dV come back group-summed in K/V's own shape.
    Returns attention output with q's global shape/sharding.
    ``use_flash`` runs each ring step's block compute (forward AND
    backward) through the fused Pallas kernels. ``in_spec`` overrides
    the shard_map partitioning for composed meshes — e.g.
    ``P("data", "sp", "model", None)`` to run the ring inside a
    dp×tp×sp train step (batch and heads are embarrassingly parallel
    for the ring; only position 1, the sequence dim, must carry
    ``axis``).
    """
    n = mesh.shape[axis]
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA needs n_heads ({q.shape[2]}) divisible by n_kv_heads "
            f"({k.shape[2]})"
        )
    spec = in_spec if in_spec is not None else P(None, axis, None, None)
    if len(spec) > 1 and spec[1] != axis:
        raise ValueError(
            f"in_spec must shard the sequence dim (position 1) over {axis!r}, got {spec}"
        )
    def body(q, k, v):
        # positional call: custom_vjp rejects keyword arguments
        return _ring_diff(q, k, v, axis, n, causal, use_flash)

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True, segment_ids=None) -> jax.Array:
    """Single-device attention for correctness checks.

    Generalized the same way as the fused kernel
    (ops/flash_attention.py): K/V may carry fewer heads (GQA — each
    group of ``n_heads // n_kv_heads`` query heads shares a K/V head),
    a different sequence length (causal masking bottom-right aligned:
    query row i attends keys ≤ i + seq_k − seq_q, the decode
    convention; equal lengths reduce to the standard mask), and packed
    sequences (``segment_ids``: one [B, S] array or a (q_ids, kv_ids)
    tuple — attention only within matching segments)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    heads, heads_kv = q.shape[2], k.shape[2]
    if heads != heads_kv:
        k = jnp.repeat(k, heads // heads_kv, axis=2)
        v = jnp.repeat(v, heads // heads_kv, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = q.shape[1], k.shape[1]
        q_pos = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
        mask = q_pos >= jnp.arange(seq_k)[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            q_seg, kv_seg = segment_ids
        else:
            q_seg = kv_seg = segment_ids
        seg = q_seg[:, :, None] == kv_seg[:, None, :]  # [B, Sq, Sk]
        scores = jnp.where(seg[:, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)
