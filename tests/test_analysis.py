"""Baseline & anomaly-detection layer tests (ISSUE 4): the rolling
baseline statistics, the detector chain and hysteresis, cohort
straggler ranking, durable-status persistence — and the acceptance
slice: a FakeClock+FakeEngine scripted check whose matmul TFLOPs step
from 100% to 70% of baseline walks ``healthcheck_anomaly_state``
ok→warning→degraded with hysteresis (no flap on a single outlier),
survives a simulated controller restart through ``.status.analysis``,
and surfaces the degraded mark in ``/statusz`` and ``am-tpu status``.
"""

import asyncio
import collections
import json

import pytest

from activemonitor_tpu.analysis import (
    AnalysisEngine,
    CheckBaselines,
    CohortIndex,
    DetectorConfig,
    Hysteresis,
    LEVEL_DEGRADED,
    LEVEL_OK,
    LEVEL_WARNING,
    MetricBaseline,
    RatedFractionDetector,
    RobustZScoreDetector,
    TrendDetector,
)
from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_SUCCEEDED
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.utils.clock import FakeClock

METRIC = "mxu-matmul-tflops"
WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


def make_hc(name="hc-ana", analysis=None, remedy=False):
    spec = {
        "repeatAfterSec": 60,
        "level": "cluster",
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if analysis is not None:
        spec["analysis"] = analysis
    if remedy:
        spec["remedyworkflow"] = {
            "generateName": f"{name}-remedy-",
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        }
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


ANALYSIS_SPEC = {"warmupRuns": 5, "zThreshold": 3.0, "metrics": [METRIC]}


# ---------------------------------------------------------------------
# baseline statistics
# ---------------------------------------------------------------------


def test_welford_matches_textbook_mean_and_std():
    baseline = MetricBaseline()
    values = [10.0, 12.0, 14.0, 16.0, 18.0]
    for v in values:
        baseline.observe(v)
    assert baseline.n == 5
    assert baseline.mean == pytest.approx(14.0)
    # sample std of an arithmetic sequence step 2: sqrt(10)
    assert baseline.std == pytest.approx(10.0 ** 0.5)
    assert baseline.median == 14.0
    assert baseline.mad == 2.0


def test_median_mad_resist_one_wild_outlier():
    baseline = MetricBaseline()
    for v in [100.0] * 9 + [1000.0]:
        baseline.observe(v)
    assert baseline.median == 100.0
    assert baseline.mad == 0.0
    # the mean moved, the robust center did not — and the z of a normal
    # sample stays small while the outlier's own z is huge
    assert abs(baseline.zscore(100.0)) < 1.0
    assert baseline.zscore(1000.0) > 10.0


def test_constant_series_scale_is_floored_not_zero():
    baseline = MetricBaseline()
    for _ in range(5):
        baseline.observe(100.0)
    assert baseline.scale() == pytest.approx(5.0)  # 5% relative floor
    assert baseline.zscore(70.0) == pytest.approx(-6.0)


def test_nonfinite_samples_never_poison_the_accumulators():
    baseline = MetricBaseline()
    baseline.observe(10.0)
    baseline.observe(float("nan"))
    baseline.observe(float("inf"))
    assert baseline.n == 1
    assert baseline.mean == 10.0


def test_baseline_roundtrips_compactly_through_dict():
    baseline = MetricBaseline()
    for v in [100.0, 101.5, 98.75, 102.25, 99.0]:
        baseline.observe(v)
    restored = MetricBaseline.from_dict(json.loads(json.dumps(baseline.to_dict())))
    assert restored.n == baseline.n
    assert restored.mean == pytest.approx(baseline.mean, rel=1e-5)
    assert restored.median == baseline.median
    assert restored.zscore(70.0) == pytest.approx(baseline.zscore(70.0), rel=1e-4)


def test_check_baselines_warmup_gate_and_defensive_restore():
    clock = FakeClock()
    baselines = CheckBaselines(clock, warmup_runs=3)
    for v in [1.0, 2.0]:
        baselines.observe("m", v)
    assert not baselines.warmed("m")
    baselines.observe("m", 3.0)
    assert baselines.warmed("m")
    assert baselines.updated_at == clock.now()
    # garbage blobs restore to a fresh state, never raise
    for garbage in (None, [], "x", {"m": "nope"}, {"m": {"n": "NaN"}}, {3: {}}):
        restored = CheckBaselines.from_dict(garbage, clock, 3)
        assert restored.metrics() in ([], ["m"]) or True
    assert CheckBaselines.from_dict({"m": {"n": 2, "mean": 5.0}}, clock, 3).peek(
        "m"
    ).n == 2


# ---------------------------------------------------------------------
# detectors + hysteresis
# ---------------------------------------------------------------------


def warmed_baseline(values):
    baseline = MetricBaseline()
    for v in values:
        baseline.observe(v)
    return baseline


def test_zscore_detector_levels():
    detector = RobustZScoreDetector()
    config = DetectorConfig(z_threshold=3.0)
    baseline = warmed_baseline([100.0] * 8)  # scale floored at 5.0
    assert detector.evaluate(METRIC, 100.0, baseline, config) == LEVEL_OK
    assert detector.evaluate(METRIC, 80.0, baseline, config) == LEVEL_WARNING  # |z|=4
    assert detector.evaluate(METRIC, 70.0, baseline, config) == LEVEL_DEGRADED  # |z|=6
    # symmetric: a metric far ABOVE baseline is as anomalous
    assert detector.evaluate(METRIC, 130.0, baseline, config) == LEVEL_DEGRADED


def test_rated_fraction_detector_is_absolute_and_name_scoped():
    detector = RatedFractionDetector()
    config = DetectorConfig()
    assert detector.evaluate("mxu-matmul-tflops", 0.5, None, config) is None
    assert detector.evaluate("mxu-fraction-of-rated", 0.95, None, config) == LEVEL_OK
    assert (
        detector.evaluate("mxu-fraction-of-rated", 0.80, None, config)
        == LEVEL_WARNING
    )
    assert (
        detector.evaluate("ici_allreduce_fraction_of_rated", 0.60, None, config)
        == LEVEL_DEGRADED
    )


def test_trend_detector_catches_slow_creep_the_zscore_misses():
    config = DetectorConfig(z_threshold=3.0, trend_min_samples=8)
    # 1% decline per run: every step is well inside the noise band...
    values = [100.0 - i for i in range(12)]
    baseline = warmed_baseline(values[:-1])
    z = RobustZScoreDetector().evaluate(METRIC, values[-1], baseline, config)
    assert z == LEVEL_OK  # the point reading looks fine
    trend = TrendDetector().evaluate(METRIC, values[-1], baseline, config)
    assert trend == LEVEL_WARNING  # ~11% drift across the window
    # flat series: no drift
    flat = warmed_baseline([100.0] * 11)
    assert TrendDetector().evaluate(METRIC, 100.0, flat, config) == LEVEL_OK


def test_hysteresis_single_outlier_never_flaps():
    state = Hysteresis(confirm_runs=2, calm_runs=3)
    assert state.update(LEVEL_DEGRADED) is None  # one outlier: no move
    assert state.level == LEVEL_OK
    assert state.update(LEVEL_OK) is None  # back to normal: streak reset
    assert state.update(LEVEL_DEGRADED) is None  # another lone outlier
    assert state.level == LEVEL_OK


def test_hysteresis_escalates_one_step_per_confirmed_run():
    state = Hysteresis(confirm_runs=2, calm_runs=2)
    assert state.update(LEVEL_DEGRADED) is None
    assert state.update(LEVEL_DEGRADED) == (LEVEL_OK, LEVEL_WARNING)
    assert state.update(LEVEL_DEGRADED) is None  # streak restarts
    assert state.update(LEVEL_DEGRADED) == (LEVEL_WARNING, LEVEL_DEGRADED)
    # recovery is as deliberate: calm_runs of ok per step down
    assert state.update(LEVEL_OK) is None
    assert state.update(LEVEL_OK) == (LEVEL_DEGRADED, LEVEL_WARNING)
    assert state.update(LEVEL_OK) is None
    assert state.update(LEVEL_OK) == (LEVEL_WARNING, LEVEL_OK)


def test_hysteresis_roundtrips_through_dict():
    state = Hysteresis()
    state.update(LEVEL_DEGRADED)
    state.update(LEVEL_DEGRADED)
    restored = Hysteresis.from_dict(json.loads(json.dumps(state.to_dict())))
    assert restored.level == LEVEL_WARNING
    assert Hysteresis.from_dict({"level": 99}).level == LEVEL_DEGRADED  # clamped
    assert Hysteresis.from_dict({"level": "x"}).level == LEVEL_OK  # defensive


# ---------------------------------------------------------------------
# cohort straggler ranking
# ---------------------------------------------------------------------


def test_cohort_flags_the_straggler_slice():
    cohorts = CohortIndex()
    for i in range(5):
        cohorts.record("pool-a", METRIC, f"health/slice-{i}", 100.0 + i * 0.5)
    cohorts.record("pool-a", METRIC, "health/slice-sick", 60.0)
    outliers = cohorts.outliers("pool-a", METRIC)
    assert [key for key, _score in outliers] == ["health/slice-sick"]
    assert outliers[0][1] < 0  # below the cohort
    assert cohorts.is_outlier("pool-a", METRIC, "health/slice-sick")
    assert not cohorts.is_outlier("pool-a", METRIC, "health/slice-0")
    assert cohorts.worst_score("pool-a", "health/slice-sick") == outliers[0][1]


def test_cohort_below_minimum_size_gives_no_verdict():
    cohorts = CohortIndex()
    cohorts.record("pool-a", METRIC, "a/x", 100.0)
    cohorts.record("pool-a", METRIC, "a/y", 10.0)
    assert cohorts.scores("pool-a", METRIC) == {}
    assert cohorts.outliers("pool-a", METRIC) == []


def test_cohort_membership_moves_and_forgets():
    cohorts = CohortIndex()
    for i in range(3):
        cohorts.record("pool-a", METRIC, f"a/s{i}", 100.0)
    cohorts.record("pool-a", METRIC, "a/mover", 100.0)
    # the spec's cohort label changed: the old cohort must drop the member
    cohorts.record("pool-b", METRIC, "a/mover", 100.0)
    assert "a/mover" not in cohorts.scores("pool-a", METRIC)
    cohorts.forget("a/s0")
    assert "a/s0" not in cohorts.members("pool-a")


# ---------------------------------------------------------------------
# engine (unit level)
# ---------------------------------------------------------------------


def observe_n(engine, hc, values, start_run=0):
    verdicts = []
    for i, value in enumerate(values):
        verdicts.append(
            engine.observe(
                hc, {METRIC: value}, ok=True, run_id=f"wf-{start_run + i}"
            )
        )
    return verdicts


def test_engine_warmup_then_staircase_to_degraded():
    clock = FakeClock()
    metrics = MetricsCollector()
    engine = AnalysisEngine(clock, metrics)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    verdicts = observe_n(engine, hc, [100.0] * 5 + [70.0] * 4)
    states = [v.state for v in verdicts]
    assert states == ["ok"] * 6 + ["warning", "warning", "degraded"]
    transitions = [v.transition for v in verdicts if v.transition]
    assert transitions == [("ok", "warning"), ("warning", "degraded")]
    # the baseline never absorbed the degraded samples
    assert engine._checks[hc.key].baselines.peek(METRIC).median == 100.0
    # durable blob rides hc.status and is JSON-serializable
    blob = json.loads(json.dumps(hc.status.analysis))
    assert blob["state"] == "degraded"
    assert blob["baselines"][METRIC]["n"] == 5
    labels = {"healthcheck_name": "hc-ana", "namespace": "health", "state": "degraded"}
    assert metrics.sample_value("healthcheck_anomaly_state", labels) == 1.0
    assert metrics.sample_value(
        "healthcheck_metric_zscore",
        {"healthcheck_name": "hc-ana", "namespace": "health", "metric": "mxu_matmul_tflops"},
    ) == pytest.approx(-6.0)


def test_engine_single_outlier_keeps_lazy_ok_and_no_series():
    metrics = MetricsCollector()
    engine = AnalysisEngine(FakeClock(), metrics)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    verdicts = observe_n(engine, hc, [100.0] * 5 + [70.0] + [100.0] * 3)
    assert all(v.state == "ok" for v in verdicts)
    for state in ("ok", "warning", "degraded"):
        assert (
            metrics.sample_value(
                "healthcheck_anomaly_state",
                {"healthcheck_name": "hc-ana", "namespace": "health", "state": state},
            )
            is None
        )


def test_engine_same_run_id_is_observed_once():
    engine = AnalysisEngine(FakeClock(), None)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    for _ in range(10):
        engine.observe(hc, {METRIC: 100.0}, ok=True, run_id="wf-same")
    assert engine._checks[hc.key].baselines.peek(METRIC).n == 1


def test_engine_failed_runs_never_feed_the_baseline():
    engine = AnalysisEngine(FakeClock(), None)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    observe_n(engine, hc, [100.0] * 5)
    verdict = engine.observe(hc, {METRIC: 5.0}, ok=False, run_id="wf-fail")
    assert verdict.state == "ok"
    assert engine._checks[hc.key].baselines.peek(METRIC).n == 5


def test_engine_metrics_filter_and_spec_removal():
    metrics = MetricsCollector()
    engine = AnalysisEngine(FakeClock(), metrics)
    hc = make_hc(analysis={"warmupRuns": 2, "metrics": [METRIC]})
    engine.observe(
        hc, {METRIC: 100.0, "other-metric": 1.0}, ok=True, run_id="wf-0"
    )
    assert engine._checks[hc.key].baselines.metrics() == [METRIC]
    # the analysis: block edited off the live spec: state + series drop
    hc.spec.analysis = None
    assert engine.observe(hc, {METRIC: 100.0}, ok=True, run_id="wf-1") is None
    assert hc.key not in engine._checks
    assert hc.status.analysis is None
    baseline_labels = {
        "healthcheck_name": "hc-ana",
        "namespace": "health",
        "metric": "mxu_matmul_tflops",
        "stat": "count",
    }
    assert metrics.sample_value("healthcheck_metric_baseline", baseline_labels) is None


def test_engine_vanished_metric_decays_instead_of_sticking_degraded():
    """A metric the probe stops emitting must not hold the check
    degraded (damped, remedy-triggering) forever — it decays back to
    ok through the calm hysteresis, and the recovered entry is pruned
    while its baseline survives for a possible return."""
    engine = AnalysisEngine(FakeClock(), None)
    hc = make_hc(analysis={"warmupRuns": 5})  # no metrics[] filter
    observe_n(engine, hc, [100.0] * 5 + [70.0] * 4)
    assert engine.state(hc.key) == "degraded"
    # the probe stops emitting the metric: empty samples on ok runs
    states = []
    for i in range(8):
        verdict = engine.observe(hc, {}, ok=True, run_id=f"wf-gone-{i}")
        states.append(verdict.state)
    assert states[-1] == "ok"
    assert "degraded" not in states[3:]  # decayed, calm_runs per step
    assert engine._checks[hc.key].hysteresis == {}  # recovered entry pruned
    assert engine._checks[hc.key].baselines.peek(METRIC).n == 5  # kept


def test_engine_metric_filtered_out_drops_its_state_immediately():
    engine = AnalysisEngine(FakeClock(), None)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    observe_n(engine, hc, [100.0] * 5 + [70.0] * 4)
    assert engine.state(hc.key) == "degraded"
    # operator edits the filter to a different metric: the old entry
    # must not keep reporting (the probe still emits it, but it is no
    # longer under analysis)
    hc.spec.analysis.metrics = ["other-metric"]
    verdict = engine.observe(
        hc, {METRIC: 70.0, "other-metric": 1.0}, ok=True, run_id="wf-x"
    )
    assert verdict.state == "ok"
    assert METRIC not in engine._checks[hc.key].hysteresis


def test_removing_the_analysis_block_clears_blob_and_damp():
    """Spec removal must clear the durable blob even with no live
    engine state (restart between removal and next run), and the
    reconciler must lift the analysis schedule damping."""
    clock = FakeClock()
    # engine side: durable blob, fresh engine, spec removed
    hc = make_hc()  # no analysis block
    hc.status.analysis = {"v": 1, "state": "degraded", "baselines": {}}
    engine = AnalysisEngine(clock, None)
    assert engine.observe(hc, {METRIC: 1.0}, ok=True, run_id="wf") is None
    assert hc.status.analysis is None
    # reconciler side: degraded damping is lifted once no verdict comes
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    hc2 = make_hc(analysis=ANALYSIS_SPEC)
    for i, v in enumerate([100.0] * 5 + [70.0] * 4):
        reconciler._note_analysis(hc2, {METRIC: v}, ok=True, run_id=f"w{i}")
    assert reconciler.resilience.checks.damp_factor(hc2.key) == 2.0
    hc2.spec.analysis = None
    reconciler._note_analysis(hc2, {METRIC: 70.0}, ok=True, run_id="w99")
    assert reconciler.resilience.checks.damp_factor(hc2.key) == 1.0


def test_statusz_zscore_matches_the_exported_gauge():
    """summary() must report the z the gauge exported at run time, not
    a recompute against a baseline the sample itself already updated."""
    metrics = MetricsCollector()
    engine = AnalysisEngine(FakeClock(), metrics)
    hc = make_hc(analysis={"warmupRuns": 3})
    observe_n(engine, hc, [100.0, 101.0, 99.0, 102.0])
    gauge = metrics.sample_value(
        "healthcheck_metric_zscore",
        {"healthcheck_name": "hc-ana", "namespace": "health", "metric": "mxu_matmul_tflops"},
    )
    summary = engine.summary(hc)
    assert summary["metrics"][METRIC]["zscore"] == gauge


def test_engine_restores_state_from_durable_status_blob():
    clock = FakeClock()
    engine = AnalysisEngine(clock, None)
    hc = make_hc(analysis=ANALYSIS_SPEC)
    observe_n(engine, hc, [100.0] * 5 + [70.0] * 4)
    assert engine.state(hc.key) == "degraded"
    # "restart": a fresh engine adopts the blob the status write persisted
    hc2 = make_hc(analysis=ANALYSIS_SPEC)
    hc2.status.analysis = json.loads(json.dumps(hc.status.analysis))
    metrics2 = MetricsCollector()
    engine2 = AnalysisEngine(clock, metrics2)
    verdict = engine2.observe(hc2, {METRIC: 70.0}, ok=True, run_id="wf-r")
    assert verdict.state == "degraded"
    assert verdict.transition is None  # adopted, not re-derived from ok
    assert engine2._checks[hc2.key].baselines.peek(METRIC).median == 100.0
    # adoption materialized the one-hot trio immediately
    assert (
        metrics2.sample_value(
            "healthcheck_anomaly_state",
            {"healthcheck_name": "hc-ana", "namespace": "health", "state": "degraded"},
        )
        == 1.0
    )


def test_engine_summary_schema_for_statusz():
    engine = AnalysisEngine(FakeClock(), None)
    hc = make_hc(analysis={**ANALYSIS_SPEC, "cohort": "pool-a"})
    observe_n(engine, hc, [100.0] * 6)
    summary = engine.summary(hc)
    assert summary["state"] == "ok"
    assert summary["cohort"] == "pool-a"
    assert summary["metrics"][METRIC]["warmed_up"] is True
    assert summary["metrics"][METRIC]["baseline_median"] == 100.0
    assert summary["metrics"][METRIC]["last"] == 100.0
    assert engine.summary(make_hc(name="plain")) is None


# ---------------------------------------------------------------------
# acceptance: scripted FakeClock + FakeEngine end to end
# ---------------------------------------------------------------------


def scripted_engine(values):
    """FakeEngine whose Nth workflow succeeds on the first poll with
    the Nth scripted matmul TFLOPs sample in its contract."""
    engine = FakeWorkflowEngine()
    queue = collections.deque(values)
    assigned = {}

    def completer(wf, _count):
        name = wf["metadata"]["name"]
        if name not in assigned:
            if not queue:
                return None
            assigned[name] = queue.popleft()
        contract = json.dumps(
            {"metrics": [{"name": METRIC, "value": assigned[name]}]}
        )
        return {
            "phase": PHASE_SUCCEEDED,
            "outputs": {"parameters": [{"name": "metrics", "value": contract}]},
        }

    engine._default_completer = completer
    return engine


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


def build_controller(clock, client, values):
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine(values),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
    manager._health_addr = "127.0.0.1:0"
    return manager, reconciler, metrics


async def drive_runs(clock, count, interval=60.0, first=False):
    for i in range(count):
        if not first or i > 0:
            await clock.advance(interval)
        await settle()
        await clock.advance(1.0)
        await settle()


STATE_LABELS = lambda state: {  # noqa: E731 - tiny local shorthand
    "healthcheck_name": "hc-ana",
    "namespace": "health",
    "state": state,
}


@pytest.mark.asyncio
async def test_acceptance_step_degradation_statusz_cli_and_restart():
    import aiohttp

    from activemonitor_tpu.__main__ import render_status_table

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    values = [100.0] * 5 + [70.0] * 4
    manager, reconciler, metrics = build_controller(clock, client, values)
    await manager.start()
    try:
        hc = make_hc(analysis=ANALYSIS_SPEC)
        await client.apply(hc)
        key = "health/hc-ana"

        # warm-up: 5 runs at 100% — state ok, and the lazy one-hot has
        # materialized NO series (absence == ok)
        await drive_runs(clock, 5, first=True)
        assert reconciler.analysis.state(key) == "ok"
        for state in ("ok", "warning", "degraded"):
            assert metrics.sample_value(
                "healthcheck_anomaly_state", STATE_LABELS(state)
            ) is None

        # run 6: first 70% sample — a LONE outlier so far, so the
        # reported state must not move (hysteresis)
        await drive_runs(clock, 1)
        assert reconciler.analysis.state(key) == "ok"

        # run 7: deviation confirmed — ok -> warning
        await drive_runs(clock, 1)
        assert reconciler.analysis.state(key) == "warning"
        assert metrics.sample_value(
            "healthcheck_anomaly_state", STATE_LABELS("warning")
        ) == 1.0
        assert metrics.sample_value(
            "healthcheck_anomaly_state", STATE_LABELS("degraded")
        ) == 0.0

        # runs 8-9: warning -> degraded (one step per confirmed streak)
        await drive_runs(clock, 2)
        assert reconciler.analysis.state(key) == "degraded"
        assert metrics.sample_value(
            "healthcheck_anomaly_state", STATE_LABELS("degraded")
        ) == 1.0
        assert metrics.sample_value(
            "healthcheck_anomaly_state", STATE_LABELS("warning")
        ) == 0.0
        # the z-score gauge carries the deviation, the baseline held at 100
        assert metrics.sample_value(
            "healthcheck_metric_zscore",
            {
                "healthcheck_name": "hc-ana",
                "namespace": "health",
                "metric": "mxu_matmul_tflops",
            },
        ) == pytest.approx(-6.0)
        assert metrics.sample_value(
            "healthcheck_metric_baseline",
            {
                "healthcheck_name": "hc-ana",
                "namespace": "health",
                "metric": "mxu_matmul_tflops",
                "stat": "median",
            },
        ) == 100.0
        # degraded damps the schedule through the flap tracker's factor
        assert reconciler.resilience.checks.damp_factor(key) == 2.0
        # the run history carries the numeric samples (satellite: ring)
        last = reconciler.fleet.history.last(key)
        assert last.metrics == {METRIC: 70.0}

        # /statusz surfaces the degraded mark...
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port}/statusz") as r:
                assert r.status == 200
                payload = await r.json()
        [entry] = payload["checks"]
        assert entry["analysis"]["state"] == "degraded"
        assert entry["analysis"]["metrics"][METRIC]["state"] == "degraded"
        assert payload["fleet"]["anomalies"] == {"warning": 0, "degraded": 1}
        # ... and the am-tpu status table shows it in the ANOMALY column
        table = render_status_table(payload)
        header, row = table.splitlines()[1], table.splitlines()[2]
        assert header.split()[4] == "ANOMALY"
        assert row.split()[4] == "degraded"

        # the durable status carries the baseline blob the next
        # controller incarnation will adopt
        durable = await client.get("health", "hc-ana")
        assert durable.status.analysis["state"] == "degraded"
        assert durable.status.analysis["baselines"][METRIC]["n"] == 5
    finally:
        await manager.stop()

    # ---- simulated controller restart: fresh reconciler/engine/metrics
    # over the same durable store; the baseline and the degraded verdict
    # must come back from .status.analysis, not re-warm from scratch
    manager2, reconciler2, metrics2 = build_controller(clock, client, [70.0])
    await manager2.start()
    try:
        await settle()
        # the resumed schedule re-arms from durable status; fire it
        # (damped-interval upper bound: advance generously)
        await clock.advance(121.0)
        await settle()
        await clock.advance(1.0)
        await settle()
        key = "health/hc-ana"
        assert reconciler2.analysis.state(key) == "degraded"
        baseline = reconciler2.analysis._checks[key].baselines.peek(METRIC)
        assert baseline.n == 5  # restored, not re-learned
        assert baseline.median == 100.0
        assert metrics2.sample_value(
            "healthcheck_anomaly_state", STATE_LABELS("degraded")
        ) == 1.0
    finally:
        await manager2.stop()


@pytest.mark.asyncio
async def test_acceptance_single_outlier_does_not_flap_end_to_end():
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    values = [100.0] * 5 + [70.0] + [100.0] * 2
    manager, reconciler, metrics = build_controller(clock, client, values)
    await manager.start()
    try:
        await client.apply(make_hc(analysis=ANALYSIS_SPEC))
        await drive_runs(clock, len(values), first=True)
        assert reconciler.analysis.state("health/hc-ana") == "ok"
        # never left ok ⇒ zero anomaly series (cardinality contract)
        for state in ("ok", "warning", "degraded"):
            assert metrics.sample_value(
                "healthcheck_anomaly_state", STATE_LABELS(state)
            ) is None
        # and no schedule damping was requested
        assert reconciler.resilience.checks.damp_factor("health/hc-ana") == 1.0
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_trigger_on_degraded_runs_the_remedy_on_a_passing_run():
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    # remedy workflows are submitted through the same engine; the
    # completer hands every unseen workflow the next scripted value, so
    # append values for the remedy runs too
    values = [100.0] * 5 + [70.0] * 4 + [70.0] * 3
    manager, reconciler, metrics = build_controller(clock, client, values)
    engine = reconciler.engine
    await manager.start()
    try:
        hc = make_hc(
            analysis={**ANALYSIS_SPEC, "triggerOnDegraded": True}, remedy=True
        )
        await client.apply(hc)
        await drive_runs(clock, 9, first=True)
        assert reconciler.analysis.state("health/hc-ana") == "degraded"
        remedy_runs = [
            wf
            for wf in engine.submitted
            if wf["metadata"]["name"].startswith("hc-ana-remedy-")
        ]
        # run 9 confirmed the degradation: exactly its remedy fired,
        # even though every probe run SUCCEEDED
        assert len(remedy_runs) == 1
        durable = await client.get("health", "hc-ana")
        assert durable.status.remedy_total_runs == 1
    finally:
        await manager.stop()
