"""Per-check health state machine: healthy → flapping → quarantined.

The SLO layer (obs/history, obs/slo) measures how a check is doing; this
module DECIDES what the controller should do about it. Two independent
failure shapes get two different containments (the Reframe framing from
PAPERS.md: classify and contain faults, don't just count them):

- **flapping** — the check reaches a verdict, but the verdict keeps
  flipping. Every flip burns error budget AND apiserver/Argo capacity at
  full cadence, while the signal content of each run approaches zero.
  Containment: the schedule is *damped* (the effective interval is
  multiplied by ``damp_factor``) until the verdict stays put for
  ``calm_streak`` consecutive runs.
- **quarantined** — the check never reaches a verdict: parse errors,
  submit failures, crashes *pre-terminal*, ``quarantine_after`` times in
  a row. Retrying a deterministically-broken check forever is pure
  waste, so the schedule stops entirely and ``.status.state`` is set to
  ``Quarantined`` — an explicit, durable, user-clearable mark (clear the
  field to resume; docs/resilience.md walks through it).

The tracker is pure bookkeeping — no clock, no I/O — so transitions are
exactly reproducible from a scripted verdict sequence. The reconciler
owns when to consult it and what each transition does (events, metrics,
status writes, timer teardown).
"""

from __future__ import annotations

import collections
from typing import Deque, Dict, Optional, Tuple

# .status.state values (k8s-style CamelCase, like phase values).
# Healthy is represented as "" in the durable status — absence of
# trouble is not worth a field — but reported as "healthy" on /statusz.
STATE_HEALTHY = "Healthy"
STATE_FLAPPING = "Flapping"
STATE_QUARANTINED = "Quarantined"

CHECK_STATES = (STATE_HEALTHY, STATE_FLAPPING, STATE_QUARANTINED)

DEFAULT_FLAP_WINDOW = 8  # verdicts considered for flip counting
DEFAULT_FLAP_THRESHOLD = 3  # flips within the window => flapping
DEFAULT_CALM_STREAK = 4  # equal verdicts in a row => healthy again
DEFAULT_QUARANTINE_AFTER = 5  # consecutive pre-terminal errors
DEFAULT_DAMP_FACTOR = 2.0  # interval multiplier while flapping

# Hard bounds on the COMPOSED damp factor (docs/resilience.md pins both).
# The slow side caps at MAX_COMPOSED_DAMP so stacked containments (flap ×
# analysis × contention) can never damp a check into effectively never
# running — at the cap a 60s check still owes a run every 16 minutes.
# The fast side floors at MIN_BURN_DAMP so burn-rate tightening
# (resilience/adapt.py) can at most 4× a check's cadence — tighter would
# let the adaptive loop DDoS the very control plane it is trying to heal.
MAX_COMPOSED_DAMP = 16.0
MIN_BURN_DAMP = 0.25


class _CheckRecord:
    __slots__ = ("verdicts", "error_streak", "state", "persisted")

    def __init__(self, window: int):
        self.verdicts: Deque[bool] = collections.deque(maxlen=window)
        self.error_streak = 0
        self.state = STATE_HEALTHY
        # has the Quarantined mark reached durable .status.state? Until
        # it has, an empty durable field means "not yet written", not
        # "the user cleared it" — the reconciler's clear-detection
        # hinges on this bit.
        self.persisted = False


class CheckStateTracker:
    """Keyed by ``namespace/name`` like the timer wheel and result
    rings. Transition-returning mutators let the caller event/metric
    exactly once per edge."""

    def __init__(
        self,
        flap_window: int = DEFAULT_FLAP_WINDOW,
        flap_threshold: int = DEFAULT_FLAP_THRESHOLD,
        calm_streak: int = DEFAULT_CALM_STREAK,
        quarantine_after: int = DEFAULT_QUARANTINE_AFTER,
        damp_factor: float = DEFAULT_DAMP_FACTOR,
    ):
        self.flap_window = max(2, flap_window)
        self.flap_threshold = max(1, flap_threshold)
        self.calm_streak = max(1, calm_streak)
        self.quarantine_after = max(1, quarantine_after)
        self._damp_factor = max(1.0, damp_factor)
        self._records: Dict[str, _CheckRecord] = {}
        # externally-requested damping (the analysis layer parks a
        # confirmed-degraded check at a slower cadence through the same
        # damp_factor the flap containment uses); 1.0 = none
        self._analysis_damp: Dict[str, float] = {}
        # interference-aware placement damping (resilience/adapt.py parks
        # a cohort-confirmed straggler at a slower cadence); 1.0 = none
        self._contention_damp: Dict[str, float] = {}
        # burn-rate cadence tightening (resilience/adapt.py): < 1.0
        # SHRINKS the effective interval while error budget burns
        self._burn_damp: Dict[str, float] = {}

    def _record(self, key: str) -> _CheckRecord:
        rec = self._records.get(key)
        if rec is None:
            rec = self._records[key] = _CheckRecord(self.flap_window)
        return rec

    # -- inputs ---------------------------------------------------------
    def note_verdict(self, key: str, ok: bool) -> Optional[Tuple[str, str]]:
        """One terminal verdict landed. Returns ``(old, new)`` on a
        state transition, else None. A verdict also proves the submit
        path works, so the pre-terminal error streak resets."""
        rec = self._record(key)
        rec.error_streak = 0
        rec.verdicts.append(bool(ok))
        if rec.state == STATE_QUARANTINED:
            # a quarantined check does not run; a straggler verdict from
            # an in-flight workflow must not resurrect it
            return None
        flips = sum(
            1
            for a, b in zip(rec.verdicts, list(rec.verdicts)[1:])
            if a != b
        )
        if rec.state == STATE_HEALTHY and flips >= self.flap_threshold:
            rec.state = STATE_FLAPPING
            return (STATE_HEALTHY, STATE_FLAPPING)
        if rec.state == STATE_FLAPPING:
            tail = list(rec.verdicts)[-self.calm_streak:]
            if len(tail) >= self.calm_streak and len(set(tail)) == 1:
                rec.state = STATE_HEALTHY
                # start the new healthy era with a clean window: the
                # pre-calm flips still inside the ring would otherwise
                # re-trip Flapping on the very next (identical) verdict
                # — a damp/undamp oscillation on a stable check
                rec.verdicts.clear()
                return (STATE_FLAPPING, STATE_HEALTHY)
        return None

    def note_preterminal_error(self, key: str) -> Optional[Tuple[str, str]]:
        """The cycle died before any verdict (parse/submit/process
        error). Returns the transition into quarantine when the streak
        crosses the threshold."""
        rec = self._record(key)
        if rec.state == STATE_QUARANTINED:
            return None
        rec.error_streak += 1
        if rec.error_streak >= self.quarantine_after:
            old = rec.state
            rec.state = STATE_QUARANTINED
            rec.persisted = False
            return (old, STATE_QUARANTINED)
        return None

    def note_submit_ok(self, key: str) -> None:
        """A workflow was submitted cleanly: the pre-terminal streak is
        broken even if the run later fails its verdict."""
        rec = self._records.get(key)
        if rec is not None:
            rec.error_streak = 0

    # -- forced transitions ---------------------------------------------
    def quarantine(self, key: str) -> None:
        """Adopt a durable ``Quarantined`` mark found in status (e.g.
        written by a previous controller incarnation)."""
        rec = self._record(key)
        rec.state = STATE_QUARANTINED
        rec.persisted = True

    def clear(self, key: str) -> None:
        """User cleared the quarantine (or an operator reset): back to
        healthy with all streaks zeroed."""
        rec = self._record(key)
        rec.state = STATE_HEALTHY
        rec.error_streak = 0
        rec.verdicts.clear()
        rec.persisted = False

    def mark_persisted(self, key: str) -> None:
        rec = self._records.get(key)
        if rec is not None:
            rec.persisted = True

    def persisted(self, key: str) -> bool:
        rec = self._records.get(key)
        return rec.persisted if rec is not None else False

    # -- queries --------------------------------------------------------
    def state(self, key: str) -> str:
        rec = self._records.get(key)
        return rec.state if rec is not None else STATE_HEALTHY

    def set_analysis_damp(self, key: str, factor: float) -> None:
        """The analysis layer's schedule damping request for a check
        whose metrics are confirmed-degraded (analysis/engine.py).
        Factor <= 1 clears the request. Kept HERE so the reconciler's
        one damp_factor call keeps covering both containments — a
        second multiplier consulted in some call sites but not others
        is exactly the half-damped bug the flap tracker already fixed."""
        if factor and factor > 1.0:
            self._analysis_damp[key] = float(factor)
        else:
            self._analysis_damp.pop(key, None)

    def set_contention_damp(self, key: str, factor: float) -> None:
        """Interference-aware placement damping (resilience/adapt.py):
        a cohort-confirmed straggler is probed less often so its slice
        stops absorbing probe traffic while contended. Factor <= 1
        clears the request. Same single-rule contract as
        ``set_analysis_damp``."""
        if factor and factor > 1.0:
            self._contention_damp[key] = float(factor)
        else:
            self._contention_damp.pop(key, None)

    def set_burn_damp(self, key: str, factor: float) -> None:
        """Burn-rate cadence tightening (resilience/adapt.py): while a
        check's error budget burns, its interval SHRINKS (factor < 1)
        so the fleet confirms recovery sooner. Factor >= 1 clears the
        request. Clamped to ``MIN_BURN_DAMP`` — the adaptive loop can
        never tighten beyond 4× cadence."""
        if factor and 0.0 < factor < 1.0:
            self._burn_damp[key] = max(MIN_BURN_DAMP, float(factor))
        else:
            self._burn_damp.pop(key, None)

    def damp_factor(self, key: str) -> float:
        """Interval multiplier for the check's schedule — the ONE rule
        every call site consults. Slow-side containments compose by
        strongest-wins: the flap containment (>1 while flapping), the
        analysis layer's degraded-mode damping, and the placement
        layer's contention damping, capped at ``MAX_COMPOSED_DAMP`` so
        a check can never be damped into never running. The burn-rate
        tightener then multiplies the result (< 1 while burning), so a
        check that is BOTH flapping and burning still slows down —
        containment outranks urgency — while a healthy burning check
        tightens to at most ``MIN_BURN_DAMP`` of its spec cadence."""
        flap = (
            self._damp_factor
            if self.state(key) == STATE_FLAPPING
            else 1.0
        )
        slow = min(
            MAX_COMPOSED_DAMP,
            max(
                flap,
                self._analysis_damp.get(key, 1.0),
                self._contention_damp.get(key, 1.0),
            ),
        )
        return max(MIN_BURN_DAMP, slow * self._burn_damp.get(key, 1.0))

    def error_streak(self, key: str) -> int:
        rec = self._records.get(key)
        return rec.error_streak if rec is not None else 0

    def forget(self, key: str) -> None:
        """Deleted check: drop its record."""
        self._records.pop(key, None)
        self._analysis_damp.pop(key, None)
        self._contention_damp.pop(key, None)
        self._burn_damp.pop(key, None)
