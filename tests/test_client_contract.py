"""One contract, three clients.

The HealthCheckClient protocol (controller/client.py) has three real
implementations — in-memory, file-backed, and Kubernetes-over-REST —
and the reconciler/manager must behave identically on all of them. The
reference has exactly one client (controller-runtime's), so THIS suite
is the drift guard its architecture never needed: every semantic the
controller relies on runs against each implementation through one
parameterized scenario set. A behavior difference between clients is a
bug here even if each client's own test file stays green.
"""

import asyncio
import contextlib

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import InMemoryHealthCheckClient
from activemonitor_tpu.controller.client import ConflictError, NotFoundError
from activemonitor_tpu.controller.client_file import FileHealthCheckClient
from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient

from tests.kube_harness import stub_env


def make_hc(name="contract-a", namespace="health", repeat=60):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "workflow": {
                    "generateName": f"{name}-",
                    "resource": {
                        "namespace": namespace,
                        "source": {"inline": "kind: Workflow\n"},
                    },
                },
            },
        }
    )


@contextlib.asynccontextmanager
async def client_under_test(kind, tmp_path):
    if kind == "memory":
        yield InMemoryHealthCheckClient()
    elif kind == "file":
        yield FileHealthCheckClient(str(tmp_path), poll_seconds=0.05)
    else:
        async with stub_env() as (_server, api):
            yield KubernetesHealthCheckClient(api)


CLIENTS = ["memory", "file", "k8s"]


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_crud_and_status_roundtrip(kind, tmp_path):
    async with client_under_test(kind, tmp_path) as client:
        assert await client.get("health", "contract-a") is None
        created = await client.apply(make_hc())
        assert created.metadata.name == "contract-a"

        got = await client.get("health", "contract-a")
        assert got is not None and got.spec.repeat_after_sec == 60

        listed = await client.list()
        assert [h.metadata.name for h in listed] == ["contract-a"]

        # status write lands; a later spec re-apply must NOT clobber it
        got.status.status = "Succeeded"
        got.status.success_count = 3
        await client.update_status(got)
        re_applied = await client.apply(make_hc(repeat=90))
        assert re_applied.spec.repeat_after_sec == 90
        fresh = await client.get("health", "contract-a")
        assert fresh.status.success_count == 3, kind
        assert fresh.spec.repeat_after_sec == 90

        await client.delete("health", "contract-a")
        assert await client.get("health", "contract-a") is None
        with pytest.raises(NotFoundError):
            await client.delete("health", "contract-a")


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_stale_status_write_conflicts(kind, tmp_path):
    """Optimistic concurrency: a status write from a stale snapshot
    (another writer bumped the object since) must raise ConflictError
    on every client — the retry_on_conflict path depends on it."""
    async with client_under_test(kind, tmp_path) as client:
        await client.apply(make_hc())
        stale = await client.get("health", "contract-a")
        # another writer moves the object forward
        current = await client.get("health", "contract-a")
        current.status.status = "Succeeded"
        await client.update_status(current)
        stale.status.status = "Failed"
        with pytest.raises(ConflictError):
            await client.update_status(stale)


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_watch_delivers_adds_and_deletes(kind, tmp_path):
    """The manager's event loop is driven by watch(): ADDED for new
    (and pre-existing) checks, and deletion eventually surfacing as a
    DELETED event, on every client."""
    async with client_under_test(kind, tmp_path) as client:
        events = []
        seen = asyncio.Event()

        async def consume():
            async for ev in client.watch():
                events.append((ev.type, ev.name))
                if ("DELETED", "contract-a") in events:
                    seen.set()
                    return

        task = asyncio.create_task(consume())
        try:
            await asyncio.sleep(0.15)  # watch registered
            await client.apply(make_hc())

            async def added():
                return any(
                    t == "ADDED" and n == "contract-a" for t, n in events
                )

            for _ in range(100):
                if await added():
                    break
                await asyncio.sleep(0.05)
            assert await added(), (kind, events)
            await client.delete("health", "contract-a")
            await asyncio.wait_for(seen.wait(), timeout=10)
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_namespace_scoping(kind, tmp_path):
    async with client_under_test(kind, tmp_path) as client:
        await client.apply(make_hc("a", namespace="ns1"))
        await client.apply(make_hc("b", namespace="ns2"))
        assert {h.metadata.name for h in await client.list()} == {"a", "b"}
        only = await client.list("ns1")
        assert [h.metadata.name for h in only] == ["a"]
        # same name in a different namespace is a different object
        assert await client.get("ns2", "a") is None


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_apply_returns_rv_bearing_object(kind, tmp_path):
    """apply() must return an object whose resource_version arms the
    CAS guard: apply -> (another writer bumps) -> update_status from
    the apply snapshot must conflict on every client."""
    async with client_under_test(kind, tmp_path) as client:
        applied = await client.apply(make_hc())
        assert applied.metadata.resource_version, kind
        other = await client.get("health", "contract-a")
        other.status.status = "Succeeded"
        await client.update_status(other)
        applied.status.status = "Failed"
        with pytest.raises(ConflictError):
            await client.update_status(applied)


@pytest.mark.asyncio
async def test_file_client_rv_survives_second_instance(tmp_path):
    """The file store's rv is DURABLE: a second client instance (or a
    restarted controller) starting its in-memory counter at zero must
    not regress the persisted rv — a regression would let genuinely
    stale snapshots compare equal and clobber newer status."""
    a = FileHealthCheckClient(str(tmp_path), poll_seconds=0.05)
    await a.apply(make_hc())
    for _ in range(3):  # rv climbs to 3
        cur = await a.get("health", "contract-a")
        cur.status.success_count += 1
        await a.update_status(cur)
    stale = await a.get("health", "contract-a")  # rv 3

    b = FileHealthCheckClient(str(tmp_path), poll_seconds=0.05)  # fresh counter
    cur = await b.get("health", "contract-a")
    cur.status.success_count += 1
    updated = await b.update_status(cur)
    assert int(updated.metadata.resource_version) > 3  # no regression
    stale.status.success_count = 0
    with pytest.raises(ConflictError):
        await a.update_status(stale)
    fresh = await a.get("health", "contract-a")
    assert fresh.status.success_count == 4


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_spec_reapply_bumps_rv_and_conflicts_stale_writers(kind, tmp_path):
    """A spec re-apply moves the object's rv on every backend, so a
    snapshot taken BEFORE the spec change conflicts on its next status
    write — status computed against an outdated spec never lands."""
    async with client_under_test(kind, tmp_path) as client:
        await client.apply(make_hc())
        snap = await client.get("health", "contract-a")
        await client.apply(make_hc(repeat=120))  # spec change by another
        snap.status.status = "Failed"
        with pytest.raises(ConflictError):
            await client.update_status(snap)


@pytest.mark.asyncio
@pytest.mark.parametrize("kind", CLIENTS)
async def test_status_write_emits_modified(kind, tmp_path):
    """update_status surfaces as a MODIFIED watch event on every
    backend (status-subresource writes are watch events on a real
    apiserver); a manager reacting to MODIFIED must see the same
    stream whichever store backs it."""
    async with client_under_test(kind, tmp_path) as client:
        await client.apply(make_hc())
        events = []

        async def consume():
            async for ev in client.watch():
                events.append((ev.type, ev.name))

        task = asyncio.create_task(consume())
        try:
            await asyncio.sleep(0.15)
            hc = await client.get("health", "contract-a")
            hc.status.status = "Succeeded"
            await client.update_status(hc)
            for _ in range(100):
                if ("MODIFIED", "contract-a") in events:
                    break
                await asyncio.sleep(0.05)
            assert ("MODIFIED", "contract-a") in events, (kind, events)
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
