# Controller + probe payload image (reference equivalent: distroless
# runtime image, Dockerfile:24-28). One image serves both roles: the
# controller entrypoint and the probe CLI invoked by workflow templates.
FROM python:3.12-slim AS base

WORKDIR /app
COPY pyproject.toml README.md ./
COPY activemonitor_tpu ./activemonitor_tpu
RUN pip install --no-cache-dir .

# TPU probe pods additionally need libtpu; GKE TPU node images provide
# the device plumbing — install the TPU-enabled jax wheel at build time
# for probe images:
#   docker build --build-arg JAX_VARIANT="jax[tpu]" -t $IMG .
ARG JAX_VARIANT=""
RUN if [ -n "$JAX_VARIANT" ]; then \
        pip install --no-cache-dir "$JAX_VARIANT" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html; \
    fi

USER 65532:65532
ENTRYPOINT ["python", "-m", "activemonitor_tpu"]
CMD ["run", "--help"]
