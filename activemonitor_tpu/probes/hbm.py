"""HBM bandwidth probe.

Times a STREAM-scale pass (read + write = 2× payload bytes) and
compares achieved GB/s against the chip's rated HBM bandwidth. Uses the
Pallas kernel on TPU (ops/stream.py) and the fused XLA expression
elsewhere (interpret-mode Pallas is functionally identical but not
timeable).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.ops.stream import (
    stream_scale_pallas,
    stream_scale_pallas_db,
    stream_scale_xla,
)
from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    size_mb: float = 256.0,
    iters: int = 10,
    threshold: float = 0.6,
    use_pallas: bool = True,
    roofline: bool = True,
) -> ProbeResult:
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    dtype = jnp.bfloat16
    cols = 1024
    rows = max(512, int(size_mb * 1e6 / jnp.dtype(dtype).itemsize) // cols)
    rows -= rows % 512
    x = jnp.ones((rows, cols), dtype)
    payload = rows * cols * jnp.dtype(dtype).itemsize

    # two Pallas pipelines measure the same workload on TPU — the
    # automatic grid pipeline and the explicitly double-buffered DMA
    # schedule. Neither dominates across block sizes/runs (within a few
    # percent), so the probe reports the best achieved number and keeps
    # the per-variant measurements in the details.
    if on_tpu and use_pallas:
        variants = {"pallas-grid": stream_scale_pallas, "pallas-db": stream_scale_pallas_db}
    else:
        variants = {"xla": stream_scale_xla}
    # bf16 scale factor chosen representable so chained values stay finite
    scale = 1.0078125

    per_variant = {}
    for name, op in variants.items():
        def make_chain(k, op=op):
            @jax.jit
            def chain(x):
                for _ in range(k):  # data-dependent chain of full passes
                    x = op(x, scale)
                # full reduction: a partial slice would let XLA dead-code
                # the untouched elements of every pass in the chain
                return x.astype(jnp.float32).sum()

            return chain

        # wide k spread: a single pass is sub-millisecond, so the delta
        # must tower over tunnel/dispatch jitter
        seconds = chain_delta_seconds(make_chain, x, k1=4, k2=28, iters=iters)
        per_variant[name] = 2 * payload / seconds / 1e9  # read + write per pass

    kernel, gbps = max(per_variant.items(), key=lambda kv: kv[1])
    seconds = 2 * payload / gbps / 1e9

    rated = rated_for(device.device_kind)
    # roofline evidence (obs/roofline.py): STREAM-scale is the textbook
    # memory-bound op — one multiply per element against a full
    # read+write of the payload puts the intensity far left of the
    # ridge, so a healthy chip reads memory-bound near its ceiling. The
    # XLA cost comes from the fused XLA expression (same semantics the
    # Pallas pipelines implement; Mosaic custom calls carry no usable
    # compile-time cost), the analytic model is the fallback.
    roofline_capture = roofline_model.capture(
        "hbm",
        seconds=seconds,
        fn=lambda v: stream_scale_xla(v, scale),
        args=(jax.ShapeDtypeStruct((rows, cols), dtype),),
        model_flops=float(rows * cols),
        model_bytes=2.0 * payload,
        enabled=roofline,
    )
    metrics = [
        ProbeMetric("hbm-stream-gbps", gbps, help="Achieved STREAM-scale bandwidth, GB/s")
    ]
    details = {
        "payload_mb": payload / 1e6,
        "seconds_per_op": seconds,
        "kernel": kernel,
        "per_variant_gbps": {k: round(v, 1) for k, v in per_variant.items()},
        "device_kind": device.device_kind,
    }
    ok = True
    if rated is not None and on_tpu:
        fraction = gbps / rated.hbm_gbps
        metrics.append(
            ProbeMetric(
                "hbm-fraction-of-rated",
                fraction,
                help="Achieved / rated HBM bandwidth",
            )
        )
        details["rated_gbps"] = rated.hbm_gbps
        details["fraction"] = round(fraction, 3)
        ok = fraction >= threshold
        summary = f"HBM {gbps:.0f} GB/s = {fraction:.0%} of rated {rated.hbm_gbps:.0f} GB/s"
    else:
        summary = f"memory bandwidth {gbps:.1f} GB/s on {device.platform} (no rated comparison)"
    result = ProbeResult(ok=ok, summary=summary, metrics=metrics, details=details)
    roofline_model.apply(result, roofline_capture)
    return result
