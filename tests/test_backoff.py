"""Backoff tests (reference test model:
healthcheck_controller_unit_test.go:679-753 backoff param matrix)."""

import asyncio

import pytest

from activemonitor_tpu.scheduler import InverseExpBackoff, compute_backoff_params
from activemonitor_tpu.utils.clock import FakeClock


class TestComputeBackoffParams:
    def test_defaults_from_timeout(self):
        p = compute_backoff_params(workflow_timeout=600)
        assert p.max_delay == 300.0  # timeout/2
        assert p.min_delay == 10.0  # timeout/60
        assert p.factor == 0.5
        assert p.timeout == 600.0

    def test_small_timeout_clamps_to_one_second(self):
        p = compute_backoff_params(workflow_timeout=1)
        assert p.max_delay == 1.0
        assert p.min_delay == 1.0

    def test_zero_timeout_clamps(self):
        p = compute_backoff_params(workflow_timeout=0)
        assert p.max_delay == 1.0
        assert p.min_delay == 1.0
        assert p.timeout == 0.0

    def test_explicit_overrides(self):
        p = compute_backoff_params(
            workflow_timeout=60, backoff_max=2, backoff_min=1, backoff_factor="0.1"
        )
        assert p.max_delay == 2.0
        assert p.min_delay == 1.0
        assert p.factor == 0.1

    def test_negative_spec_values_are_treated_as_unset(self):
        # a negative delay would become a hot poll loop (asyncio treats
        # negative sleeps as 0) — fall back to the timeout-derived defaults
        p = compute_backoff_params(workflow_timeout=600, backoff_max=-5, backoff_min=-1)
        assert p.max_delay == 300.0
        assert p.min_delay == 10.0

    def test_bad_factor_falls_back(self):
        # reference: healthcheck_controller.go:595-601 logs and keeps 0.5
        p = compute_backoff_params(workflow_timeout=60, backoff_factor="not-a-float")
        assert p.factor == 0.5


@pytest.mark.asyncio
async def test_delays_decrease_to_min():
    clock = FakeClock()
    p = compute_backoff_params(workflow_timeout=120)  # max 60, min 2
    ieb = InverseExpBackoff(p, clock)
    seen = []

    async def driver():
        for _ in range(7):
            seen.append(ieb.current_delay)
            ok = await ieb.next()
            assert ok

    task = asyncio.create_task(driver())
    await clock.advance(60 + 30 + 15 + 7.5 + 3.75 + 2 + 2 + 1)
    await task
    assert seen == [60.0, 30.0, 15.0, 7.5, 3.75, 2.0, 2.0]


class TestFullJitter:
    """Opt-in full jitter (ISSUE 3 satellite): synchronized checks must
    not thundering-herd the apiserver after an outage, so a jittered
    pacer draws each delay uniformly from [0, delay]."""

    def test_property_jittered_delays_stay_within_zero_and_schedule(self):
        # property test across many parameter sets and draws: every
        # jittered delay lands in [0, delay] where delay is the exact
        # value the unjittered schedule would have returned
        import random

        rng = random.Random(1234)
        for case in range(50):
            params = compute_backoff_params(
                workflow_timeout=rng.randrange(1, 3600),
                backoff_max=rng.randrange(0, 600),
                backoff_min=rng.randrange(0, 60),
                backoff_factor=str(rng.uniform(0.05, 0.95)),
            )
            clock = FakeClock()
            plain = InverseExpBackoff(params, clock)
            jittered = InverseExpBackoff(
                params, clock, jitter=True, rng=random.Random(case)
            )
            for _ in range(20):
                envelope = plain.advance()
                drawn = jittered.advance()
                assert 0.0 <= drawn <= envelope, (params, envelope, drawn)

    def test_jitter_defaults_off_and_preserves_exact_schedule(self):
        params = compute_backoff_params(workflow_timeout=120)  # max 60 min 2
        ieb = InverseExpBackoff(params, FakeClock())
        assert [ieb.advance() for _ in range(4)] == [60.0, 30.0, 15.0, 7.5]

    def test_jittered_schedule_envelope_still_decays(self):
        # the underlying schedule advances unjittered: after N draws the
        # envelope equals the plain schedule's Nth delay
        import random

        params = compute_backoff_params(workflow_timeout=120)
        ieb = InverseExpBackoff(
            params, FakeClock(), jitter=True, rng=random.Random(0)
        )
        for _ in range(3):
            ieb.advance()
        assert ieb.current_delay == 7.5


@pytest.mark.asyncio
async def test_timeout_returns_false_without_sleeping():
    clock = FakeClock()
    p = compute_backoff_params(workflow_timeout=10)  # max 5, min 1, timeout 10
    ieb = InverseExpBackoff(p, clock)
    results = []

    async def driver():
        while True:
            ok = await ieb.next()
            results.append(ok)
            if not ok:
                return

    task = asyncio.create_task(driver())
    await clock.advance(30)
    await task
    # 5 + 2.5 + 1.25 + 1 = 9.75 < 10; next wait crosses the deadline
    assert results[-1] is False
    assert all(results[:-1])
    assert clock.monotonic() >= 10.0
