"""Benchmark entry point — prints ONE JSON line.

Adaptive to the hardware it lands on (BASELINE.md):

- multi-chip TPU: the north-star ICI all-reduce probe — fraction of
  rated ring bandwidth (target ≥ 0.9).
- single-chip TPU: the MXU matmul probe — fraction of rated bf16 peak
  (the per-chip floor under every distributed target).
- CPU (virtual mesh): informational all-reduce GB/s.

``vs_baseline`` is measured / target-fraction (0.9): ≥1.0 beats the
BASELINE.md bar. All timing uses the chain-difference method so tunnel
and dispatch overhead cancel (utils/timing.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

# a wedged device tunnel must degrade to a CPU-mesh measurement, not
# hang the driver: probe reachability in a killable subprocess first
_PROBE_TIMEOUT = float(os.environ.get("ACTIVEMONITOR_BENCH_PROBE_TIMEOUT", "180"))
_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "print(float(jax.jit(lambda a:(a@a).astype(jnp.float32).sum())"
    "(jnp.ones((128,128), jnp.bfloat16))))"
)


def _device_reachable() -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=_PROBE_TIMEOUT,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print(
            f"device probe hung past {_PROBE_TIMEOUT:.0f}s (wedged tunnel?)",
            file=sys.stderr,
        )
        return False
    if proc.returncode != 0:
        # surface the real diagnostic (libtpu init error, plugin
        # mismatch, OOM) instead of a misleading timeout claim
        tail = proc.stderr.decode(errors="replace").strip().splitlines()[-8:]
        print(
            "device probe exited with "
            f"{proc.returncode}:\n" + "\n".join(tail),
            file=sys.stderr,
        )
        return False
    return True


def main() -> int:
    # known-CPU runs have no tunnel to hang on — skip the probe cost
    want_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    if not want_cpu and not _device_reachable():
        print("falling back to the virtual CPU mesh", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        want_cpu = True
    import jax

    if want_cpu:
        # site customizations (e.g. an accelerator plugin on PYTHONPATH)
        # can override the env var; the config API outranks them
        jax.config.update("jax_platforms", "cpu")

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform
    target_fraction = 0.9

    if platform == "tpu" and n > 1:
        from activemonitor_tpu.probes import ici

        result = ici.run(size_mb=64, iters=5, threshold=target_fraction)
        by_name = {m.name: m.value for m in result.metrics}
        fraction = by_name.get("ici-allreduce-fraction-of-rated")
        if fraction is not None:
            doc = {
                "metric": "ici_allreduce_fraction_of_rated",
                "value": round(fraction, 4),
                "unit": "fraction",
                "vs_baseline": round(fraction / target_fraction, 4),
            }
        else:
            doc = {
                "metric": "ici_allreduce_busbw",
                "value": round(by_name["ici-allreduce-busbw-gbps"], 2),
                "unit": "GB/s",
                "vs_baseline": 1.0,
            }
    elif platform == "tpu":
        from activemonitor_tpu.probes import matmul

        # median-of-3: each run is already a max over a dim sweep of
        # min-sampled chain deltas; taking a further max would compound
        # the upward bias into physically impossible >1.0-of-rated
        # readings, while the median stays an honest estimate
        runs = []
        for _ in range(3):
            result = matmul.run(iters=5, threshold=target_fraction)
            runs.append({m.name: m.value for m in result.metrics})
        runs.sort(key=lambda r: r.get("mxu-matmul-tflops", 0))
        by_name = runs[len(runs) // 2]
        fraction = by_name.get("mxu-fraction-of-rated")
        if fraction is not None:
            doc = {
                "metric": "mxu_bf16_fraction_of_rated",
                "value": round(fraction, 4),
                "unit": "fraction",
                "vs_baseline": round(fraction / target_fraction, 4),
            }
        else:
            doc = {
                "metric": "mxu_bf16_tflops",
                "value": round(by_name["mxu-matmul-tflops"], 2),
                "unit": "TFLOP/s",
                "vs_baseline": 1.0,
            }
    else:
        from activemonitor_tpu.probes import ici

        result = ici.run(size_mb=8, iters=3)
        by_name = {m.name: m.value for m in result.metrics}
        doc = {
            "metric": "allreduce_busbw_cpu_mesh",
            "value": round(by_name["ici-allreduce-busbw-gbps"], 2),
            "unit": "GB/s",
            "vs_baseline": 1.0,
        }
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
