"""The HealthCheck reconciler — the core state machine.

Implements the reference's reconcile flow (SURVEY.md §3.2-3.4;
reference: internal/controllers/healthcheck_controller.go:170-874) as
cooperating asyncio tasks:

reconcile(key)
├─ get: gone ⇒ stop timer, done               (:175-186)
└─ process (exceptions recovered, 1s requeue)  (:190-223)
   ├─ pause: no interval and no cron ⇒ Stopped (:238-250)
   ├─ cron ⇒ effective interval = next-fire delta (+1s) (:251-263)
   ├─ dedupe: finished recently AND timer known ⇒ no-op (:264-267)
   ├─ provision check RBAC                     (:269)
   ├─ submit workflow                          (:277)
   └─ spawn watch task                         (:283)

watch task (one per in-flight workflow)
├─ poll engine with inverse-exp backoff; timeout ⇒ synthesized Failed (:607-632)
├─ Succeeded ⇒ counters/metrics/remedy-reset  (:635-661)
├─ Failed ⇒ counters/metrics + remedy gating  (:662-723)
│  └─ remedy: RBAC → submit → watch → delete RBAC (:759-874)
├─ conflict-retried status write               (:734,:1445-1462)
└─ reschedule via timer wheel                  (:745-754)

Deliberate divergences from the reference (each marked inline):

1. The watch loop runs as its own task instead of blocking a reconcile
   worker for the whole workflow duration — the reference's known
   throughput bound (SURVEY.md §2 defect (e)).
2. The timer-fired resubmission recomputes the effective interval (cron
   delta or repeatAfterSec) at reschedule time. The reference reuses the
   re-fetched spec's repeatAfterSec, which is 0 for cron-only specs and
   degenerates into an immediate-refire loop until the next watch event
   corrects it.
3. Workflow labels are computed per-check (see workflow_spec.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from activemonitor_tpu.analysis import AnalysisEngine
from activemonitor_tpu.analysis.engine import DEGRADED_DAMP_FACTOR
from activemonitor_tpu.api.types import (
    HealthCheck,
    PHASE_FAILED,
    PHASE_SUCCEEDED,
    STATUS_STOPPED,
    WORKFLOW_TYPE_HEALTHCHECK,
    WORKFLOW_TYPE_REMEDY,
)
from activemonitor_tpu.controller.client import (
    HealthCheckClient,
    NotFoundError,
    is_transient,
    retry_on_conflict,
    retry_on_transient,
)
from activemonitor_tpu.controller.events import (
    EVENT_NORMAL,
    EVENT_WARNING,
    EventRecorder,
)
from activemonitor_tpu.controller.rbac import RBACProvisioner
from activemonitor_tpu.controller.sharding import ShardFencedError
from activemonitor_tpu.controller.workflow_spec import (
    parse_remedy_workflow_from_healthcheck,
    parse_workflow_from_healthcheck,
)
from activemonitor_tpu.engine.base import WorkflowEngine
from activemonitor_tpu.metrics.collector import (
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)
from activemonitor_tpu.obs.flightrec import (
    FlightRecorder,
    KIND_DEGRADED,
    KIND_QUARANTINE,
)
from activemonitor_tpu.obs.slo import FleetStatus
from activemonitor_tpu.obs.trace import Tracer
from activemonitor_tpu.resilience import (
    BreakerOpenError,
    ResilienceCoordinator,
    STATE_FLAPPING,
    STATE_HEALTHY,
    STATE_QUARANTINED,
)
from activemonitor_tpu.resilience.adapt import AdaptiveController
from activemonitor_tpu.scheduler import (
    CronParseError,
    InverseExpBackoff,
    TimerWheel,
    compute_backoff_params,
    parse_cron,
    seconds_until_next,
)
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.reconciler")


class HealthCheckReconciler:
    def __init__(
        self,
        client: HealthCheckClient,
        engine: WorkflowEngine,
        rbac: RBACProvisioner,
        recorder: EventRecorder,
        metrics: MetricsCollector,
        clock: Optional[Clock] = None,
        tracer: Optional[Tracer] = None,
        resilience: Optional[ResilienceCoordinator] = None,
    ):
        self.client = client
        self.engine = engine
        self.rbac = rbac
        self.recorder = recorder
        self.metrics = metrics
        self.clock = clock or Clock()
        # the reconciler owns the tracer like it owns the clock — the
        # manager and the CLI reach it through here
        self.tracer = tracer or Tracer(self.clock)
        # fleet SLO aggregate (result history + error budgets), fed from
        # the status-write path below and served by the manager's
        # /statusz endpoint. Same ownership shape as the tracer.
        self.fleet = FleetStatus(self.clock, metrics)
        # degradation policy (docs/resilience.md): the shared circuit
        # breaker, the per-check health state machine, the remedy rate
        # cap, and the queued-status-write replay. Same ownership shape
        # as the tracer; /statusz reads it through the fleet aggregate.
        self.resilience = resilience or ResilienceCoordinator(self.clock, metrics)
        self.fleet.resilience = self.resilience
        # baseline & anomaly detection (docs/analysis.md): learns per-
        # metric baselines from the runs' custom-metric samples and
        # turns them into ok/warning/degraded verdicts orthogonal to
        # pass/fail. Same ownership shape as the tracer; /statusz reads
        # it through the fleet aggregate.
        self.analysis = AnalysisEngine(self.clock, metrics)
        self.fleet.analysis = self.analysis
        # goodput attribution reads the cycle's spans at record time
        # (queue wait -> the scheduling bucket, errored spans -> the
        # control-plane bucket)
        self.fleet.tracer = self.tracer
        # degradation flight recorder (docs/operations.md "Reading a
        # flight recording"): on confirmed ok→degraded, breaker-open,
        # quarantine, or shard handoff it snapshots the correlated
        # evidence — spans, result-ring tail, baselines, breaker/shard
        # state, attribution — into a bundle served at /debug/flightrec
        # (durable JSONL under --flight-dir). Same ownership shape as
        # the tracer.
        self.flightrec = FlightRecorder(self.clock)
        self.flightrec.tracer = self.tracer
        self.flightrec.history = self.fleet.history
        self.flightrec.fleet = self.fleet
        self.flightrec.resilience = self.resilience
        self.flightrec.analysis = self.analysis
        # the coordinator triggers a breaker-open bundle the moment the
        # breaker trips (the transition callback already funnels here)
        self.resilience.flightrec = self.flightrec
        # closed-loop adaptive control (resilience/adapt.py): consumes
        # burn rate + attribution off the fleet's record path and works
        # the four levers — cadence (through the checks tracker's one
        # damp rule), bucket-targeted remedies, contention placement
        # (through the analysis cohort index), and front-door degraded
        # mode (wired by the Manager when a front door exists). Same
        # ownership shape as the tracer.
        self.adapt = AdaptiveController(
            self.clock, metrics, checks=self.resilience.checks
        )
        self.adapt.flightrec = self.flightrec
        self.adapt.cohorts = self.analysis.cohorts
        self.fleet.adaptive = self.adapt
        self.timers = TimerWheel(self.clock)
        # sharded-fleet coordinator (controller/sharding.py), wired by
        # the Manager when --shards > 1: ownership gates for timer-fired
        # resubmits and the write fence that rejects a paused old
        # owner's late status writes. None = unsharded (own everything).
        self.shards = None
        self._watch_tasks: Dict[str, asyncio.Task] = {}
        # demand-driven runs (frontdoor/service.py): keys whose next
        # reconcile must SUBMIT even though the schedule is current —
        # a tenant asked for a fresher answer than the rings hold. The
        # mark is consumed by the cycle that acts on it (submits, or
        # finds an in-flight watch already satisfying the demand), so
        # ordinary watch-event reconciles never see it.
        self._demanded: set = set()
        # set by the Manager: routes failed-run requeues through its
        # workqueue (per-key serialized, stop-aware, retried on crash)
        # instead of a loop inside the dying task
        self.requeue_hook = None
        # set by the Manager (--profile-on-anomaly): called with
        # (key, reason) when attribution confirms ok→degraded, arming
        # one bounded profiler capture of the check's next run. None:
        # profiling off.
        self.profile_hook = None
        # also set by the Manager: a context-manager factory (key) ->
        # profiler capture wrapping the check's next WATCH (the actual
        # probe run: submit..poll..status write), not the scheduling
        # reconcile. None: no-op.
        self.profile_capture = None
        self._stopping = False
        self._requeue_loops: set = set()  # standalone-mode fallback loops

    # ------------------------------------------------------------------
    # entry point (reference: Reconcile, healthcheck_controller.go:170-188)
    # ------------------------------------------------------------------
    def demand(self, namespace: str, name: str) -> None:
        """Mark the check's next reconcile as demand-driven (the front
        door's trigger): the schedule-current dedupe must not swallow
        it — the cycle submits a run NOW, exactly like an owed fire.
        The caller still enqueues the key; a run already in flight
        satisfies the demand instead (its result fans out to the same
        waiters), so a demand can never stack a duplicate run."""
        self._demanded.add(f"{namespace}/{name}")

    async def reconcile(self, namespace: str, name: str) -> Optional[float]:
        """Returns a requeue-after delay in seconds, or None."""
        hc = await self.client.get(namespace, name)
        if hc is None:
            # deleted: cancel the next scheduled run (reference: :180-184).
            # Timers are keyed by namespace/name — the reference keys by
            # bare name (:139), letting same-named checks in different
            # namespaces clobber each other's schedules.
            key = f"{namespace}/{name}"
            self._demanded.discard(key)  # nothing left to demand-run
            if self.timers.exists(key):
                log.info("cancelling scheduled run for deleted healthcheck %s", key)
                self.timers.stop(key)
            # drop the check's result ring and SLO gauge series — the
            # fleet summary must not advertise a deleted check's budget
            self.fleet.forget(key, name, namespace)
            # ... and its resilience state: tracker record, any queued
            # status write, and the one-hot state metric series
            self.resilience.forget(key)
            self.metrics.clear_check_state(name, namespace)
            # ... and its learned baselines, cohort membership, and
            # anomaly/baseline/z-score series
            self.analysis.forget(key, name, namespace)
            # ... and its adaptive-control episodes (releases the
            # cadence gauge series and any derived front-door lever)
            self.adapt.forget(key)
            return None
        return await self._process_or_recover(hc)

    async def _process_or_recover(self, hc: HealthCheck) -> Optional[float]:
        # panic-recover equivalent (reference: :191-195)
        try:
            return await self._process(hc)
        except NotFoundError:
            # resource vanished mid-process: swallow (reference: :201-203)
            return None
        except ShardFencedError as e:
            # the key's shard was handed off mid-cycle: its new owner
            # drives the schedule — not an error, never quarantine fuel
            log.info("cycle for %s stopped by the shard fence (%s)", hc.key, e)
            return None
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception(
                "error processing healthcheck %s", hc.key
            )
            # count the pre-terminal error toward quarantine; a newly
            # (or already) quarantined check stops requeueing entirely
            if await self._note_cycle_error(hc):
                return None
            # 1s requeue on process error (reference: :204) — stretched
            # while the controller is degraded (docs/resilience.md)
            return self.resilience.requeue_delay(1.0)

    # ------------------------------------------------------------------
    # decision logic (reference: processHealthCheck, :225-291)
    # ------------------------------------------------------------------
    def _demand_unservable(self, key: str) -> None:
        """This cycle can never record a result (quarantined, stopped,
        no workflow resource): consume any pending demand mark — a
        stale mark would fire a surprise run when the condition clears
        — and cancel the front door's fanned-in waiters NOW, at
        reconcile speed, instead of leaving a dead in-flight entry
        absorbing joins until the reap sweep."""
        self._demanded.discard(key)
        frontdoor = self.fleet.frontdoor
        if frontdoor is not None:
            try:
                frontdoor.cache.forget(key)
            except Exception:
                log.exception("frontdoor waiter cancel failed for %s", key)

    async def _process(self, hc: HealthCheck) -> Optional[float]:
        spec = hc.spec
        if spec.workflow.resource is None:
            self._demand_unservable(hc.key)
            return None  # nothing to run (reference guards on Resource != nil, :227)

        # a queued (not-yet-replayed) status write is FRESHER truth than
        # the durable status: overlay it, or a stale finished_at would
        # make this reconcile re-submit the very run the queued write
        # records (the duplicate the chaos soak guards against)
        queued = self.resilience.queued_status(hc.key)
        if queued is not None:
            hc.status = queued.model_copy(deep=True)

        # quarantine gate (docs/resilience.md): a check whose cycles
        # repeatedly die pre-terminal stops running until a user clears
        # the durable .status.state mark. A pending front-door demand
        # is consumed unserved and its waiters cancelled at reconcile
        # speed — never a surprise run when the user clears the mark
        if await self._quarantine_gate(hc):
            self._demand_unservable(hc.key)
            return None

        # pause (reference: :238-250)
        if spec.repeat_after_sec <= 0 and not spec.schedule.cron:
            self._demand_unservable(hc.key)  # stopped: demand unserved
            hc.status.status = STATUS_STOPPED
            hc.status.error_message = (
                "workflow execution is stopped; either spec.RepeatAfterSec or "
                f"spec.Schedule must be provided. spec.RepeatAfterSec set to "
                f"{spec.repeat_after_sec}. spec.Schedule set to {spec.schedule.cron!r}"
            )
            hc.status.finished_at = self.clock.now()
            self.recorder.event(
                hc,
                EVENT_WARNING,
                "Warning",
                "Workflow execution is stopped; either spec.RepeatAfterSec or "
                "spec.Schedule must be provided",
            )
            await self._update_status(hc)
            return None

        # cron → effective interval (reference: :251-263)
        if spec.repeat_after_sec <= 0 and spec.schedule.cron:
            try:
                hc.spec.repeat_after_sec = seconds_until_next(
                    spec.schedule.cron, self.clock.now()
                )
            except CronParseError as e:
                self.recorder.event(hc, EVENT_WARNING, "Warning", "Fail to parse cron")
                log.error("fail to parse cron for %s: %s", hc.key, e)
                raise
        # dedupe (reference: :264-267): the schedule is current (no run
        # is owed yet) and a timer is known for this check ⇒ healthy.
        # Divergence 4: unlike the reference (where this guard is an
        # `else if` that cron specs never reach, so each status-write
        # event resubmits immediately — continuous churn), the guard
        # applies to cron checks too — "current" for a cron spec means
        # no fire has passed since the last finish (comparing elapsed
        # against the delta-to-NEXT-fire is wrong for absolute schedules
        # reconciled late in a period).
        remaining = self._schedule_remaining(hc)
        # a demand-driven cycle (frontdoor/service.py): the tenant asked
        # for a fresher answer than the schedule owes, so the current-
        # schedule dedupe below must not swallow this cycle — it submits
        # like an owed fire. Consumed here (one demand, one run).
        demanded = hc.key in self._demanded
        # nothing owed yet AND a live (unfired) timer ⇒ the schedule is
        # healthy; let the timer drive the next run. Time-bounding the
        # guard matters: a fired-but-bailed timer entry must not wedge
        # the check forever, and a spec edited to a faster cadence must
        # not wait out the old timer.
        if remaining is not None and self.timers.pending(hc.key) and not demanded:
            return None
        # a watch for this check is still in flight (workflow running
        # longer than the interval): don't stack a duplicate run — and
        # it satisfies any pending demand (its result fans out to the
        # same front-door waiters)
        if self._watch_active(hc.key):
            self._demanded.discard(hc.key)
            return None
        # Divergence 10: true resume after a controller restart. The
        # reference's dedupe needs its process-local timer, so a restart
        # resubmits EVERY recent check at once (a restart storm). Here a
        # current-schedule check with no live timer — the boot-resync
        # state, or a cadence shrunk by a spec edit — (re)builds its
        # timer from durable status for the remaining time to the owed
        # fire. Overdue checks (a fire passed while down) fall through
        # and run immediately.
        if remaining is not None and not demanded:
            self.timers.schedule(hc.key, remaining, self._resubmit_callback(hc))
            self.recorder.event(
                hc,
                EVENT_NORMAL,
                "Normal",
                "Schedule resumed from durable status for the remaining interval",
            )
            return None
        # a run is owed NOW (or demanded now): cancel any still-pending
        # timer first (the sub-second rounding sliver, or a stale long
        # timer after a spec edit) so it cannot double-fire behind this
        # submission — a demanded run re-anchors the cadence at its own
        # finish, which is correct: a fresh result just landed
        self._demanded.discard(hc.key)
        self.timers.stop(hc.key)

        # per-run RBAC (reference: :269)
        await self.rbac.create_rbac_for_workflow(hc, WORKFLOW_TYPE_HEALTHCHECK)

        wf_name = await self._submit_workflow(hc)
        self._spawn_watch(hc, wf_name)
        return None

    def _schedule_remaining(self, hc: HealthCheck) -> Optional[float]:
        """Seconds until the NEXT owed fire, judged purely from durable
        status — or None when a run is owed right now (never ran, or a
        fire/interval passed since finished_at, e.g. while the
        controller was down). One definition serves both the dedupe
        guard (remaining is not None ⇒ nothing owed yet) and the
        restart-resume timer (anchored at finished_at, so downtime
        neither double-runs nor stretches the cadence). A flapping
        check's interval is damped by the tracker's factor HERE as well
        as at reschedule time — judging "owed" against the raw cadence
        would let any reconcile event defeat the damping."""
        if hc.status.finished_at is None:
            return None  # never ran: owed now
        now = self.clock.now()
        damp = self.resilience.checks.damp_factor(hc.key)
        elapsed = (now - hc.status.finished_at).total_seconds()
        if hc.spec.schedule.cron:
            try:
                schedule = parse_cron(hc.spec.schedule.cron)
                next_after_finish = schedule.next(hc.status.finished_at)
            except CronParseError:
                return None  # unparseable: let the normal path complain
            period = (
                next_after_finish - hc.status.finished_at
            ).total_seconds() * damp
            if elapsed >= period:
                return None  # a (damped) fire passed since the last finish: owed
            return max(1.0, period - elapsed)
        interval = hc.spec.repeat_after_sec * damp
        if elapsed >= interval:
            return None  # interval elapsed: owed
        return max(1.0, interval - elapsed)

    # ------------------------------------------------------------------
    # resilience: per-check state machine + degraded-mode plumbing
    # (docs/resilience.md; no reference counterpart — the reference
    # retries every failure identically at a fixed 1 s cadence)
    # ------------------------------------------------------------------
    def _sync_state_metric(self, hc: HealthCheck) -> None:
        self.metrics.set_check_state(
            hc.metadata.name,
            hc.metadata.namespace,
            self.resilience.checks.state(hc.key),
        )

    async def _quarantine_gate(self, hc: HealthCheck) -> bool:
        """True when the check is quarantined and must not run.
        Reconciles the in-memory tracker with the durable
        ``.status.state`` mark: adopts a mark written by a previous
        controller incarnation, retries a mark whose write failed at
        transition time, and — the user contract — lifts the quarantine
        when the durable field we know we wrote comes back cleared."""
        key = hc.key
        tracker = self.resilience.checks
        durable = hc.status.state == STATE_QUARANTINED
        tracked = tracker.state(key) == STATE_QUARANTINED
        if durable and not tracked:
            # durable mark from a previous incarnation: adopt it (the
            # restart-resume analogue of divergence 10, for quarantine)
            log.info("adopting durable quarantine mark for %s", key)
            tracker.quarantine(key)
            self._sync_state_metric(hc)
            return True
        if durable and tracked:
            tracker.mark_persisted(key)
            return True
        if not durable and tracked:
            if tracker.persisted(key):
                # we know the mark was written (or queued — the status
                # overlay in _process keeps a queued mark visible), so
                # an empty field now means a USER cleared it: resume
                log.info("quarantine for %s cleared by user; resuming", key)
                tracker.clear(key)
                self._sync_state_metric(hc)
                self.recorder.event(
                    hc,
                    EVENT_NORMAL,
                    "Normal",
                    "Quarantine cleared; resuming the check's schedule",
                )
                return False
            # the transition-time write never landed: retry it now
            hc.status.state = STATE_QUARANTINED
            try:
                await self._update_status(hc)
                tracker.mark_persisted(key)
            except NotFoundError:
                pass  # deleted meanwhile; the deleted path cleans up
            except Exception:
                log.exception(
                    "failed to persist quarantine mark for %s; will retry",
                    key,
                )
            return True
        return False

    async def _note_cycle_error(self, hc: HealthCheck) -> bool:
        """Count one pre-terminal cycle error (parse/submit/process/
        watch crash) toward quarantine. Returns True when the check is
        quarantined and its schedule must stop. Errors during degraded
        mode are the FLEET's problem, not the check's — they never
        count, or an apiserver outage would quarantine innocents."""
        if self.resilience.degraded:
            return False
        tracker = self.resilience.checks
        transition = tracker.note_preterminal_error(hc.key)
        if transition is None:
            # either below the threshold (keep requeueing) or already
            # quarantined (a straggler error — stay stopped)
            return tracker.state(hc.key) == STATE_QUARANTINED
        key = hc.key
        log.warning(
            "quarantining %s after %d consecutive pre-terminal errors; "
            "clear .status.state to resume",
            key,
            tracker.quarantine_after,
        )
        # the consumed timer must not refire a check we just parked
        self.timers.stop(key)
        # ship the postmortem with the verdict: spans, ring tail,
        # breaker state — everything that explains the error streak
        self.flightrec.record(
            KIND_QUARANTINE,
            key=key,
            error_streak=tracker.quarantine_after,
        )
        self.recorder.event(
            hc,
            EVENT_WARNING,
            "Warning",
            "HealthCheck quarantined after repeated pre-terminal errors; "
            "clear .status.state to resume",
        )
        self._sync_state_metric(hc)
        hc.status.state = STATE_QUARANTINED
        hc.status.error_message = (
            "quarantined: the check's workflow repeatedly errored before "
            "reaching a verdict; clear .status.state to resume"
        )
        try:
            await self._update_status(hc)
            tracker.mark_persisted(key)
        except NotFoundError:
            pass  # deleted meanwhile
        except Exception:
            # likely the same outage that caused the errors — the
            # _quarantine_gate retries the mark on the next reconcile
            log.exception("failed to persist quarantine mark for %s", key)
        return True

    def _note_verdict(self, hc: HealthCheck, ok: bool) -> None:
        """Feed a terminal verdict to the flap state machine and keep
        the durable ``.status.state`` mark in sync — it rides the same
        status write that records the verdict."""
        tracker = self.resilience.checks
        transition = tracker.note_verdict(hc.key, ok)
        state = tracker.state(hc.key)
        if state != STATE_QUARANTINED:
            hc.status.state = "" if state == STATE_HEALTHY else state
        if transition is not None:
            _old, new = transition
            if new == STATE_FLAPPING:
                log.warning(
                    "%s is flapping (verdict keeps flipping); damping its "
                    "schedule by %.1fx",
                    hc.key,
                    tracker.damp_factor(hc.key),
                )
                self.recorder.event(
                    hc,
                    EVENT_WARNING,
                    "Warning",
                    "HealthCheck verdict is flapping; schedule damped until "
                    "it stabilizes",
                )
            else:
                log.info("%s verdict stabilized; schedule restored", hc.key)
                self.recorder.event(
                    hc,
                    EVENT_NORMAL,
                    "Normal",
                    "HealthCheck verdict stabilized; schedule restored",
                )
        self._sync_state_metric(hc)

    def _note_analysis(
        self, hc: HealthCheck, samples: dict, *, ok: bool, run_id: str = ""
    ) -> bool:
        """Feed one run's numeric samples to the baseline/anomaly
        engine (docs/analysis.md) and act on its verdict: events on
        state transitions, schedule damping while confirmed-degraded
        (through the flap tracker's damp_factor, so every cadence
        computation sees it). Returns True when the check's analysis
        state is degraded. The durable baseline blob lands on
        ``hc.status.analysis`` and rides the pending status write."""
        verdict = self.analysis.observe(hc, samples, ok=ok, run_id=run_id)
        if verdict is None:
            # no verdict (no analysis: block, or it was just removed):
            # any damping a previous degraded verdict requested must
            # not outlive the subsystem that asked for it
            self.resilience.checks.set_analysis_damp(hc.key, 1.0)
            return False
        if verdict.transition is not None:
            old, new = verdict.transition
            worsened = ("ok", "warning", "degraded").index(new) > (
                "ok", "warning", "degraded"
            ).index(old)
            if new == "degraded":
                # confirmed arrival at degraded (once per episode — the
                # hysteresis staircase passes warning first): snapshot
                # the evidence while the triggering cycle's spans and
                # the pre-transition baselines are still live
                self.flightrec.record(
                    KIND_DEGRADED,
                    key=hc.key,
                    transition=list(verdict.transition),
                    zscores=dict(verdict.zscores),
                )
                if self.profile_hook is not None:
                    # a confirmed degradation is the other trigger for
                    # profile-on-anomaly (burn-rate lives in the SLO
                    # layer): arm one capture of this check's NEXT run
                    try:
                        self.profile_hook(hc.key, "degraded")
                    except Exception:
                        log.exception(
                            "profile hook failed for %s", hc.key
                        )
            if worsened:
                self.recorder.event(
                    hc,
                    EVENT_WARNING,
                    "Warning",
                    f"HealthCheck metrics anomaly state is {new} "
                    "(deviation from learned baseline confirmed)",
                )
            elif new == "ok":
                self.recorder.event(
                    hc,
                    EVENT_NORMAL,
                    "Normal",
                    "HealthCheck metrics recovered to baseline",
                )
        # damp the schedule while degraded — same containment the flap
        # tracker applies, surfaced through the same damp_factor
        self.resilience.checks.set_analysis_damp(
            hc.key, DEGRADED_DAMP_FACTOR if verdict.degraded else 1.0
        )
        return verdict.degraded

    async def replay_status_writes(self) -> int:
        """Drain status writes queued while the breaker was open —
        oldest first, stopping at the first failure (or if the breaker
        re-opens mid-drain). Called by the manager's resilience sweep
        and opportunistically after any successful live write."""
        res = self.resilience
        replayed = 0
        while res.pending_status_writes():
            if not res.breaker.allow():
                break
            item = res.next_status_write()
            if item is None:
                break
            key, queued = item
            try:
                await self._write_status_now(queued)
            except NotFoundError:
                log.info("dropping queued status write for deleted %s", key)
                continue
            except ShardFencedError as e:
                # the shard moved while this write sat in the queue: the
                # new owner's status is the truth now — drop, don't spin
                self._note_fenced_write(queued, e)
                continue
            except asyncio.CancelledError:
                res.requeue_status_write(key, queued)
                raise
            except Exception:
                res.requeue_status_write(key, queued)
                log.warning(
                    "replay of queued status write for %s failed; will retry",
                    key,
                    exc_info=True,
                )
                break
            replayed += 1
            log.info("replayed queued status write for %s", key)
        return replayed

    # ------------------------------------------------------------------
    # submit (reference: createSubmitWorkflow, :502-534)
    # ------------------------------------------------------------------
    async def _parse_manifest(self, parser, hc: HealthCheck, workflow_spec):
        """A url/file artifact read is BLOCKING I/O (requests.get with
        a 30 s timeout; a possibly-NFS disk read) — run inline on the
        loop it would freeze every other check, the watches, AND lease
        renewal (whose ~2/3-lease deadline a slow artifact server could
        eat, costing leadership for a fetch). Only the I/O-bearing
        sources pay the thread hop — the store layer owns that
        classification next to its reader dispatch — so inline-source
        fake-clock tests stay deterministic."""
        from activemonitor_tpu.store import is_blocking_source

        resource = getattr(workflow_spec, "resource", None)
        if is_blocking_source(getattr(resource, "source", None)):
            return await asyncio.to_thread(parser, hc)
        return parser(hc)

    @property
    def _engine_name(self) -> str:
        """Label value for the engine submit/poll counters."""
        return getattr(self.engine, "name", type(self.engine).__name__)

    @property
    def _records_engine_outcomes(self) -> bool:
        """Engines built on the KubeApi transport (Argo) feed the shared
        breaker there; for everything else (local/fake) the reconciler's
        own call sites are the breaker's only signal source."""
        return not getattr(self.engine, "shares_kube_transport", False)

    async def _engine_submit(self, manifest: dict, key: str = "") -> str:
        """engine.submit behind the shared breaker: rejected fast while
        open, outcome recorded for transport-less engines. In the
        sharded fleet the SUBMIT is fenced too, not just the status
        write — a paused old owner resuming mid-cycle would otherwise
        still launch a duplicate workflow (whose record the write fence
        then drops, so the adopter re-runs it a third time). Zero extra
        I/O while our lease knowledge is fresh; one lease GET when
        stale — exactly the admit_write discipline."""
        if self.shards is not None and key:
            await self.shards.admit_write(key)
        breaker = self.resilience.breaker
        if not breaker.allow():
            raise BreakerOpenError(breaker.name, breaker.retry_after())
        try:
            wf_name = await self.engine.submit(manifest)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if self._records_engine_outcomes:
                breaker.observe(e)
            raise
        if self._records_engine_outcomes:
            breaker.observe(None)
        return wf_name

    async def _submit_workflow(self, hc: HealthCheck) -> str:
        try:
            with self.tracer.span("parse", healthcheck=hc.key):
                manifest = await self._parse_manifest(
                    parse_workflow_from_healthcheck, hc, hc.spec.workflow
                )
        except Exception:
            self.recorder.event(
                hc, EVENT_WARNING, "Warning", "Error creating or submitting workflow"
            )
            raise
        with self.tracer.span(
            "submit", healthcheck=hc.key, engine=self._engine_name
        ):
            wf_name = await self._engine_submit(manifest, key=hc.key)
        self.metrics.record_engine_submit(self._engine_name)
        # a clean submission breaks the pre-terminal error streak even
        # if the run later fails its verdict
        self.resilience.checks.note_submit_ok(hc.key)
        self.recorder.event(hc, EVENT_NORMAL, "Normal", "Successfully created workflow")
        return wf_name

    async def _pace_poll(
        self, ieb: InverseExpBackoff, wf_namespace: str, wf_name: str
    ) -> bool:
        """One backoff step between status polls. Engines exposing
        ``wait_change`` (the Argo engine's watch-backed cache) wake the
        loop the moment the workflow object changes instead of sleeping
        out the whole delay — detection becomes event-driven with the
        inverse-exp cadence as the fallback bound. The change-wait races
        the pacing sleep on ``self.clock``, so fake-clock tests drive
        time exactly as with poll-only engines. Returns False once the
        poll deadline has passed (caller synthesizes failure)."""
        waiter = getattr(self.engine, "wait_change", None)
        if waiter is None:
            return await ieb.next()
        if ieb.expired():
            return False
        sleep_task = asyncio.ensure_future(self.clock.sleep(ieb.advance()))
        wake_task = asyncio.ensure_future(waiter(wf_namespace, wf_name))
        try:
            await asyncio.wait(
                {sleep_task, wake_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if (
                wake_task.done()
                and not wake_task.cancelled()
                and wake_task.exception() is not None
                and not sleep_task.done()
            ):
                # a raising wait_change must not turn into an unpaced
                # hot poll loop: log it and let the backoff sleep pace
                log.warning(
                    "wait_change for %s/%s failed (%r); falling back to "
                    "timed polling for this step",
                    wf_namespace,
                    wf_name,
                    wake_task.exception(),
                )
                await sleep_task
        finally:
            for task in (sleep_task, wake_task):
                if not task.done():
                    task.cancel()
            await asyncio.gather(sleep_task, wake_task, return_exceptions=True)
        return True

    def _watch_active(self, key: str) -> bool:
        t = self._watch_tasks.get(key)
        return t is not None and not t.done()

    def _spawn_watch(self, hc: HealthCheck, wf_name: str) -> None:
        """Divergence 1: poll in a free task, not in the reconcile worker."""
        key = hc.key
        self._watch_tasks[key] = asyncio.create_task(
            self._watch_guarded(hc, wf_name),
            name=f"watch:{key}:{wf_name}",
        )

    async def _watch_guarded(self, hc: HealthCheck, wf_name: str) -> None:
        """Exception recovery for detached watch tasks: a transient
        engine/client error must not silently kill the check's schedule
        — emulate the reference's 1s requeue (:204) by re-reconciling."""
        try:
            if self.profile_capture is not None:
                # an armed profile-on-anomaly capture wraps exactly this
                # run (the watch IS the probe run: poll + status write);
                # a no-op context otherwise
                with self.profile_capture(hc.key):
                    await self._watch_workflow_reschedule(hc, wf_name)
            else:
                await self._watch_workflow_reschedule(hc, wf_name)
        except asyncio.CancelledError:
            raise
        except ShardFencedError as e:
            # handed off mid-watch (e.g. the remedy submit was fenced):
            # the new owner drives this check now — no requeue, and
            # never an error counted toward quarantine
            log.info("watch for %s stopped by the shard fence (%s)", hc.key, e)
            return
        except Exception:
            log.exception("watch failed for %s; requeueing in 1s", hc.key)
            self.recorder.event(
                hc, EVENT_WARNING, "Warning", "Error executing Workflow"
            )
            if await self._note_cycle_error(hc):
                return  # quarantined: the schedule stops here
            await self._requeue_until_clean(hc)

    async def _requeue_until_clean(self, hc: HealthCheck) -> None:
        """Put the check back on the reconcile path after a failed run —
        and keep it there until a reconcile lands cleanly (a single
        shot would strand the schedule if the API-server outage
        outlives one retry; the reference's workqueue re-rate-limits
        indefinitely, deletion ends the loop via None). Deregisters
        this task from the in-flight table first: the guard must not
        see a (still-running) requeue and skip the retry.

        Under a Manager the requeue goes through its WORKQUEUE
        (requeue_hook): per-key serialized against event-driven
        reconciles, honors stop, and a crashed reconcile re-rate-limits
        at 1 s — so no reconcile ever runs outside the queue's
        discipline, and nothing outlives Manager.stop(). The in-task
        loop remains only for standalone reconcilers (no Manager), is
        tracked in ``_requeue_loops``, and exits on shutdown."""
        if self._watch_tasks.get(hc.key) is asyncio.current_task():
            del self._watch_tasks[hc.key]
        current = asyncio.current_task()
        if current is not None:
            # tracked for BOTH paths: the hook path's 1 s sleeper was
            # deregistered from _watch_tasks above, so without this it
            # would be invisible to shutdown() and outlive stop()
            self._requeue_loops.add(current)
        if self.requeue_hook is not None:
            try:
                # the reference's 1 s cadence, stretched while degraded
                await self.clock.sleep(self.resilience.requeue_delay(1.0))
                if not self._stopping:
                    self.requeue_hook(hc.metadata.namespace, hc.metadata.name)
            finally:
                if current is not None:
                    self._requeue_loops.discard(current)
            return
        try:
            delay: Optional[float] = self.resilience.requeue_delay(1.0)
            while delay and not self._stopping:
                await self.clock.sleep(delay)
                if self._stopping:
                    return
                try:
                    delay = await self.reconcile(
                        hc.metadata.namespace, hc.metadata.name
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("requeued reconcile of %s failed", hc.key)
                    delay = self.resilience.requeue_delay(1.0)
        finally:
            if current is not None:
                self._requeue_loops.discard(current)

    def has_inflight(self, predicate) -> bool:
        """True while any live watch task tracks a key matching
        ``predicate`` — the shard layer defers voluntary sheds on this
        (an in-flight run whose status write lands after the shed would
        be fenced and dropped, and the adopter would re-run it)."""
        return any(
            predicate(key)
            for key, task in self._watch_tasks.items()
            if not task.done()
        )

    def release_keys(self, predicate) -> int:
        """Shard handoff: drop every piece of LOCAL scheduling state for
        keys matching ``predicate`` — pending timers, in-flight watch
        tasks, queued status writes. The adopting owner rebuilds all of
        it from durable status (divergence 10), so anything left here
        could only double-fire or write fenced garbage. Returns how many
        timers/watches were released."""
        released = 0
        for key in self.timers.names():
            if predicate(key) and self.timers.stop(key):
                released += 1
        for key, task in list(self._watch_tasks.items()):
            if not predicate(key):
                continue
            if not task.done():
                task.cancel()
                released += 1
            self._watch_tasks.pop(key, None)
        self.resilience.drop_status_writes_matching(predicate)
        return released

    async def wait_watches(self) -> None:
        """Test/shutdown helper: wait for all in-flight watches."""
        tasks = [t for t in self._watch_tasks.values() if not t.done()]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def shutdown(self) -> None:
        self._stopping = True
        stragglers = list(self._watch_tasks.values()) + list(self._requeue_loops)
        for t in stragglers:
            if not t.done():
                t.cancel()
        await asyncio.gather(*stragglers, return_exceptions=True)
        await self.timers.shutdown()

    async def _poll_workflow(
        self,
        wf_namespace: str,
        wf_name: str,
        ieb: InverseExpBackoff,
        timed_out: bool,
        *,
        storm_rides_past_deadline: bool,
        what: str = "workflow",
    ):
        """One poll step shared by the healthcheck and remedy watches —
        the error policy lives HERE so the two loops cannot drift:

        - pre-deadline errors always retry in place at the 1 s requeue
          cadence (aborting to a requeued reconcile submits a DUPLICATE
          workflow for the same fire — the defect the chaos soak found);
        - past the deadline, the verdict comes from an authoritative
          confirm-read. A TRANSIENT error (5xx/429) retries that read
          when ``storm_rides_past_deadline`` (healthcheck watch: the
          liveness of the old requeue-forever ladder, without its
          duplicates); a DETERMINISTIC error (4xx, code bug) — or any
          error on the remedy path, whose ephemeral WRITE-capable RBAC
          must not stay alive under an unbounded storm — stops
          retrying, and the caller synthesizes Failed.

        Returns ``(workflow, timed_out, retry)``; ``retry=True`` means
        the caller should ``continue`` its loop (workflow is None then).
        """
        self.metrics.record_engine_poll(self._engine_name)
        breaker = self.resilience.breaker
        try:
            # the shared breaker gates polls too: while it is open no
            # read is attempted (BreakerOpenError duck-types as a
            # transient 503 below, so the loop retries in place at the
            # degraded cadence instead of hammering a sick backend)
            if not breaker.allow():
                raise BreakerOpenError(breaker.name, breaker.retry_after())
            if timed_out:
                # the deadline verdict must come from the API server,
                # not a possibly-lagging watch cache: a terminal phase
                # that landed during a watch reconnect gap must win
                getter = getattr(self.engine, "get_fresh", self.engine.get)
                workflow = await getter(wf_namespace, wf_name)
            else:
                workflow = await self.engine.get(wf_namespace, wf_name)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if self._records_engine_outcomes:
                breaker.observe(e)
            transient = is_transient(e)
            log.warning(
                "%s error polling %s %s/%s%s",
                "transient" if transient else "deterministic",
                what,
                wf_namespace,
                wf_name,
                (
                    "; giving up on this run (synthesizing Failed)"
                    if timed_out and not (transient and storm_rides_past_deadline)
                    else "; retrying"
                ),
                exc_info=True,
            )
            if timed_out and not (transient and storm_rides_past_deadline):
                return {}, timed_out, False  # caller synthesizes Failed
            # the reference's 1 s error cadence, stretched while degraded
            await self.clock.sleep(self.resilience.requeue_delay(1.0))
            if ieb.expired():
                timed_out = True
            return None, timed_out, True
        if self._records_engine_outcomes:
            breaker.observe(None)
        return workflow, timed_out, False

    # ------------------------------------------------------------------
    # watch + status + reschedule (reference: watchWorkflowReschedule, :607-757)
    # ------------------------------------------------------------------
    async def _watch_workflow_reschedule(self, hc: HealthCheck, wf_name: str) -> None:
        wf_namespace = hc.spec.workflow.resource.namespace
        then = self.clock.now()
        params = compute_backoff_params(
            workflow_timeout=hc.spec.workflow.timeout,
            backoff_max=hc.spec.backoff_max,
            backoff_min=hc.spec.backoff_min,
            backoff_factor=hc.spec.backoff_factor,
        )
        ieb = InverseExpBackoff(params, self.clock)
        timed_out = False
        run_remedy = False
        polls = 0
        # one "poll" span bounds the whole detection window (submit →
        # terminal phase); remedy and the status write are SIBLING
        # phases recorded after it, so per-phase durations add up to the
        # cycle instead of nesting remedy time inside poll time
        with self.tracer.span(
            "poll", healthcheck=hc.key, workflow=wf_name
        ) as poll_span:
            while True:
                now = self.clock.now()
                polls += 1
                workflow, timed_out, retry = await self._poll_workflow(
                    wf_namespace, wf_name, ieb, timed_out,
                    storm_rides_past_deadline=True,
                )
                if retry:
                    continue
                if workflow is None:
                    # workflow GC'd / healthcheck deleted: swallow, no reschedule
                    # (reference: :618-623)
                    self.recorder.event(
                        hc,
                        EVENT_WARNING,
                        "Warning",
                        "Error attempting to find workflow for healthcheck. This may "
                        "indicate that either the healthcheck was removed or the "
                        "Workflow was GC'd before active-monitor could obtain the status",
                    )
                    poll_span.attrs["outcome"] = "gone"
                    return
                status = workflow.get("status") or {}
                if timed_out and status.get("phase") not in (PHASE_SUCCEEDED, PHASE_FAILED):
                    # poll deadline exceeded ⇒ synthesized failure (reference:
                    # :627-632 — though unlike the reference, a terminal phase
                    # seen on this final poll is honored rather than discarded)
                    status = {"phase": PHASE_FAILED, "message": PHASE_FAILED}
                    self.recorder.event(hc, EVENT_WARNING, "Warning", "Workflow timed out")
                phase = status.get("phase")

                if phase == PHASE_SUCCEEDED:
                    self.recorder.event(
                        hc, EVENT_NORMAL, "Normal", "Workflow status is Succeeded"
                    )
                    hc.status.status = PHASE_SUCCEEDED
                    hc.status.started_at = then
                    hc.status.finished_at = now
                    hc.status.success_count += 1
                    hc.status.total_healthcheck_runs = (
                        hc.status.success_count + hc.status.failed_count
                    )
                    hc.status.last_successful_workflow = wf_name
                    self.metrics.record_success(
                        hc.metadata.name,
                        WORKFLOW_LABEL_HEALTHCHECK,
                        then.timestamp(),
                        now.timestamp(),
                    )
                    # custom metrics, wired for real (reference gap:
                    # SURVEY.md §2) — keyed by the workflow run so a
                    # status replayed through a second path can never
                    # double-increment counter-type metrics
                    self.metrics.record_custom_metrics(
                        hc.metadata.name, status, run_id=wf_name
                    )
                    samples = MetricsCollector.parse_custom_samples(status)
                    timings = MetricsCollector.parse_phase_timings(status)
                    roofline = MetricsCollector.parse_roofline(status)
                    # the run lands in the result history on the same
                    # path that writes status — one source for SLO math,
                    # the anomaly detectors AND goodput attribution
                    self.fleet.record(
                        hc,
                        ok=True,
                        latency=(now - then).total_seconds(),
                        workflow=wf_name,
                        metrics=samples,
                        timings=timings,
                        roofline=roofline,
                    )
                    # the verdict drives the flap state machine; the
                    # durable .status.state mark rides this same write
                    self._note_verdict(hc, ok=True)
                    # baseline analysis: a run can PASS its threshold yet
                    # be far below its own baseline — the degradation
                    # verdict (and optionally the remedy) comes from here
                    degraded = self._note_analysis(
                        hc, samples, ok=True, run_id=wf_name
                    )
                    trigger_degraded = (
                        degraded
                        and hc.spec.analysis is not None
                        and hc.spec.analysis.trigger_on_degraded
                        and not hc.spec.remedy_workflow.is_empty()
                    )
                    if trigger_degraded:
                        # spec.analysis.triggerOnDegraded: treat the
                        # confirmed degradation like a failure for remedy
                        # purposes (the per-check and fleet-wide remedy
                        # gates still apply downstream)
                        self.recorder.event(
                            hc,
                            EVENT_WARNING,
                            "Warning",
                            "HealthCheck passed but metrics are degraded "
                            "from baseline; triggering remedy",
                        )
                        run_remedy = True
                    elif (
                        not hc.spec.remedy_workflow.is_empty()
                        and hc.status.remedy_total_runs >= 1
                    ):
                        hc.status.reset_remedy("HealthCheck Passed so Remedy is reset")
                        self.recorder.event(
                            hc, EVENT_NORMAL, "Normal", "HealthCheck passed so Remedy is reset"
                        )
                    break

                if phase == PHASE_FAILED:
                    self.recorder.event(
                        hc, EVENT_WARNING, "Warning", "Workflow status is Failed"
                    )
                    hc.status.status = PHASE_FAILED
                    hc.status.started_at = then
                    hc.status.finished_at = now
                    hc.status.last_failed_at = now
                    hc.status.error_message = str(status.get("message") or "")
                    hc.status.failed_count += 1
                    hc.status.total_healthcheck_runs = (
                        hc.status.success_count + hc.status.failed_count
                    )
                    hc.status.last_failed_workflow = wf_name
                    self.metrics.record_failure(
                        hc.metadata.name,
                        WORKFLOW_LABEL_HEALTHCHECK,
                        then.timestamp(),
                        now.timestamp(),
                    )
                    self.metrics.record_custom_metrics(
                        hc.metadata.name, status, run_id=wf_name
                    )
                    samples = MetricsCollector.parse_custom_samples(status)
                    timings = MetricsCollector.parse_phase_timings(status)
                    roofline = MetricsCollector.parse_roofline(status)
                    self.fleet.record(
                        hc,
                        ok=False,
                        latency=(now - then).total_seconds(),
                        workflow=wf_name,
                        metrics=samples,
                        timings=timings,
                        roofline=roofline,
                    )
                    self._note_verdict(hc, ok=False)
                    # failed runs never feed the baselines (their
                    # metrics, if any, describe a broken run) — but the
                    # durable analysis blob still rides this write
                    self._note_analysis(hc, samples, ok=False, run_id=wf_name)
                    run_remedy = True
                    break

                if not await self._pace_poll(ieb, wf_namespace, wf_name):
                    timed_out = True
            poll_span.attrs["outcome"] = phase
            poll_span.attrs["polls"] = polls
        if run_remedy:
            # same position in the flow as the reference's in-loop call
            # (:681): after failure accounting, before the status write
            await self._maybe_run_remedy(hc)

        # status write + reschedule (reference: :732-755)
        if hc.metadata.deletion_timestamp is None:
            try:
                with self.tracer.span("status_write", healthcheck=hc.key):
                    await self._update_status(hc)
            except NotFoundError:
                self.timers.stop(hc.key)
                return
            except Exception:
                # transient write failure (API-server blip outliving the
                # conflict retries): raise so _watch_guarded requeues in
                # 1s like the reference's reconcile error path (:204).
                # Stopping the timer here instead would leave the check
                # schedule dead until some external watch event arrived.
                log.exception("error updating healthcheck resource %s", hc.key)
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "Error updating healthcheck resource"
                )
                raise
            repeat = self._effective_repeat_after(hc)
            if repeat > 0:
                self.timers.schedule(hc.key, repeat, self._resubmit_callback(hc))
                self.recorder.event(
                    hc, EVENT_NORMAL, "Normal", "Rescheduled workflow for next run"
                )

    def _effective_repeat_after(self, hc: HealthCheck) -> int:
        """Divergence 2: recompute the interval at reschedule time —
        damped by the flap tracker's composed factor, so a flapping
        check burns budget and apiserver capacity at a fraction of its
        cadence until its verdict stabilizes, and a burning check
        (resilience/adapt.py, factor < 1) confirms recovery sooner.
        Floored at 1s: a tightened short interval truncating to 0 would
        read as "paused", silently stopping the very check the adaptive
        loop wants to run MORE often."""
        damp = self.resilience.checks.damp_factor(hc.key)
        if hc.spec.repeat_after_sec > 0 and not hc.spec.schedule.cron:
            return max(1, int(hc.spec.repeat_after_sec * damp))
        if hc.spec.schedule.cron:
            try:
                return max(
                    1,
                    int(
                        seconds_until_next(
                            hc.spec.schedule.cron, self.clock.now()
                        )
                        * damp
                    ),
                )
            except CronParseError:
                return 0
        if hc.spec.repeat_after_sec > 0:
            return max(1, int(hc.spec.repeat_after_sec * damp))
        return 0

    def _resubmit_callback(self, prev_hc: HealthCheck):
        """Timer-fired resubmission (reference: createSubmitWorkflowHelper,
        :479-500): re-fetch the CR, submit, watch."""

        namespace, name = prev_hc.metadata.namespace, prev_hc.metadata.name

        async def resubmit() -> None:
            # atomically (no awaits) check-and-claim the in-flight slot:
            # registering BEFORE the first await means a concurrent
            # reconcile sees _watch_active and cannot cancel this timer
            # task mid-submit (which would orphan a created workflow)
            current = asyncio.current_task()
            existing = self._watch_tasks.get(f"{namespace}/{name}")
            if existing is not None and not existing.done() and existing is not current:
                # a run is still in flight (it will reschedule on its
                # own completion) — never stack a duplicate
                return
            if current is not None:
                self._watch_tasks[f"{namespace}/{name}"] = current

            # sharded fleet: the shard may have been handed off since
            # this timer was armed (shed, lease lost) — its new owner
            # drives the schedule now, so firing here would double-run
            if self.shards is not None and not self.shards.owns_key(
                f"{namespace}/{name}"
            ):
                return

            hc = await self.client.get(namespace, name)
            if hc is None:
                return
            # same freshest-truth overlay as _process: a status (or a
            # quarantine mark) parked in the replay queue must win over
            # the stale durable copy — without it the gate below would
            # misread a queued Quarantined mark as a user clear
            queued = self.resilience.queued_status(hc.key)
            if queued is not None:
                hc.status = queued.model_copy(deep=True)
            # a check quarantined since the timer was armed must not
            # refire (the gate also adopts/clears the durable mark)
            if await self._quarantine_gate(hc):
                return
            # the spec may have changed since this timer was armed: if
            # nothing is owed under the CURRENT spec (cadence slowed, or
            # a sub-second rounding sliver), re-arm for the remaining
            # time instead of firing early
            remaining = self._schedule_remaining(hc)
            if remaining is not None:
                self.timers.schedule(hc.key, remaining, self._resubmit_callback(hc))
                return
            # keep the effective interval for timeout/backoff derivation
            if hc.spec.repeat_after_sec <= 0 and hc.spec.schedule.cron:
                try:
                    hc.spec.repeat_after_sec = seconds_until_next(
                        hc.spec.schedule.cron, self.clock.now()
                    )
                except CronParseError:
                    return
            if hc.spec.repeat_after_sec <= 0:
                return  # paused since the timer was armed
            # a fresh ROOT trace per timer-driven run: the timer task's
            # context snapshot was taken when the PREVIOUS cycle armed
            # it, so inheriting would chain every run of this check into
            # one unbounded trace
            with self.tracer.trace("cycle", healthcheck=hc.key, origin="timer"):
                try:
                    await self.rbac.create_rbac_for_workflow(
                        hc, WORKFLOW_TYPE_HEALTHCHECK
                    )
                    wf_name = await self._submit_workflow(hc)
                except asyncio.CancelledError:
                    raise
                except ShardFencedError as e:
                    # handed off between the ownership gate above and
                    # the submit: the new owner fires this run
                    log.info(
                        "timer-fired run for %s stopped by the shard "
                        "fence (%s)", hc.key, e,
                    )
                    return
                except Exception:
                    log.exception(
                        "error creating or submitting workflow for %s", hc.key
                    )
                    self.recorder.event(
                        hc,
                        EVENT_WARNING,
                        "Warning",
                        "Error creating or submitting workflow",
                    )
                    # the timer entry is consumed, so bailing here would end
                    # the check's schedule FOREVER (the chaos-soak tier
                    # caught exactly this: a 500 on the timer-fired resubmit
                    # left dead schedules — owed run, no timer, no watch).
                    # Ride the same requeue ladder a failed watch uses —
                    # unless the streak just quarantined the check.
                    if await self._note_cycle_error(hc):
                        return
                    await self._requeue_until_clean(hc)
                    return
                # already registered in _watch_tasks at the top, so
                # reconcile's in-flight guard and wait_watches() saw this
                # timer-driven run from before the submit
                await self._watch_guarded(hc, wf_name)

        return resubmit

    # ------------------------------------------------------------------
    # remedy (reference: :677-721 gating, processRemedyWorkflow :759-786,
    # watchRemedyWorkflow :788-874)
    # ------------------------------------------------------------------
    async def _maybe_run_remedy(self, hc: HealthCheck) -> None:
        spec = hc.spec
        if spec.remedy_workflow.is_empty():
            return
        if spec.remedy_runs_limit != 0 and spec.remedy_reset_interval != 0:
            if spec.remedy_runs_limit > hc.status.remedy_total_runs:
                await self._admit_remedy(hc)
            else:
                # limit hit: wait out the reset interval, then reset and run
                # (reference: :689-711)
                since_last = (
                    (self.clock.now() - hc.status.remedy_finished_at).total_seconds()
                    if hc.status.remedy_finished_at is not None
                    else float("inf")
                )
                if spec.remedy_reset_interval >= since_last:
                    log.info(
                        "skipping remedy for %s: run limit reached, waiting out "
                        "the reset interval",
                        hc.key,
                    )
                else:
                    hc.status.reset_remedy("RemedyResetInterval elapsed so Remedy is reset")
                    self.recorder.event(
                        hc,
                        EVENT_NORMAL,
                        "Normal",
                        "RemedyResetInterval elapsed so Remedy is reset",
                    )
                    await self._admit_remedy(hc)
        else:
            # gates unset ⇒ always run (reference: :712-720)
            await self._admit_remedy(hc)

    async def _admit_remedy(self, hc: HealthCheck) -> None:
        """The fleet-wide remedy rate cap (docs/resilience.md), layered
        ON TOP of the per-check gates above: one bad rollout failing
        hundreds of checks at once must not launch hundreds of
        self-healing workflows in the same minute. Suppressed runs are
        evented and counted; the per-check gates are untouched, so the
        next failure after refill runs the remedy normally."""
        name, namespace = hc.metadata.name, hc.metadata.namespace
        if not self.resilience.admit_remedy():
            self.metrics.record_remedy_run(name, namespace, "suppressed")
            log.warning(
                "remedy for %s suppressed: fleet-wide remedy budget "
                "(--remedy-rate) exhausted",
                hc.key,
            )
            self.recorder.event(
                hc,
                EVENT_WARNING,
                "Warning",
                "Remedy suppressed by the fleet-wide remedy rate cap",
            )
            return
        self.metrics.record_remedy_run(name, namespace, "admitted")
        await self._process_remedy(hc)

    async def _process_remedy(self, hc: HealthCheck) -> None:
        with self.tracer.span("remedy", healthcheck=hc.key):
            await self._process_remedy_inner(hc)

    async def _process_remedy_inner(self, hc: HealthCheck) -> None:
        # attribution-targeted selection (resilience/adapt.py lever 2):
        # the failing run's bucket — recorded by the fleet BEFORE the
        # remedy gate ran — picks a byBucket workflow over the plain
        # fallback. RBAC below still provisions from the plain entry
        # (the documented contract: byBucket entries ride the fallback's
        # serviceAccount unless they name a pre-provisioned one).
        last = self.fleet.history.last(hc.key)
        bucket = last.bucket if last is not None else ""
        remedy = hc.spec.remedy_workflow.select_for_bucket(bucket)
        if remedy is None:
            # only unmatched byBucket entries, no fallback: a remedy is
            # configured but not for THIS failure mode — evented, never
            # an error (the next failure may hit a mapped bucket)
            self.recorder.event(
                hc,
                EVENT_NORMAL,
                "Normal",
                "No remedy configured for attribution bucket "
                f"'{bucket or 'unknown'}'; skipping remedy run",
            )
            return
        if remedy is not hc.spec.remedy_workflow:
            self.adapt.note_remedy_selected(hc.key, bucket)
            self.recorder.event(
                hc,
                EVENT_NORMAL,
                "Normal",
                f"Selected byBucket['{bucket}'] remedy workflow for this "
                "failure's attribution",
            )
        await self.rbac.create_rbac_for_workflow(hc, WORKFLOW_TYPE_REMEDY)
        # remedy RBAC is ephemeral (reference: :779-784) — and because
        # it is the WRITE-capable identity, it must be torn down on
        # every exit path: a parse error, a submit failure, or an engine
        # exception mid-watch may not leave the SA/Role/Binding behind
        # (the reference shares this leak shape at
        # healthcheck_controller.go:773-784; we close it)
        try:
            try:
                with self.tracer.span(
                    "parse", healthcheck=hc.key, workflow_type="remedy"
                ):
                    manifest = await self._parse_manifest(
                        lambda h: parse_remedy_workflow_from_healthcheck(
                            h, remedy=remedy
                        ),
                        hc,
                        remedy,
                    )
            except Exception:
                self.recorder.event(
                    hc,
                    EVENT_WARNING,
                    "Warning",
                    "Error creating or submitting remedyworkflow",
                )
                raise
            with self.tracer.span(
                "submit",
                healthcheck=hc.key,
                workflow_type="remedy",
                engine=self._engine_name,
            ):
                wf_name = await self._engine_submit(manifest, key=hc.key)
            self.metrics.record_engine_submit(self._engine_name)
            self.recorder.event(
                hc, EVENT_NORMAL, "Normal", "Successfully created remedyWorkflow"
            )
            await self._watch_remedy_workflow(hc, wf_name, remedy)
        finally:
            try:
                await self.rbac.delete_rbac_for_workflow(hc)
            except Exception:
                # a failed teardown must not mask the original error;
                # the next remedy run retries the delete via the
                # collision-rename path
                log.warning(
                    "failed to delete ephemeral remedy RBAC for %s",
                    hc.key,
                    exc_info=True,
                )

    async def _watch_remedy_workflow(
        self, hc: HealthCheck, wf_name: str, remedy=None
    ) -> None:
        # watch the namespace the SELECTED remedy actually submitted to
        # (a byBucket entry may target a different namespace than the
        # plain fallback)
        if remedy is None:
            remedy = hc.spec.remedy_workflow
        wf_namespace = remedy.resource.namespace
        then = self.clock.now()
        # remedy polling derives from the CHECK's timeout with default
        # factor — parity with the reference (:791-801)
        params = compute_backoff_params(workflow_timeout=hc.spec.workflow.timeout)
        ieb = InverseExpBackoff(params, self.clock)
        timed_out = False
        with self.tracer.span(
            "poll", healthcheck=hc.key, workflow=wf_name, workflow_type="remedy"
        ):
            write_owed = await self._watch_remedy_loop(
                hc, wf_name, wf_namespace, then, ieb, timed_out
            )
        if not write_owed:
            return
        if hc.metadata.deletion_timestamp is None:
            try:
                with self.tracer.span(
                    "status_write", healthcheck=hc.key, workflow_type="remedy"
                ):
                    await self._update_status(hc)
            except NotFoundError:
                self.timers.stop(hc.key)

    async def _watch_remedy_loop(
        self, hc, wf_name, wf_namespace, then, ieb, timed_out
    ) -> bool:
        """Poll the remedy workflow to a terminal verdict and record it
        on ``hc.status``; returns False when the workflow vanished
        (parent deleted / GC'd) and no status write is owed."""
        while True:
            now = self.clock.now()
            workflow, timed_out, retry = await self._poll_workflow(
                wf_namespace, wf_name, ieb, timed_out,
                # the finally in _process_remedy would otherwise hold the
                # WRITE-capable ephemeral RBAC alive under an unbounded
                # storm — the remedy path always converges at the deadline
                storm_rides_past_deadline=False,
                what="remedy workflow",
            )
            if retry:
                continue
            if workflow is None:
                return False  # parent deleted / GC'd (reference: :806-810)
            status = workflow.get("status") or {}
            if timed_out and status.get("phase") not in (PHASE_SUCCEEDED, PHASE_FAILED):
                # same final-poll policy as the healthcheck loop above: a
                # terminal phase seen at the deadline is honored, not discarded
                status = {"phase": PHASE_FAILED, "message": PHASE_FAILED}
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "remedy workflow is timedout"
                )
            phase = status.get("phase")

            if phase == PHASE_SUCCEEDED:
                self.recorder.event(
                    hc, EVENT_NORMAL, "Normal", "Remedy workflow status is Succeeded"
                )
                hc.status.remedy_status = PHASE_SUCCEEDED
                hc.status.remedy_started_at = then
                hc.status.remedy_finished_at = now
                hc.status.remedy_success_count += 1
                hc.status.remedy_total_runs = (
                    hc.status.remedy_success_count + hc.status.remedy_failed_count
                )
                hc.status.last_successful_workflow = wf_name
                self.metrics.record_success(
                    hc.metadata.name,
                    WORKFLOW_LABEL_REMEDY,
                    then.timestamp(),
                    now.timestamp(),
                )
                self.metrics.record_custom_metrics(
                    hc.metadata.name, status, run_id=wf_name
                )
                break
            if phase == PHASE_FAILED:
                self.recorder.event(
                    hc, EVENT_WARNING, "Warning", "remedy workflow status is failed"
                )
                hc.status.remedy_status = PHASE_FAILED
                hc.status.remedy_started_at = then
                hc.status.remedy_finished_at = now
                hc.status.remedy_last_failed_at = now
                hc.status.remedy_error_message = str(status.get("message") or "")
                hc.status.remedy_failed_count += 1
                hc.status.remedy_total_runs = (
                    hc.status.remedy_success_count + hc.status.remedy_failed_count
                )
                hc.status.last_failed_workflow = wf_name
                self.metrics.record_failure(
                    hc.metadata.name,
                    WORKFLOW_LABEL_REMEDY,
                    then.timestamp(),
                    now.timestamp(),
                )
                self.metrics.record_custom_metrics(
                    hc.metadata.name, status, run_id=wf_name
                )
                break

            if not await self._pace_poll(ieb, wf_namespace, wf_name):
                timed_out = True
        return True

    # ------------------------------------------------------------------
    # status writes (reference: updateHealthCheckStatus, :1445-1462)
    # ------------------------------------------------------------------
    def _note_fenced_write(self, hc: HealthCheck, why: Exception | None = None) -> None:
        """A status write was rejected by the shard fence: the key's
        shard has a new owner, so this replica's record of the run is
        DROPPED (never queued — replaying it later would overwrite the
        new owner's truth, the split-brain write the chaos soak pins)."""
        log.warning(
            "dropping status write for %s: shard fence rejected it (%s)",
            hc.key, why or "shard not owned",
        )
        if self.shards is not None:
            self.shards.note_fenced(hc.key)

    async def _update_status(self, hc: HealthCheck) -> None:
        res = self.resilience
        if self.shards is not None and not self.shards.owns_for_write(hc.key):
            # cheap local fence BEFORE the breaker check: a degraded old
            # owner must not park a fenced write for replay either.
            # owns_for_write, not owns_key: a shard mid-voluntary-shed
            # (draining) still holds its lease, and an in-flight run
            # finishing during the pre-shed scan must record its result
            self._note_fenced_write(hc)
            return
        if not res.breaker.allow():
            # degraded mode: the write records a run that ALREADY
            # happened — park it for replay instead of failing the
            # cycle (the reschedule proceeds, so the cadence survives
            # the outage and nothing double-submits meanwhile)
            res.queue_status_write(hc)
            return
        try:
            await self._write_status_now(hc)
        except ShardFencedError as e:
            self._note_fenced_write(hc, e)
            return
        except BreakerOpenError:
            # the breaker tripped mid-ladder (these very failures fed
            # it): same parking contract as above
            res.queue_status_write(hc)
            return
        except Exception as e:
            if is_transient(e) and not res.breaker.allow():
                # the ladder exhausted on transients AND the breaker is
                # now open (fed by those failures, possibly recorded
                # only at ladder granularity): park instead of raising,
                # or the requeue path would re-reconcile a stale status
                # and double-submit the run this write records
                res.queue_status_write(hc)
                return
            raise
        if res.pending_status_writes():
            # a live write just landed, so the path is back: drain the
            # backlog opportunistically rather than waiting for the
            # manager's next sweep
            await self.replay_status_writes()

    async def _write_status_now(self, hc: HealthCheck) -> None:
        if self.shards is not None:
            # resourceVersion fencing (controller/sharding.py): verify
            # this replica still holds the key's shard lease before the
            # write — a paused old owner's late write raises here and is
            # dropped by every caller, never retried or queued
            await self.shards.admit_write(hc.key)

        async def attempt():
            fresh = await self.client.get(hc.metadata.namespace, hc.metadata.name)
            if fresh is None:
                raise NotFoundError(hc.key)
            fresh.status = hc.status.model_copy(deep=True)
            return await self.client.update_status(fresh)

        async def write():
            return await retry_on_conflict(attempt)

        # client outcomes feed the shared breaker — at the KubeApi
        # transport for cluster clients, here for everything else
        record = not getattr(self.client, "shares_kube_transport", False)
        try:
            # transient 5xx ride out IN PLACE: this write records a run
            # that already happened, and losing it sends the requeue path
            # back through a full reconcile that submits a DUPLICATE
            # workflow for the same scheduled fire (the chaos-soak tier
            # measured 26 submissions for 3 recorded runs without this)
            updated = await retry_on_transient(write, clock=self.clock)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if record:
                res_breaker = self.resilience.breaker
                res_breaker.observe(e)
            raise
        if record:
            self.resilience.breaker.observe(None)
        hc.metadata.resource_version = updated.metadata.resource_version
