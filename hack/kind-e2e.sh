#!/usr/bin/env bash
# Real-cluster e2e tier, runnable locally with one command:
#
#   make kind-e2e
#
# Stands up a kind cluster (hack/kind-cluster.yaml), installs the CRD +
# Argo (pinned, instance-id contract wired), runs the controller
# against the cluster, applies examples/inline-hello.yaml and asserts
# it reaches Succeeded with real per-check RBAC objects and Events.
# The same steps run in CI (ci.yml kind-e2e job calls this script) —
# reference equivalent: the manual kind flow in README.md:54-79.
#
# Requirements: kind, kubectl, docker, python (with this repo installed
# or `pip install -e .`-able).
set -euo pipefail
cd "$(dirname "$0")/.."

CLUSTER_NAME="${KIND_CLUSTER_NAME:-activemonitor-e2e}"
KEEP_CLUSTER="${KEEP_CLUSTER:-0}"
TIMEOUT_SECS="${E2E_TIMEOUT_SECS:-300}"
CONTROLLER_PID=""

cleanup() {
  [ -n "$CONTROLLER_PID" ] && kill "$CONTROLLER_PID" 2>/dev/null || true
  if [ "$KEEP_CLUSTER" != "1" ]; then
    kind delete cluster --name "$CLUSTER_NAME" 2>/dev/null || true
  fi
}
trap cleanup EXIT

if ! kind get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
  kind create cluster --name "$CLUSTER_NAME" --config hack/kind-cluster.yaml
fi
kubectl config use-context "kind-$CLUSTER_NAME"

echo "--- installing CRD, namespace, Argo"
kubectl apply -f config/crd/activemonitor.keikoproj.io_healthchecks.yaml
kubectl create namespace health --dry-run=client -o yaml | kubectl apply -f -
./deploy/install-argo.sh

echo "--- starting controller against the kind cluster"
python -m activemonitor_tpu run --client k8s --engine argo \
  --no-metrics-secure --metrics-bind-address 127.0.0.1:18443 \
  --health-probe-bind-address 127.0.0.1:18081 &
CONTROLLER_PID=$!
sleep 5
kill -0 "$CONTROLLER_PID" || { echo "controller died at startup"; exit 1; }

echo "--- applying examples/inline-hello.yaml and waiting for Succeeded"
python -m activemonitor_tpu apply --client k8s -f examples/inline-hello.yaml

status=""
deadline=$((SECONDS + TIMEOUT_SECS))
while [ "$SECONDS" -lt "$deadline" ]; do
  status=$(kubectl -n health get hc inline-hello \
    -o jsonpath='{.status.status}' 2>/dev/null || true)
  [ "$status" = "Succeeded" ] && break
  sleep 5
done
if [ "$status" != "Succeeded" ]; then
  echo "check never reached Succeeded (last: '$status'); dumping state"
  kubectl -n health get hc -o yaml || true
  kubectl -n health get workflows.argoproj.io -o wide || true
  kubectl -n health get pods -o wide || true
  exit 1
fi

echo "--- asserting real per-check RBAC + Events"
kubectl -n health get serviceaccount activemonitor-probe-sa
kubectl -n health get events \
  --field-selector involvedObject.kind=HealthCheck | head

echo "kind-e2e OK: inline-hello Succeeded with real RBAC and Events"
