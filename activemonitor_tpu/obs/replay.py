"""Deterministic replay of a recorded front-door workload trace.

The journal's ``arrival`` stream (obs/journal.py) is the workload
trace ROADMAP item 6 asks for: every front-door submission with its
booked tenant, check key, outcome and inter-arrival gap. This module
turns that trace back into load:

- :class:`RecordedArrivals` — a deterministic arrival schedule with
  the SAME interface as ``scheduler/arrivals.PoissonArrivals``
  (``next()`` / ``choice()`` / ``now``), so
  ``frontdoor/traffic.replayed_checks`` emits ``CheckRequest``s the
  exact way ``open_loop_checks`` does, just from the recording instead
  of a seeded Poisson process.
- :func:`load_trace` — journal directory → schedule + the structured
  restore warnings (``load_blob`` discipline, via ``read_journal``).
- :func:`drive_requests` — the shared FakeClock harness that pushes a
  schedule through a real ``FrontDoor`` (admission → coalescing →
  trigger) with a synthetic always-ok backend, recording through a
  journal when one is wired. The ``am-tpu record``/``replay`` verbs,
  the ``frontdoor-replay`` matrix op and the acceptance tests all
  drive THIS function, so "replay is deterministic" is one property
  proven in one place.

Determinism contract, mirroring PoissonArrivals': one pass, fixed draw
order per request — ``next()`` (arrival time from the recorded gap),
then ``choice(tenants)`` (the recorded tenant), then ``choice(checks)``
(the recorded check). ``choice`` answers from the recording when the
recorded value is in the offered universe and falls back to the first
element otherwise (a trace replayed against a shrunken check set stays
deterministic instead of crashing).

Wall-clock-free by construction (``hack/lint.py`` bans ``time.time()``
/ ``time.monotonic()`` here, same module-name keying as journal.py):
the schedule lives on the recorded timeline and the harness lives on a
FakeClock advanced to each arrival.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from activemonitor_tpu.obs.journal import STREAM_ARRIVAL, read_journal

# synthetic backend latency the drive harness stamps on every resolved
# run — any positive constant works, the point is that it is the SAME
# for record and replay so outcome sequences compare bit-exactly
DRIVE_LATENCY_SECONDS = 0.01


class RecordedArrivals:
    """A recorded arrival stream as a deterministic schedule.

    ``events`` are journal ``arrival`` dicts (or anything with
    ``tenant``/``check``/``gap`` keys), oldest first."""

    def __init__(self, events: Sequence[dict]):
        self._events: List[dict] = [
            {
                "tenant": str(ev.get("tenant", "")),
                "check": str(ev.get("check", "")),
                "gap": max(0.0, float(ev.get("gap", 0.0) or 0.0)),
                "freshness": ev.get("freshness"),
            }
            for ev in events
        ]
        self.now = 0.0
        self._i = -1
        # the pending replay draws for the current request, popped by
        # choice() in the documented order: tenant first, then check
        self._pending: List[str] = []
        self.tenants: Tuple[str, ...] = tuple(
            sorted({ev["tenant"] for ev in self._events if ev["tenant"]})
        )
        self.checks: Tuple[str, ...] = tuple(
            sorted({ev["check"] for ev in self._events if ev["check"]})
        )

    def __len__(self) -> int:
        return len(self._events)

    @property
    def freshness(self) -> Optional[float]:
        """The current request's recorded per-request freshness
        override (None: the door default was used)."""
        if 0 <= self._i < len(self._events):
            value = self._events[self._i]["freshness"]
            return float(value) if value is not None else None
        return None

    def next(self) -> float:
        """The next recorded arrival time (cumulative gaps), advancing
        to the next recorded request — PoissonArrivals.next()'s
        contract on the recorded timeline."""
        self._i += 1
        if self._i >= len(self._events):
            raise IndexError("recorded trace exhausted")
        event = self._events[self._i]
        self.now += event["gap"]
        self._pending = [event["tenant"], event["check"]]
        return self.now

    def choice(self, seq: Sequence[str]) -> str:
        """The recorded draw when it is in ``seq``; deterministic
        fallback (first element) otherwise — PoissonArrivals.choice()'s
        signature without the rng."""
        options = tuple(seq)
        if not options:
            raise IndexError("choice from an empty sequence")
        if self._pending:
            want = self._pending.pop(0)
            if want in options:
                return want
        return options[0]

    def coverage(self) -> dict:
        """The replay-coverage summary the ``am-tpu journal`` verb
        prints: how much recorded traffic a replay would reproduce."""
        return {
            "events": len(self._events),
            "span_seconds": sum(ev["gap"] for ev in self._events),
            "tenants": list(self.tenants),
            "checks": list(self.checks),
        }


def load_trace(journal_dir: str) -> Tuple[RecordedArrivals, List[dict]]:
    """Journal directory → (schedule, warnings). A torn journal yields
    an EMPTY schedule plus the structured warning (never a partial
    trace — same all-or-nothing discipline as the boot replay)."""
    events, warnings = read_journal(journal_dir)
    arrivals = [ev for ev in events if ev.get("stream") == STREAM_ARRIVAL]
    return RecordedArrivals(arrivals), warnings


async def drive_requests(
    requests,
    *,
    journal=None,
    quota_per_minute: float = 1_000_000.0,
    default_freshness: float = 30.0,
) -> dict:
    """Push ``CheckRequest``s through a real front door on a FakeClock.

    Builds the full submit path — AdmissionController → CoalescingCache
    → trigger — with a synthetic backend that records an ok result
    (fixed :data:`DRIVE_LATENCY_SECONDS`) immediately after each
    submit, so runs resolve, later duplicates ride the cache, and the
    whole drive is a deterministic function of the request sequence.
    When ``journal`` is wired the door records its arrival stream
    through it (the ``am-tpu record`` path)."""
    from activemonitor_tpu.frontdoor.admission import (
        AdmissionController,
        TenantQuota,
    )
    from activemonitor_tpu.frontdoor.service import FrontDoor
    from activemonitor_tpu.obs.history import ResultHistory
    from activemonitor_tpu.utils.clock import FakeClock

    clock = FakeClock()
    history = ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(
            default_quota=TenantQuota(rate_per_minute=quota_per_minute),
            clock=clock,
        ),
        clock=clock,
        default_freshness=default_freshness,
    )
    if journal is not None:
        door.journal = journal
    triggered: List[str] = []
    door.bind(lambda ns, name: triggered.append(f"{ns}/{name}"))

    outcomes: List[str] = []
    tenants: List[str] = []
    checks: List[str] = []
    arrivals: List[float] = []
    tenant_mix: Dict[str, int] = {}
    n = 0
    for req in requests:
        n += 1
        ahead = req.arrival - clock.monotonic()
        if ahead > 0:
            await clock.advance(ahead)
        ticket = door.submit(req.tenant, req.check, req.freshness)
        while triggered:
            key = triggered.pop(0)
            history.record(
                key,
                ok=True,
                latency=DRIVE_LATENCY_SECONDS,
                workflow="replay-drive",
                trace_id=f"replay-{req.rid}",
            )
        await ticket.wait()
        outcomes.append(ticket.outcome)
        tenants.append(req.tenant)
        checks.append(req.check)
        arrivals.append(req.arrival)
        tenant_mix[req.tenant] = tenant_mix.get(req.tenant, 0) + 1
    conservation = door.conservation()
    return {
        "requests": n,
        "outcomes": outcomes,
        "tenants": tenants,
        "checks": checks,
        "arrivals": arrivals,
        "tenant_mix": dict(sorted(tenant_mix.items())),
        "outcome_counts": {
            outcome: outcomes.count(outcome) for outcome in sorted(set(outcomes))
        },
        "conservation": conservation,
        "conservation_ok": conservation["ok"],
        "snapshot": door.snapshot(),
    }
