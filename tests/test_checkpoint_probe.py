"""Checkpoint probe (orbax sharded save/restore) on the CPU mesh."""

import json

from activemonitor_tpu.probes import checkpoint


def test_roundtrip_over_virtual_mesh(tmp_path):
    result = checkpoint.run(size_mb=4.0, directory=str(tmp_path))
    assert result.ok
    assert result.details["devices"] == 8
    assert result.details["bitwise"] is True
    assert result.details["sharding_preserved"] is True
    names = {m.name for m in result.metrics}
    assert names == {
        "checkpoint-save-gbps",
        "checkpoint-restore-gbps",
        "checkpoint-roundtrip-ok",
    }
    ok = next(m for m in result.metrics if m.name == "checkpoint-roundtrip-ok")
    assert ok.value == 1.0


def test_temp_dir_cleaned_up():
    import glob
    import tempfile

    before = set(glob.glob(tempfile.gettempdir() + "/activemonitor-ckpt-*"))
    result = checkpoint.run(size_mb=2.0)
    after = set(glob.glob(tempfile.gettempdir() + "/activemonitor-ckpt-*"))
    assert result.ok
    assert after == before  # throwaway dir removed


def test_rerun_same_directory(tmp_path):
    # a periodic HealthCheck reuses its --directory every run — the
    # second save must overwrite, not crash on the existing path
    first = checkpoint.run(size_mb=2.0, directory=str(tmp_path))
    second = checkpoint.run(size_mb=2.0, directory=str(tmp_path))
    assert first.ok and second.ok


def test_contract_line(tmp_path):
    result = checkpoint.run(size_mb=2.0, directory=str(tmp_path))
    parsed = json.loads(result.contract_line())
    assert len(parsed["metrics"]) == 3
