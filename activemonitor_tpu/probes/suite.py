"""Aggregate probe suite — the whole battery in one payload.

One workflow, one compile cache, one verdict: runs every applicable
probe and merges their metrics into a single contract line. The
natural payload for a single "is this TPU healthy" HealthCheck; probes
inapplicable to the hardware (rated comparisons on unknown chips,
multi-device checks on one chip) degrade the way they do individually.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import List, Optional, Tuple

from activemonitor_tpu.probes.base import PhaseTimings, ProbeResult

log = logging.getLogger("activemonitor.probes")


def enable_persistent_compile_cache(directory: str = "") -> Optional[str]:
    """Point XLA's persistent compilation cache at a stable directory so
    repeated battery runs (the steady state of a periodic HealthCheck)
    skip recompilation — the dominant cost of a cold `probes all` run on
    TPU. Override with $ACTIVEMONITOR_COMPILE_CACHE; returns the
    directory, or None if the cache could not be enabled."""
    import jax

    directory = (
        directory
        or os.environ.get("ACTIVEMONITOR_COMPILE_CACHE")
        or os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "activemonitor-tpu",
            "xla-cache",
        )
    )
    try:
        os.makedirs(directory, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", directory)
        # cache even fast compiles: the battery compiles dozens of small
        # programs and their sum is what the cadence pays
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        return directory
    except Exception as e:
        log.warning("persistent compile cache unavailable (%s)", e)
        return None


def run(
    quick: bool = False,
    skip: Optional[List[str]] = None,
    compile_cache: bool = True,
    roofline: bool = True,
) -> ProbeResult:
    skip = set(skip or [])
    if compile_cache:
        enable_persistent_compile_cache()
    results: List[Tuple[str, ProbeResult]] = []
    # each sub-probe is one phase of the battery payload: the timings
    # block tells the controller where a slow `probes all` run spent its
    # time without re-running anything
    timings = PhaseTimings()

    def add(name: str, fn) -> None:
        if name in skip:
            return
        with timings.phase(name):
            try:
                results.append((name, fn()))
            except Exception as e:  # a crashing probe is a failing probe
                results.append(
                    (name, ProbeResult(ok=False, summary=f"{name} crashed: {e!r}"))
                )

    from activemonitor_tpu.probes import (
        compile_smoke,
        decode,
        devices,
        hbm,
        ici,
        matmul,
        memory,
        ring,
        training_step,
    )

    iters = 3 if quick else 5
    add("devices", lambda: devices.run())
    add("memory", lambda: memory.run(probe_gb=0.5 if quick else 1.0))
    add("compile-smoke", lambda: compile_smoke.run(tiny=quick))
    # quick mode narrows the sweep to the cheap dim; full mode uses the
    # probe's own default sweep (single source of truth) so the battery
    # reports the same max-over-dims signal as `probes matmul`. The
    # probe itself owns the off-TPU downsizing.
    if quick:
        add("matmul", lambda: matmul.run(dims=(4096,), iters=iters, roofline=roofline))
    else:
        add("matmul", lambda: matmul.run(iters=iters, roofline=roofline))
        # the MXU's other throughput mode (v5e+); v4/unknown chips
        # degrade to an informational pass inside the probe. Same full
        # dim sweep as bf16: which dim the compiler tiles best varies,
        # and a single pinned dim could fail a healthy chip
        add("matmul-int8", lambda: matmul.run(iters=iters, dtype="int8", roofline=roofline))
    add("hbm", lambda: hbm.run(size_mb=128 if quick else 256, iters=iters, roofline=roofline))
    add("ici-allreduce", lambda: ici.run(size_mb=16 if quick else 64, iters=iters, roofline=roofline))
    from activemonitor_tpu.probes import collectives as collectives_probe

    # the ici probe already measured all-reduce and the ring hop; the
    # sweep adds only the patterns it hasn't covered
    add(
        "collectives",
        lambda: collectives_probe.run(
            size_mb=16 if quick else 64,
            iters=iters,
            cases=("allgather", "reducescatter", "alltoall"),
            roofline=roofline,
        ),
    )
    if not quick:
        # the message-size autotune sweep (schedule zoo vs XLA builtins
        # across the payload grid) — quick grid even in the full
        # battery: the full 256 MB grid is a dedicated-probe bill, and
        # the battery only needs the decision-table evidence refreshed
        add(
            "collectives-sweep",
            lambda: collectives_probe.sweep(quick=True, iters=iters),
        )
    # quick mode skips the overlap telemetry (the serial-baseline pass
    # and cross-schedule checks are extra compiles — same philosophy as
    # skipping the perf bars); the full battery reports
    # ring-overlap-efficiency and the sustained busbw fraction
    add(
        "ring-attention",
        lambda: ring.run(
            seq_per_device=256 if quick else 1024,
            iters=iters,
            overlap_metrics=not quick,
            roofline=roofline,
        ),
    )
    from activemonitor_tpu.probes import flash

    import jax as _jax

    from activemonitor_tpu.probes.rated import FLASH_FRACTION_BAR, TRAIN_MFU_BAR

    # seq=None: the per-platform default (4096 on TPU, the interpret-
    # mode 512 cap elsewhere — an explicit seq is honored verbatim and
    # would stall a CPU suite run); quick mode pins the short
    # per-platform length the battery always used (1024 on TPU, 512 in
    # interpret mode). The device lookup stays INSIDE the lambda so a
    # backend-init failure is a failing probe, not an aborted battery.
    # The full battery enforces the BASELINE.md single-chip bars — an
    # underperforming chip FAILS, it doesn't just report low gauges;
    # quick mode (tiny shapes, throwaway timings) skips the bars
    def _quick_seq():
        return 1024 if _jax.devices()[0].platform == "tpu" else 512

    add(
        "flash-attention",
        lambda: flash.run(
            seq=_quick_seq() if quick else None,
            iters=iters,
            min_fraction=None if quick else FLASH_FRACTION_BAR,
            roofline=roofline,
        ),
    )
    # full mode runs the SAME shape bench.py's train() calibration
    # measures (batch_per_device=8, seq=128) — the bar and the evidence
    # it is raised from must see the same per-step workload, or a bar
    # calibrated on big steps fails healthy chips on small ones
    add(
        "training-step",
        lambda: training_step.run(
            tiny=quick,
            batch_per_device=4 if quick else 8,
            seq=64 if quick else 128,
            mfu_threshold=None if quick else TRAIN_MFU_BAR,
            roofline=roofline,
        ),
    )
    add(
        "decode",
        lambda: decode.run(
            tiny=quick, batch=4, prompt_len=8, iters=iters, roofline=roofline
        ),
    )
    from activemonitor_tpu.probes import serving as serving_probe

    # the continuous-batching serving loop rides the battery next to
    # the static decode probe (its compiles share the persistent
    # cache); quick mode shrinks the soak, not the gates — logits
    # agreement and token conservation are checked either way
    add(
        "serving",
        lambda: serving_probe.run(
            tiny=quick,
            n_requests=6 if quick else 12,
            max_batch=4,
            roofline=roofline,
        ),
    )
    # the disaggregated pools ride next to the colocated soak: same
    # scripted cost model both sides, so the TTFT comparison is the
    # topology and the ledgers (pool boundary, prefix, speculation)
    # gate either way
    add(
        "serving-disagg",
        lambda: serving_probe.run_disagg(
            tiny=quick,
            n_requests=8 if quick else 12,
            roofline=roofline,
        ),
    )
    from activemonitor_tpu.probes import straggler, transfer

    add(
        "straggler",
        lambda: straggler.run(dim=1024 if quick else 0, iters=iters),
    )
    add("transfer", lambda: transfer.run(size_mb=16 if quick else 64, iters=iters))
    from activemonitor_tpu.probes import checkpoint

    add("checkpoint", lambda: checkpoint.run(size_mb=16 if quick else 64))
    from activemonitor_tpu.probes import dcn

    # informational pass on single-process runs; real coverage on
    # multi-host slices where jax.distributed is initialized
    add("dcn-allreduce", lambda: dcn.run(size_mb=4 if quick else 16, iters=iters))

    metrics = []
    failed = []
    merged_timings: dict = dict(timings)
    merged_roofline: dict = {}
    roofline_skipped: dict = {}
    for name, result in results:
        metrics.extend(result.metrics)
        # a sub-probe attributing its own phases nests under its name
        # ("training-step.compile"), beside the battery's per-probe wall
        # time
        for phase, seconds in result.timings.items():
            merged_timings[f"{name}.{phase}"] = seconds
        # roofline verdicts merge under their own metric prefixes (the
        # prefixes are battery-unique by construction); STRUCTURED
        # skips are collected too — a quick-mode/interpret run that
        # could not run cost analysis must say so in the details, not
        # silently omit the roofline fields
        merged_roofline.update(result.roofline)
        for prefix, entry in (result.details.get("roofline") or {}).items():
            if isinstance(entry, dict) and "skipped" in entry:
                roofline_skipped[prefix] = entry["skipped"]
        status = "OK " if result.ok else "FAIL"
        print(f"  [{status}] {name}: {result.summary}", file=sys.stderr)
        if not result.ok:
            failed.append(name)
    ok = not failed
    summary = (
        f"all {len(results)} probes passed"
        if ok
        else f"{len(failed)}/{len(results)} probes failed: {', '.join(failed)}"
    )
    details = {"probes_run": len(results), "failed": failed}
    if roofline_skipped:
        details["roofline_skipped"] = roofline_skipped
    return ProbeResult(
        ok=ok,
        summary=summary,
        metrics=metrics,
        details=details,
        timings=merged_timings,
        roofline=merged_roofline,
    )
