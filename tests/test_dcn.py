"""Multi-host (DCN) probe tests — real multi-process collectives over
localhost Gloo, the CI stand-in for a multi-host TPU slice."""

import json
import os
import socket
import subprocess
import sys

import pytest

from activemonitor_tpu.probes import dcn
from activemonitor_tpu.utils.compat import SUPPORTS_CPU_MULTIPROCESS

# two-process tests need cross-process collectives on the CPU
# backend, which the legacy jaxlib runtime does not implement
needs_cpu_multiprocess = pytest.mark.skipif(
    not SUPPORTS_CPU_MULTIPROCESS,
    reason="legacy jaxlib: no multiprocess computations on CPU",
)


def test_single_process_degrades_gracefully():
    result = dcn.run()
    assert result.ok
    assert result.details["processes"] == 1
    assert result.metrics[0].name == "dcn-hosts"
    # the skip names the two-tier topology it lacked (the run_per_axis
    # skip contract applied to the dcn probe)
    assert result.details["skipped"] is True
    assert result.details["mesh"]["dcn"] == 1
    assert result.details["mesh"]["ici"] >= 1


def _run_two_workers(make_argv, timeout: float, local_devices: int = 1):
    """Spawn two worker processes against a fresh localhost coordinator
    and reap them. ``make_argv(rank, port)`` returns each worker's
    argv. ``local_devices`` > 1 forces a virtual per-process device
    count so the (dcn, ici) mesh has a real inner tier. Survivors are
    ALWAYS killed — a worker wedged in a collective (the exact failure
    these tests guard) must not outlive the test and leak into the
    rest of the CI run."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local device per process keeps it fast
    if local_devices > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={local_devices}"
        )
    # pick a free port so concurrent/parallel test runs don't collide
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    workers = [
        subprocess.Popen(
            make_argv(rank, port),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for rank in range(2)
    ]
    outputs = []
    try:
        for proc in workers:
            out, _ = proc.communicate(timeout=timeout)
            outputs.append(out.decode())
            assert proc.returncode == 0, out.decode()[-1500:]
    finally:
        for proc in workers:
            if proc.poll() is None:
                proc.kill()
    return outputs


@needs_cpu_multiprocess
def test_two_process_dcn_allreduce():
    """Spawn two real worker processes; both run the dcn-allreduce probe
    CLI against a localhost coordinator and must agree + succeed."""

    def argv(rank, port):
        return [
            sys.executable,
            "-c",
            # config API beats the env-registered tunnel plugin
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "from activemonitor_tpu.probes.cli import main; import sys;"
            "sys.exit(main(["
            f"'--coordinator', '127.0.0.1:{port}',"
            f"'--num-processes', '2', '--process-id', '{rank}',"
            "'dcn-allreduce', '--size-mb', '1', '--iters', '2']))",
        ]

    for out in _run_two_workers(argv, timeout=150):
        contract = json.loads(out.strip().splitlines()[-1])
        by_name = {m["name"]: m["value"] for m in contract["metrics"]}
        assert by_name["dcn-hosts"] == 2
        assert by_name["dcn-allreduce-correct"] == 1.0
        assert by_name["dcn-allreduce-busbw-gbps"] > 0
        # the per-tier spelling + the hierarchical-composition gate
        # ride the same contract line
        assert by_name["dcn-xslice-busbw-gbps"] > 0
        assert by_name["dcn-hier-allreduce-correct"] == 1.0


@needs_cpu_multiprocess
def test_two_process_hier_composition_over_real_tiers():
    """The ISSUE-13 acceptance composition on REAL two-process tiers:
    each worker carries 2 virtual local devices, the two processes
    form one (dcn=2, ici=2) mesh, and the hierarchical all-reduce —
    ICI reduce-scatter inside each process, DCN exchange between
    them, ICI all-gather back — must match the joint psum bitwise-
    deterministically on both workers, with the latency composition
    agreeing too."""

    def argv(rank, port):
        driver = (
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "import jax.numpy as jnp;"
            f"jax.distributed.initialize('127.0.0.1:{port}', 2, {rank});"
            "from functools import partial;"
            "from jax.sharding import PartitionSpec as P;"
            "from activemonitor_tpu.parallel.mesh import make_multihost_mesh;"
            "from activemonitor_tpu.parallel.partition import shard_map;"
            "from activemonitor_tpu.parallel.schedules import ("
            "    hier_all_reduce, hier_all_reduce_latency);"
            "mesh = make_multihost_mesh();"
            "assert dict(mesh.shape) == {'dcn': 2, 'ici': 2}, mesh.shape;"
            "x = (jnp.arange(4 * 6 * 3, dtype=jnp.float32)"
            "     .reshape(4 * 6, 3) % 7);"
            "\n"
            "@partial(shard_map, mesh=mesh, in_specs=P(('dcn', 'ici')),\n"
            "         out_specs=P(None), check_vma=False)\n"
            "def diffs(v):\n"
            "    want = jax.lax.psum(v, ('dcn', 'ici'))\n"
            "    bw = hier_all_reduce(v, 'dcn', 'ici', 2, 2)\n"
            "    lat = hier_all_reduce_latency(v, 'dcn', 'ici', 2, 2)\n"
            "    return jnp.stack([\n"
            "        jnp.max(jnp.abs(bw - want)),\n"
            "        jnp.max(jnp.abs(lat - want)),\n"
            "    ])[None]\n"
            "out = jax.jit(diffs)(x)\n"
            "print('DIFFS', float(out[0, 0]), float(out[0, 1]))\n"
        )
        return [sys.executable, "-c", driver]

    outputs = _run_two_workers(argv, timeout=240, local_devices=2)
    for out in outputs:
        (line,) = [l for l in out.splitlines() if l.startswith("DIFFS ")]
        assert line == "DIFFS 0.0 0.0", out[-1200:]


@needs_cpu_multiprocess
def test_two_process_train_step_over_dcn():
    """The flagship train step spans HOSTS: two real processes form one
    dp=2 mesh over the distributed runtime (gradient psums ride DCN),
    each contributes its own batch shard, and both must agree on the
    (replicated) loss — the multi-host story the reference's NCCL/MPI
    backend plays, as an executable test."""

    def argv(rank, port):
        driver = (
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "import jax.numpy as jnp;"
            f"jax.distributed.initialize('127.0.0.1:{port}', 2, {rank});"
            "from activemonitor_tpu.parallel.mesh import make_2d_mesh;"
            "from activemonitor_tpu.probes import training_step;"
            "mesh = make_2d_mesh(shape=(2, 1));"  # pure dp across the hosts
            "r = training_step.run(tiny=True, batch_per_device=2, seq=16,"
            "                      steps=1, mesh=mesh);"
            "assert r.ok, r.summary;"
            "print('LOSS', round(r.details['loss_last'], 6));"
            "print('MESH', r.details['mesh'])"
        )
        return [sys.executable, "-c", driver]

    outputs = _run_two_workers(argv, timeout=300)
    losses = []
    for out in outputs:
        (loss_line,) = [l for l in out.splitlines() if l.startswith("LOSS ")]
        losses.append(loss_line)
        assert "{'data': 2, 'model': 1}" in out
    # the loss is replicated over the mesh: both hosts see the same value
    assert losses[0] == losses[1], outputs


@needs_cpu_multiprocess
def test_two_process_checkpoint_resume_over_dcn(tmp_path):
    """Multi-host durability: both processes of a dp=2 mesh save ONE
    sharded checkpoint to shared storage (orbax's multi-process
    barriers), restore it, and continue to the same replicated loss —
    the preemption-recovery flow of a real multi-host slice."""
    shared = str(tmp_path / "ckpt")

    def argv(rank, port):
        driver = (
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            f"jax.distributed.initialize('127.0.0.1:{port}', 2, {rank});"
            "from activemonitor_tpu.models.probe_model import tiny_config;"
            "from activemonitor_tpu.parallel.mesh import make_2d_mesh;"
            "from activemonitor_tpu.parallel.distributed import distribute;"
            "from activemonitor_tpu.probes.training_step import ("
            "    build_sharded_train_step, save_train_state,"
            "    restore_train_state, train_state_templates);"
            "cfg = tiny_config();"
            "mesh = make_2d_mesh(shape=(2, 1));"
            "step, params, opt, data_sh = build_sharded_train_step(cfg, mesh);"
            "tokens = distribute(jax.random.randint("
            "    jax.random.key(3), (4, 17), 0, cfg.vocab_size), data_sh);"
            "params, opt, l1 = step(params, opt, tokens);"
            f"save_train_state({shared!r}, params, opt, step=1);"
            "p_like, o_like = train_state_templates(cfg, mesh);"
            f"r_params, r_opt, at = restore_train_state({shared!r}, p_like, o_like);"
            "assert at == 1;"
            "_, _, l2 = step(r_params, r_opt, tokens);"
            "print('LOSSES', round(float(l1), 6), round(float(l2), 6))"
        )
        return [sys.executable, "-c", driver]

    outputs = _run_two_workers(argv, timeout=240)
    lines = []
    for out in outputs:
        (line,) = [l for l in out.splitlines() if l.startswith("LOSSES ")]
        lines.append(line)
    # both the pre-save loss and the post-restore continuation agree
    # across hosts (replicated loss, one shared checkpoint)
    assert lines[0] == lines[1], outputs


@needs_cpu_multiprocess
def test_survivor_fails_fast_and_elastic_resume_after_peer_death(tmp_path):
    """The failure half of the multi-host story: one process of a dp=2
    mesh dies mid-training. The survivor must ERROR OUT of its next
    collective (a hang here would wedge a real slice until the job
    scheduler's own timeout), and a fresh single-process run must
    restore the last durable checkpoint onto a 1-device mesh and keep
    training — preemption recovery with a shrunken mesh, end to end."""
    import time

    shared = str(tmp_path / "ckpt")

    def argv(rank, port):
        saved_flag = str(tmp_path / f"saved-{rank}")
        driver = (
            "import jax, pathlib, time;"
            "jax.config.update('jax_platforms', 'cpu');"
            f"jax.distributed.initialize('127.0.0.1:{port}', 2, {rank});"
            "from activemonitor_tpu.models.probe_model import tiny_config;"
            "from activemonitor_tpu.parallel.mesh import make_2d_mesh;"
            "from activemonitor_tpu.parallel.distributed import distribute;"
            "from activemonitor_tpu.probes.training_step import ("
            "    build_sharded_train_step, save_train_state);"
            "cfg = tiny_config();"
            "mesh = make_2d_mesh(shape=(2, 1));"
            "step, params, opt, data_sh = build_sharded_train_step(cfg, mesh);"
            "tokens = distribute(jax.random.randint("
            "    jax.random.key(3), (4, 17), 0, cfg.vocab_size), data_sh);"
            "params, opt, loss = step(params, opt, tokens);"
            f"save_train_state({shared!r}, params, opt, step=1);"
            f"pathlib.Path({saved_flag!r}).write_text('ok');"
            "print('SAVED', flush=True);"
            # keep training: every step's gradient psum crosses the
            # process boundary, so the peer's death must surface here
            "\nfor i in range(10000):\n"
            "    params, opt, loss = step(params, opt, tokens)\n"
            "    jax.block_until_ready(loss)\n"
            "    time.sleep(0.05)\n"
        )
        return [sys.executable, "-c", driver]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    workers = [
        subprocess.Popen(
            argv(rank, port),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=repo,
        )
        for rank in range(2)
    ]
    try:
        # wait until BOTH ranks have committed the checkpoint
        deadline = time.monotonic() + 180
        flags = [tmp_path / "saved-0", tmp_path / "saved-1"]
        while not all(f.exists() for f in flags):
            for proc in workers:
                assert proc.poll() is None, (
                    "worker died before checkpointing: "
                    + proc.communicate()[0].decode()[-1500:]
                )
            assert time.monotonic() < deadline, "checkpoint never committed"
            time.sleep(0.2)

        workers[1].kill()  # the peer vanishes mid-training

        # the survivor must exit NONZERO on its own — before the
        # timeout, without being killed. A hang is the failure mode.
        try:
            out, _ = workers[0].communicate(timeout=150)
        except subprocess.TimeoutExpired:
            raise AssertionError(
                "survivor hung in a collective after peer death"
            )
        assert workers[0].returncode != 0, out.decode()[-800:]
        assert b"SAVED" in out  # it got through the durable save first
    finally:
        for proc in workers:
            if proc.poll() is None:
                proc.kill()

    # elastic resume: a FRESH 1-process run restores the 2-process
    # checkpoint onto a 1-device mesh and trains on
    resume = (
        "import jax;"
        "jax.config.update('jax_platforms', 'cpu');"
        "from activemonitor_tpu.models.probe_model import tiny_config;"
        "from activemonitor_tpu.parallel.mesh import make_2d_mesh;"
        "from activemonitor_tpu.parallel.distributed import distribute;"
        "from activemonitor_tpu.probes.training_step import ("
        "    build_sharded_train_step, restore_train_state,"
        "    train_state_templates);"
        "cfg = tiny_config();"
        "mesh = make_2d_mesh(shape=(1, 1));"
        "step, _, _, data_sh = build_sharded_train_step(cfg, mesh);"
        "p_like, o_like = train_state_templates(cfg, mesh);"
        f"params, opt, at = restore_train_state({shared!r}, p_like, o_like);"
        "assert at == 1, at;"
        "tokens = distribute(jax.random.randint("
        "    jax.random.key(3), (4, 17), 0, cfg.vocab_size), data_sh);"
        "params, opt, loss = step(params, opt, tokens);"
        "import math; assert math.isfinite(float(loss));"
        "print('RESUMED', at, float(loss))"
    )
    done = subprocess.run(
        [sys.executable, "-c", resume],
        env=env,
        capture_output=True,
        cwd=repo,
        timeout=240,
    )
    assert done.returncode == 0, done.stdout.decode()[-1500:] + done.stderr.decode()[-1500:]
    assert b"RESUMED 1" in done.stdout
