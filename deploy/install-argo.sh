#!/usr/bin/env bash
# One-shot Argo Workflows install for active-monitor-tpu.
# Reference equivalent: deploy/deploy-argo.yaml (which vendors the full
# Argo distribution); here the release is pinned and pulled from
# upstream, then scoped to this framework via the instance-id contract.
#
# NAMESPACE defaults to "argo" because the upstream install.yaml's
# ClusterRoleBindings hardcode subjects in the "argo" namespace —
# installing it anywhere else leaves the workflow-controller SA unbound
# (Forbidden on every watch). The controller is a cluster install: it
# processes labeled workflows in EVERY namespace, including "health"
# where active-monitor-tpu submits probes.
set -euo pipefail

ARGO_VERSION="${ARGO_VERSION:-v3.5.8}"
NAMESPACE="${NAMESPACE:-argo}"
HERE="$(cd "$(dirname "$0")" && pwd)"

kubectl create namespace "${NAMESPACE}" --dry-run=client -o yaml | kubectl apply -f -

# pinned upstream distribution (CRDs + workflow-controller + server)
kubectl apply -n "${NAMESPACE}" -f \
  "https://github.com/argoproj/argo-workflows/releases/download/${ARGO_VERSION}/install.yaml"

# instance-id contract: only workflows labeled
# workflows.argoproj.io/controller-instanceid=activemonitor-workflows
# are processed by this controller (active-monitor-tpu labels every
# submission; see activemonitor_tpu/controller/workflow_spec.py:34-35).
# The ConfigMap is namespace-less and applied with -n so it always lands
# next to the workflow-controller that reads it.
kubectl apply -n "${NAMESPACE}" -f "${HERE}/install-argo.yaml"
kubectl -n "${NAMESPACE}" rollout restart deployment workflow-controller

kubectl -n "${NAMESPACE}" rollout status deployment workflow-controller --timeout=120s
echo "Argo ${ARGO_VERSION} installed in namespace ${NAMESPACE} (instance-id: activemonitor-workflows)"
