"""Composable probe DAGs — compile-smoke → ICI sweep → training-step.

The FlowMesh framing (PAPERS.md: composable LLM workflows) applied to
probes: a tenant's question is rarely one probe — "is my slice ready
for training?" is a compile smoke, then an ICI sweep, then a
training-step probe, where a failed upstream makes the downstream
meaningless. A :class:`ProbeDag` declares that shape; the front door
executes it stage by stage through the SAME submit path every one-shot
request rides, which buys two things for free:

- **reuse instead of re-probing**: every step is a coalescing-cache
  submission, so a step whose check already has a fresh-enough result
  (because another tenant's DAG — or the check's own schedule — just
  ran it) serves from the ring, and N tenants submitting the same DAG
  inside one freshness window share ONE run per step.
- **unchanged backend semantics**: a step that does run is compiled
  into the existing Manager enqueue path, so sharding, tracing,
  attribution, and SLO accounting all apply to DAG steps exactly as
  they do to watch-path runs.

Syntax (docs/operations.md "Probe DAGs"): stages separated by ``->``,
siblings within a stage by ``,`` — every step of a stage depends on
every step of the previous stage::

    health/compile-smoke -> health/ici-sweep -> health/training-step
    health/compile-smoke -> health/ici-sweep, health/hbm -> health/train

No clock, no I/O — pure declaration + validation (wall-clock lint ban
applies to this package; here there is simply no time at all).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class DagStep:
    """One step: a check identity plus the step names it waits on."""

    name: str  # unique step label within the DAG
    check: str  # "namespace/name" — the check identity submitted
    after: Tuple[str, ...] = ()  # upstream step names (all must finish)
    freshness: Optional[float] = None  # per-step window; None = DAG default


@dataclass(frozen=True)
class ProbeDag:
    """A validated DAG: unique step names, known dependencies, acyclic.

    ``stages()`` is the execution plan — Kahn levels, declaration-order
    stable, so the same DAG always executes in the same order (the
    determinism the acceptance tests pin).
    """

    name: str
    steps: Tuple[DagStep, ...]
    _stages: Tuple[Tuple[DagStep, ...], ...] = field(
        default=(), compare=False, repr=False
    )

    def __post_init__(self):
        names = [s.name for s in self.steps]
        if not names:
            raise ValueError(f"dag {self.name!r} has no steps")
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"dag {self.name!r} repeats step name(s) {sorted(dupes)}; "
                "a repeated check rides the coalescing cache — list it once"
            )
        known = set(names)
        for step in self.steps:
            unknown = [dep for dep in step.after if dep not in known]
            if unknown:
                raise ValueError(
                    f"dag {self.name!r} step {step.name!r} depends on "
                    f"unknown step(s) {unknown}"
                )
            if step.name in step.after:
                raise ValueError(
                    f"dag {self.name!r} step {step.name!r} depends on itself"
                )
        # Kahn layering, declaration-order stable; leftovers = a cycle
        remaining: Dict[str, DagStep] = {s.name: s for s in self.steps}
        done: set = set()
        stages: List[Tuple[DagStep, ...]] = []
        while remaining:
            ready = tuple(
                step
                for step in self.steps
                if step.name in remaining
                and all(dep in done for dep in step.after)
            )
            if not ready:
                raise ValueError(
                    f"dag {self.name!r} has a dependency cycle among "
                    f"{sorted(remaining)}"
                )
            for step in ready:
                del remaining[step.name]
                done.add(step.name)
            stages.append(ready)
        object.__setattr__(self, "_stages", tuple(stages))

    def stages(self) -> Tuple[Tuple[DagStep, ...], ...]:
        """Execution levels: every step of level i waits for all of its
        dependencies, which live in earlier levels by construction."""
        return self._stages


def parse_dag(
    name: str, text: str, freshness: Optional[float] = None
) -> ProbeDag:
    """The arrow syntax: ``a -> b, c -> d`` builds three stages where
    each stage's steps depend on ALL of the previous stage's (the
    common pipeline shape; richer shapes construct :class:`DagStep`
    directly). Tokens are check identities (``namespace/name``) and
    double as step names, so a malformed spec names its own token."""
    stages = [
        [token.strip() for token in stage.split(",") if token.strip()]
        for stage in text.split("->")
    ]
    stages = [stage for stage in stages if stage]
    if not stages:
        raise ValueError(f"dag {name!r}: empty spec {text!r}")
    for stage in stages:
        for token in stage:
            # validated at PARSE time: a malformed later-stage token
            # must reject the whole request before any earlier stage
            # pays quota or launches a probe run
            if "/" not in token:
                raise ValueError(
                    f"dag {name!r}: step {token!r} is not a "
                    "namespace/name check identity"
                )
    steps: List[DagStep] = []
    previous: Sequence[str] = ()
    for stage in stages:
        for token in stage:
            steps.append(
                DagStep(
                    name=token,
                    check=token,
                    after=tuple(previous),
                    freshness=freshness,
                )
            )
        previous = tuple(stage)
    return ProbeDag(name=name, steps=tuple(steps))
