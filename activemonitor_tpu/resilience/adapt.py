"""Closed-loop goodput control: the layer that ACTS on what the fleet
measures (ROADMAP item 1 — measure → attribute → act; the ML
Productivity Goodput direction from PAPERS.md, with Maple-style policy
portability: the policy consumes only the fleet's own burn-rate and
attribution signals, so it behaves identically on any cluster).

Four levers, each reusing an existing mechanism rather than growing a
parallel one:

- **cadence** — while a check's error budget burns, its probe interval
  tightens through the ONE ``damp_factor`` composition in
  resilience/health.py (``set_burn_damp``); calm releases it. Hysteresis
  (``ENGAGE_AFTER`` burning observations to engage, ``RELEASE_AFTER``
  calm ones to release) means a single burn spike never flaps the
  cadence.
- **remedy** — the failing run's attribution bucket selects a
  bucket-targeted remedy workflow (``spec.remedyworkflow.byBucket``,
  api/types.py); the reconciler reports each targeted selection here so
  the episode is visible in /statusz and ``am-tpu why``.
- **placement** — cohort straggler scores (analysis/fleet.py
  ``CohortIndex``) steer probe traffic away from contended slices: a
  member beyond ``CONTENTION_SIGMAS`` is parked at ``CONTENTION_DAMP``×
  cadence through the same damp rule.
- **frontdoor** — under a confirmed ``control_plane`` burn the
  coalescing freshness ceiling widens (an explicit degraded-mode
  ceiling, frontdoor/coalesce.py) and low-priority tenants are shed by
  quota re-pricing, so cached answers absorb demand while the control
  plane heals — before the breaker has to trip.

Every engage/release/target decision is evented into a bounded decision
log (served on /statusz and in ``am-tpu why``), exported through the
pinned ``healthcheck_adaptive_*`` metric families, and recorded as a
flight-recorder bundle — an operator can always answer "why is this
check probing at 2× cadence right now".

No wall clock anywhere (hack/lint.py bans it for all of resilience/):
time flows in through the injected clock only, so every episode is
exactly reproducible under FakeClock.
"""

from __future__ import annotations

import collections
import logging
from typing import Deque, Dict, List, Optional

from activemonitor_tpu.resilience.health import CheckStateTracker

log = logging.getLogger("activemonitor.adapt")

# A burn rate above 1.0 means the error budget is being spent faster
# than the SLO window replenishes it — the same threshold the profile
# hook uses (obs/slo.py), so the two anomaly responders always agree on
# what "burning" means.
BURN_THRESHOLD = 1.0

# Hysteresis: engage after this many CONSECUTIVE burning observations,
# release after this many consecutive calm ones. Asymmetric on purpose —
# quick to tighten (a real burn costs budget every minute), slower to
# relax (releasing on the first good run would flap the cadence on a
# 50%-failing check).
ENGAGE_AFTER = 2
RELEASE_AFTER = 3

# Cadence tightening factor while burning (0.5 = probe twice as often).
# Composed through resilience/health.py damp_factor, whose
# MIN_BURN_DAMP floor caps total tightening at 4×.
TIGHTEN_FACTOR = 0.5

# Placement: a cohort member whose worst straggler score reaches this
# many sigmas is contended; its cadence is damped by CONTENTION_DAMP
# (strongest-wins with flap/analysis damping, capped at
# MAX_COMPOSED_DAMP).
CONTENTION_SIGMAS = 3.0
CONTENTION_DAMP = 2.0

# Front-door degraded mode: the coalescing freshness ceiling stretches
# to this multiple of the operator default, and low-priority tenant
# quotas are re-priced to this fraction of their configured rate.
DEGRADED_FRESHNESS_FACTOR = 4.0
SHED_FACTOR = 0.25

LEVER_CADENCE = "cadence"
LEVER_REMEDY = "remedy"
LEVER_PLACEMENT = "placement"
LEVER_FRONTDOOR = "frontdoor"
LEVERS = (LEVER_CADENCE, LEVER_REMEDY, LEVER_PLACEMENT, LEVER_FRONTDOOR)

ACTION_ENGAGE = "engage"
ACTION_RELEASE = "release"
ACTION_TARGET = "target"

# bounded decision log: at one decision a minute this is an hour of
# history — enough to read an episode end-to-end from /statusz alone
DECISION_LOG_CAPACITY = 64


class AdaptiveController:
    """Owns the four levers. The reconciler constructs it beside the
    flight recorder; the Manager wires ``frontdoor`` when the front
    door is configured and drives ``sweep()`` from the resilience loop.
    ``observe`` rides the fleet's record path (obs/slo.py) — the same
    place the burn rate is already computed — so acting costs no extra
    evaluation."""

    def __init__(self, clock, metrics, checks: CheckStateTracker):
        self.clock = clock
        self.metrics = metrics
        self.checks = checks
        # wired after construction (same pattern as FlightRecorder):
        self.flightrec = None  # obs/flightrec.py — engage/release bundles
        self.frontdoor = None  # frontdoor/service.py — lever 4
        self.cohorts = None  # analysis/fleet.py CohortIndex — lever 3
        # hysteresis streaks per check key
        self._hot: Dict[str, int] = {}
        self._calm: Dict[str, int] = {}
        # engaged cadence episodes: key -> {factor, cause, since, burn}
        self._engaged: Dict[str, dict] = {}
        # contended placements: key -> cohort name
        self._contended: Dict[str, str] = {}
        # last bucket-targeted remedy per key
        self._remedy_selected: Dict[str, str] = {}
        self._frontdoor_engaged = False
        self._frontdoor_since = ""
        self._log: Deque[dict] = collections.deque(
            maxlen=DECISION_LOG_CAPACITY
        )

    # -- shared plumbing ------------------------------------------------
    def _now_iso(self) -> str:
        return self.clock.now().isoformat()

    def _decide(
        self, lever: str, action: str, key: str, cause: str, detail: str
    ) -> None:
        """One adaptation decision: decision log + transition counter +
        flight-recorder bundle. Never raises — a broken observability
        sink must not stop the control loop."""
        entry = {
            "ts": self._now_iso(),
            "lever": lever,
            "action": action,
            "key": key,
            "cause": cause,
            "detail": detail,
        }
        self._log.append(entry)
        try:
            self.metrics.record_adaptive_transition(lever, action)
        except Exception:
            log.exception("adaptive transition metric failed")
        if self.flightrec is not None:
            try:
                from activemonitor_tpu.obs.flightrec import KIND_ADAPTIVE

                self.flightrec.record(
                    KIND_ADAPTIVE,
                    key=key,
                    lever=lever,
                    action=action,
                    cause=cause,
                    detail=detail,
                )
            except Exception:
                log.exception("adaptive flight bundle failed")

    def _refresh_lever_gauges(self) -> None:
        active = {
            LEVER_CADENCE: bool(self._engaged),
            LEVER_REMEDY: bool(self._remedy_selected),
            LEVER_PLACEMENT: bool(self._contended),
            LEVER_FRONTDOOR: self._frontdoor_engaged,
        }
        try:
            for lever, on in active.items():
                self.metrics.set_adaptive_lever(lever, on)
        except Exception:
            log.exception("adaptive lever gauges failed")

    @staticmethod
    def _split_key(key: str):
        namespace, _, name = key.partition("/")
        return namespace, name

    # -- lever 1: burn-rate cadence -------------------------------------
    def observe(self, hc, *, burn_rate, bucket: str) -> None:
        """One recorded run for an SLO'd check, with its freshly
        evaluated burn rate and attribution bucket. Called by
        FleetStatus._record — the single place both signals exist."""
        if burn_rate is None:
            return
        key = hc.key
        burning = float(burn_rate) > BURN_THRESHOLD
        episode = self._engaged.get(key)
        if burning:
            self._calm.pop(key, None)
            self._hot[key] = self._hot.get(key, 0) + 1
            if episode is not None:
                episode["burn"] = round(float(burn_rate), 3)
                # the first burning runs may classify as unknown; adopt
                # the first real attribution so the frontdoor lever (and
                # the operator) see the true cause
                if bucket and episode["cause"] in ("", "unknown"):
                    episode["cause"] = bucket
            elif self._hot[key] >= ENGAGE_AFTER:
                self._engage_cadence(hc, burn_rate, bucket)
        else:
            self._hot.pop(key, None)
            self._calm[key] = self._calm.get(key, 0) + 1
            if episode is not None and self._calm[key] >= RELEASE_AFTER:
                self._release_cadence(hc)
        self._sync_frontdoor()
        self._refresh_lever_gauges()

    def _engage_cadence(self, hc, burn_rate, bucket: str) -> None:
        key = hc.key
        cause = bucket or "unknown"
        self.checks.set_burn_damp(key, TIGHTEN_FACTOR)
        self._engaged[key] = {
            "factor": TIGHTEN_FACTOR,
            "cause": cause,
            "since": self._now_iso(),
            "burn": round(float(burn_rate), 3),
        }
        try:
            self.metrics.set_adaptive_cadence(
                hc.metadata.name, hc.metadata.namespace, TIGHTEN_FACTOR
            )
        except Exception:
            log.exception("adaptive cadence gauge failed")
        self._decide(
            LEVER_CADENCE,
            ACTION_ENGAGE,
            key,
            cause,
            f"burn {float(burn_rate):.3g} > {BURN_THRESHOLD:g} for "
            f"{ENGAGE_AFTER} runs; interval x{TIGHTEN_FACTOR:g}",
        )

    def _release_cadence(self, hc) -> None:
        key = hc.key
        episode = self._engaged.pop(key, {})
        self.checks.set_burn_damp(key, 1.0)
        try:
            self.metrics.clear_adaptive_cadence(
                hc.metadata.name, hc.metadata.namespace
            )
        except Exception:
            log.exception("adaptive cadence gauge failed")
        self._decide(
            LEVER_CADENCE,
            ACTION_RELEASE,
            key,
            str(episode.get("cause", "")),
            f"burn <= {BURN_THRESHOLD:g} for {RELEASE_AFTER} runs; "
            "interval restored",
        )

    # -- lever 2: bucket-targeted remedies ------------------------------
    def note_remedy_selected(self, key: str, bucket: str) -> None:
        """The reconciler picked a ``byBucket`` remedy over the plain
        fallback for this check's latest failure."""
        self._remedy_selected[key] = bucket
        self._decide(
            LEVER_REMEDY,
            ACTION_TARGET,
            key,
            bucket,
            f"byBucket[{bucket}] remedy selected over fallback",
        )
        self._refresh_lever_gauges()

    # -- lever 3: interference-aware placement --------------------------
    def _sweep_placement(self) -> None:
        if self.cohorts is None:
            return
        contended_now: Dict[str, str] = {}
        for cohort in self.cohorts.cohorts():
            for key in self.cohorts.members(cohort):
                score = self.cohorts.worst_score(cohort, key)
                if score is not None and abs(score) >= CONTENTION_SIGMAS:
                    contended_now[key] = cohort
        for key, cohort in contended_now.items():
            if key not in self._contended:
                self.checks.set_contention_damp(key, CONTENTION_DAMP)
                self._decide(
                    LEVER_PLACEMENT,
                    ACTION_ENGAGE,
                    key,
                    "contention",
                    f"cohort {cohort} straggler >= "
                    f"{CONTENTION_SIGMAS:g} sigmas; interval "
                    f"x{CONTENTION_DAMP:g}",
                )
        for key, cohort in list(self._contended.items()):
            if key not in contended_now:
                self.checks.set_contention_damp(key, 1.0)
                self._decide(
                    LEVER_PLACEMENT,
                    ACTION_RELEASE,
                    key,
                    "contention",
                    f"cohort {cohort} back within "
                    f"{CONTENTION_SIGMAS:g} sigmas; interval restored",
                )
        self._contended = contended_now

    # -- lever 4: front-door degraded mode ------------------------------
    def _sync_frontdoor(self) -> None:
        """Derive the front-door lever from the engaged cadence
        episodes: any episode whose cause is ``control_plane`` engages
        it; none releases it. Derived (not edge-triggered) so a forget
        of the last control-plane episode releases on the next sweep."""
        if self.frontdoor is None:
            return
        want = any(
            ep.get("cause") == "control_plane"
            for ep in self._engaged.values()
        )
        if want and not self._frontdoor_engaged:
            self._frontdoor_engaged = True
            self._frontdoor_since = self._now_iso()
            try:
                self.frontdoor.widen_freshness(DEGRADED_FRESHNESS_FACTOR)
                self.frontdoor.admission.shed_low_priority(SHED_FACTOR)
            except Exception:
                log.exception("frontdoor degraded-mode engage failed")
            self._decide(
                LEVER_FRONTDOOR,
                ACTION_ENGAGE,
                "",
                "control_plane",
                f"freshness ceiling x{DEGRADED_FRESHNESS_FACTOR:g}; "
                f"low-priority quotas x{SHED_FACTOR:g}",
            )
        elif not want and self._frontdoor_engaged:
            self._frontdoor_engaged = False
            self._frontdoor_since = ""
            try:
                self.frontdoor.restore_freshness()
                self.frontdoor.admission.restore_quotas()
            except Exception:
                log.exception("frontdoor degraded-mode release failed")
            self._decide(
                LEVER_FRONTDOOR,
                ACTION_RELEASE,
                "",
                "control_plane",
                "freshness ceiling and tenant quotas restored",
            )
        try:
            ceiling = 0.0
            if self.frontdoor is not None:
                ceiling = float(self.frontdoor.cache.freshness_ceiling())
            self.metrics.set_adaptive_freshness_ceiling(ceiling)
        except Exception:
            log.exception("adaptive freshness ceiling gauge failed")

    # -- periodic sweep (Manager resilience loop) -----------------------
    def sweep(self) -> None:
        """Refresh the non-run-driven levers: placement contention from
        the cohort index, the derived front-door state, and the lever
        gauges. Never raises — it shares a loop with the breaker."""
        try:
            self._sweep_placement()
            self._sync_frontdoor()
            self._refresh_lever_gauges()
        except Exception:
            log.exception("adaptive sweep failed")

    # -- lifecycle ------------------------------------------------------
    def forget(self, key: str) -> None:
        """Deleted check: drop its episodes and release its damping.
        The damp entries live in the shared tracker, which the
        reconciler forgets separately; popping here keeps the snapshot
        honest even if sweep never runs again."""
        self._hot.pop(key, None)
        self._calm.pop(key, None)
        episode = self._engaged.pop(key, None)
        self._contended.pop(key, None)
        self._remedy_selected.pop(key, None)
        if episode is not None:
            namespace, name = self._split_key(key)
            try:
                self.metrics.clear_adaptive_cadence(name, namespace)
            except Exception:
                log.exception("adaptive cadence gauge failed")
        self._sync_frontdoor()
        self._refresh_lever_gauges()

    # -- read side ------------------------------------------------------
    def check_adapt(self, key: str) -> Optional[dict]:
        """Per-check adaptation block for /statusz ``checks[]`` and
        ``am-tpu why``; None when no lever touches the check."""
        levers: List[str] = []
        episode = self._engaged.get(key)
        if episode is not None:
            levers.append(LEVER_CADENCE)
        if key in self._contended:
            levers.append(LEVER_PLACEMENT)
        if key in self._remedy_selected:
            levers.append(LEVER_REMEDY)
        if not levers:
            return None
        return {
            "levers": levers,
            "cadence_factor": (
                episode["factor"] if episode is not None else None
            ),
            "cause": episode["cause"] if episode is not None else None,
            "since": episode["since"] if episode is not None else None,
            "cohort": self._contended.get(key),
            "remedy_bucket": self._remedy_selected.get(key),
        }

    def snapshot(self) -> dict:
        """Fleet-level adaptive block for /statusz."""
        ceiling = None
        if self.frontdoor is not None:
            try:
                ceiling = float(self.frontdoor.cache.freshness_ceiling())
            except Exception:
                ceiling = None
        levers = {
            LEVER_CADENCE: len(self._engaged),
            LEVER_REMEDY: len(self._remedy_selected),
            LEVER_PLACEMENT: len(self._contended),
            LEVER_FRONTDOOR: 1 if self._frontdoor_engaged else 0,
        }
        return {
            "engaged": any(levers.values()),
            "levers": levers,
            "cadence": {k: dict(v) for k, v in self._engaged.items()},
            "placement": dict(self._contended),
            "frontdoor": {
                "engaged": self._frontdoor_engaged,
                "since": self._frontdoor_since or None,
                "freshness_ceiling": ceiling,
                "shed_factor": (
                    SHED_FACTOR if self._frontdoor_engaged else None
                ),
            },
            "recent": list(self._log),
        }
