"""Opportunistic TPU-evidence harness.

The device tunnel wedges for HOURS at a time (it ate the round-2 and
round-3 driver bench artifacts despite bench.py retrying over ~11
minutes).  Betting a round's perf evidence on one end-of-round window
is the wrong capture strategy; this harness inverts it:

    python hack/tpu_evidence.py --watch            # poll for hours
    make bench-tpu                                 # one capture attempt

Each cycle probes device reachability in a killable subprocess.  When
the tunnel is healthy it runs the FULL capture — bench.py's primary
metric, the secondary kernel metrics (flash fwd/bwd, HBM stream, int8),
and the flash block-size sweep — in another killable subprocess, then
atomically writes:

- ``BENCH_TPU.json``  — machine-readable last-known-good TPU numbers,
  timestamped; bench.py's CPU fallback embeds this block so even a
  wedged end-of-round artifact carries real measurements.
- ``SWEEP_TPU.md``    — the human-readable sweep tables that the block
  defaults in ops/flash_attention.py cite.

Writes are tmp+rename so a reader (bench.py, the driver, a human) never
sees a torn file.  The harness never touches git: the builder commits
artifacts deliberately, keeping the repo index free of daemon races.

Reference analogue: the reference has no perf bar at all (BASELINE.md —
no published numbers); this harness exists because OUR bar (BASELINE.md
targets) requires driver-verifiable TPU measurements.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "print(float(jax.jit(lambda a:(a@a).astype(jnp.float32).sum())"
    "(jnp.ones((128,128), jnp.bfloat16))))"
)


def _log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%H:%M:%S")
    print(f"[tpu-evidence {stamp}Z] {msg}", file=sys.stderr, flush=True)


def device_reachable(timeout: float) -> bool:
    """One killable probe attempt (no retries — the watch loop IS the
    retry policy, spread over hours rather than minutes)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout,
            capture_output=True,
            cwd=_REPO,
        )
    except subprocess.TimeoutExpired:
        _log(f"probe hung past {timeout:.0f}s (wedged tunnel)")
        return False
    if proc.returncode != 0:
        tail = proc.stderr.decode(errors="replace").strip().splitlines()[-4:]
        _log("probe exited %d: %s" % (proc.returncode, " | ".join(tail)))
        return False
    return True


def _capture() -> dict:
    """Child-mode body: run on the real device, return the evidence doc.

    Imports bench.py for the primary + secondary measurement so the
    harness can never drift from what the driver's bench reports.
    """
    sys.path.insert(0, _REPO)
    import bench  # noqa: E402

    doc = bench._measure(want_cpu=False)
    if doc.get("platform") != "tpu":
        raise SystemExit(f"capture landed on {doc.get('platform')}, not tpu")

    from activemonitor_tpu.probes import flash as flash_probe

    try:
        sweep = flash_probe.sweep(rounds=2, iters=3)
        doc["flash_sweep"] = {
            "summary": sweep.summary,
            "details": sweep.details,
        }
    except Exception as exc:  # pragma: no cover - hardware dependent
        doc["flash_sweep"] = {"error": str(exc)[:200]}
    return doc


def _render_sweep_md(doc: dict) -> str:
    """SWEEP_TPU.md — the block-size tables, human-readable."""
    sweep = doc.get("flash_sweep", {})
    details = sweep.get("details", {})
    lines = [
        "# Flash-attention block-size sweep (real TPU capture)",
        "",
        f"- captured: {doc.get('captured_at', '?')}",
        f"- device: {doc.get('device_kind', '?')} ({doc.get('n_devices', '?')} chip)",
        f"- shape: B={details.get('batch')} S={details.get('seq')} "
        f"H={details.get('heads')} D={details.get('head_dim')} "
        f"causal={details.get('causal')}",
        "",
        f"**{sweep.get('summary', sweep.get('error', 'capture failed'))}**",
        "",
    ]

    def table(name: str, tbl: dict) -> list:
        if not tbl:
            return []
        out = [f"## {name}", "", "| blocks (q×k) | TFLOP/s |", "|---|---|"]
        for key, val in sorted(tbl.items()):
            out.append(f"| {key} | {val} |")
        out.append("")
        return out

    lines += table("Forward", details.get("forward_table_tflops", {}))
    lines += table(
        "Effective fwd+bwd (best fwd + swept bwd blocks)",
        details.get("train_table_tflops", {}),
    )
    lines += [
        "Captured by `hack/tpu_evidence.py` when the device tunnel was",
        "healthy; regenerate with `make bench-tpu`.",
        "",
    ]
    return "\n".join(lines)


def _atomic_write(path: str, text: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def capture_once(args: argparse.Namespace) -> bool:
    """Probe → capture → write artifacts. True on a committed capture."""
    if not device_reachable(args.probe_timeout):
        return False
    _log("tunnel healthy — starting full capture (compiles may take minutes)")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child-capture"],
            timeout=args.capture_timeout,
            capture_output=True,
            cwd=_REPO,
        )
    except subprocess.TimeoutExpired:
        _log(f"capture hung past {args.capture_timeout:.0f}s (mid-run wedge)")
        return False
    sys.stderr.write(proc.stderr.decode(errors="replace"))
    lines = [ln for ln in proc.stdout.decode(errors="replace").splitlines() if ln]
    if proc.returncode != 0 or not lines:
        _log(f"capture exited {proc.returncode}")
        return False
    try:
        doc = json.loads(lines[-1])
    except json.JSONDecodeError:
        _log("capture emitted no JSON tail")
        return False
    doc["captured_at"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    doc["harness"] = "hack/tpu_evidence.py"
    out = os.path.join(_REPO, args.out)
    _atomic_write(out, json.dumps(doc, indent=2) + "\n")
    _atomic_write(os.path.join(_REPO, args.sweep_out), _render_sweep_md(doc))
    _log(
        f"captured {doc.get('metric')}={doc.get('value')} {doc.get('unit')} "
        f"→ {args.out} + {args.sweep_out}"
    )
    return True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--watch", action="store_true",
                        help="poll until --max-hours instead of one attempt")
    parser.add_argument("--interval", type=float, default=300.0,
                        help="seconds between probes while wedged")
    parser.add_argument("--refresh", type=float, default=7200.0,
                        help="seconds between captures once one succeeded")
    parser.add_argument("--max-hours", type=float, default=11.0,
                        help="watch-mode lifetime")
    parser.add_argument("--probe-timeout", type=float, default=90.0)
    parser.add_argument("--capture-timeout", type=float, default=2400.0)
    parser.add_argument("--out", default="BENCH_TPU.json")
    parser.add_argument("--sweep-out", default="SWEEP_TPU.md")
    parser.add_argument("--child-capture", action="store_true",
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.child_capture:
        print(json.dumps(_capture()))
        return 0

    if not args.watch:
        ok = capture_once(args)
        _log("capture %s" % ("succeeded" if ok else "failed — tunnel wedged?"))
        return 0 if ok else 1

    deadline = time.monotonic() + args.max_hours * 3600.0
    captured = 0
    while time.monotonic() < deadline:
        if capture_once(args):
            captured += 1
            sleep = args.refresh
        else:
            sleep = args.interval
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(sleep, remaining))
    _log(f"watch window over — {captured} capture(s)")
    return 0 if captured else 1


if __name__ == "__main__":
    sys.exit(main())
