"""RBAC provisioner tests (reference test model:
healthcheck_controller_unit_test.go:310-613)."""

import pytest

from activemonitor_tpu.api import (
    ArtifactLocation,
    HealthCheck,
    PolicyRule,
    RemedyWorkflow,
    ResourceObject,
)
from activemonitor_tpu.controller import (
    DEFAULT_HEALTHCHECK_RULES,
    DEFAULT_REMEDY_RULES,
    InMemoryRBACBackend,
    MANAGED_BY_LABEL_KEY,
    MANAGED_BY_VALUE,
    RBACError,
    RBACObject,
    RBACProvisioner,
    resolve_rbac_rules,
)


def make_hc(level="cluster", sa="check-sa", remedy_sa=None, custom_rules=None):
    remedy = RemedyWorkflow()
    if remedy_sa is not None:
        remedy = RemedyWorkflow(
            generate_name="remedy-",
            resource=ResourceObject(
                namespace="health",
                service_account=remedy_sa,
                source=ArtifactLocation(inline="kind: Workflow"),
            ),
        )
    return HealthCheck.from_dict(
        {
            "metadata": {"name": "hc-test", "namespace": "health", "uid": "u1"},
            "spec": {
                "level": level,
                "repeatAfterSec": 60,
                "workflow": {
                    "generateName": "check-",
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": sa,
                        "source": {"inline": "kind: Workflow"},
                    },
                    "rbacRules": custom_rules or [],
                },
                "remedyworkflow": remedy.model_dump(by_alias=True, exclude_none=True),
            },
        }
    )


@pytest.fixture()
def backend():
    return InMemoryRBACBackend()


@pytest.fixture()
def prov(backend):
    return RBACProvisioner(backend)


@pytest.mark.asyncio
async def test_cluster_level_creates_sa_role_binding(prov, backend):
    await prov.create_rbac_for_workflow(make_hc(), "healthCheck")
    assert ("ServiceAccount", "health", "check-sa") in backend.objects
    role = backend.objects[("ClusterRole", "", "check-sa-cluster-role")]
    binding = backend.objects[("ClusterRoleBinding", "", "check-sa-cluster-role-binding")]
    assert binding.role_ref == "check-sa-cluster-role"
    assert binding.subject == "health/check-sa"
    # read-only verbs (reference: :85-101) — except the Argo 3.4+
    # executor-reporting grant, which is write-scoped to exactly
    # workflowtaskresults (divergence #9, docs/design.md)
    for rule in role.rules:
        if rule.resources == ["workflowtaskresults"]:
            assert set(rule.verbs) == {"create", "patch"}
        else:
            assert set(rule.verbs) == {"get", "list", "watch"}


@pytest.mark.asyncio
async def test_namespace_level_creates_ns_role(prov, backend):
    await prov.create_rbac_for_workflow(make_hc(level="namespace"), "healthCheck")
    assert ("Role", "health", "check-sa-ns-role") in backend.objects
    assert ("RoleBinding", "health", "check-sa-ns-role-binding") in backend.objects
    assert ("ClusterRole", "", "check-sa-cluster-role") not in backend.objects


@pytest.mark.asyncio
async def test_remedy_gets_write_verbs(prov, backend):
    hc = make_hc(remedy_sa="remedy-sa")
    await prov.create_rbac_for_workflow(hc, "remedy")
    role = backend.objects[("ClusterRole", "", "remedy-sa-cluster-role")]
    for rule in role.rules:
        assert "create" in rule.verbs and "delete" in rule.verbs


@pytest.mark.asyncio
async def test_sa_collision_renames_remedy_sa(prov, backend):
    # reference: :316-319
    hc = make_hc(sa="shared-sa", remedy_sa="shared-sa")
    await prov.create_rbac_for_workflow(hc, "remedy")
    assert hc.spec.remedy_workflow.resource.service_account == "shared-sa-remedy"
    assert ("ServiceAccount", "health", "shared-sa-remedy") in backend.objects


@pytest.mark.asyncio
async def test_remedy_missing_sa_is_error(prov):
    hc = make_hc()
    hc.spec.remedy_workflow = RemedyWorkflow(
        generate_name="remedy-",
        resource=ResourceObject(namespace="health", source=ArtifactLocation(inline="x: y")),
    )
    with pytest.raises(RBACError, match="ServiceAccount for the RemedyWorkflow"):
        await prov.create_rbac_for_workflow(hc, "healthCheck")


@pytest.mark.asyncio
async def test_remedy_nil_resource_is_error(prov):
    hc = make_hc()
    hc.spec.remedy_workflow = RemedyWorkflow(generate_name="remedy-")
    with pytest.raises(RBACError, match="Resource is nil"):
        await prov.create_rbac_for_workflow(hc, "healthCheck")


@pytest.mark.asyncio
async def test_unset_level_is_error(prov):
    with pytest.raises(RBACError, match="level is not set"):
        await prov.create_rbac_for_workflow(make_hc(level=""), "healthCheck")


@pytest.mark.asyncio
async def test_custom_rules_override(prov, backend):
    custom = [{"apiGroups": ["batch"], "resources": ["jobs"], "verbs": ["get"]}]
    await prov.create_rbac_for_workflow(make_hc(custom_rules=custom), "healthCheck")
    role = backend.objects[("ClusterRole", "", "check-sa-cluster-role")]
    assert len(role.rules) == 1
    assert role.rules[0].resources == ["jobs"]


@pytest.mark.asyncio
async def test_idempotent_create_reuses_existing(prov, backend):
    hc = make_hc()
    await prov.create_rbac_for_workflow(hc, "healthCheck")
    marker = backend.objects[("ServiceAccount", "health", "check-sa")]
    await prov.create_rbac_for_workflow(hc, "healthCheck")
    assert backend.objects[("ServiceAccount", "health", "check-sa")] is marker


@pytest.mark.asyncio
async def test_delete_remedy_rbac_guarded_by_managed_label(prov, backend):
    # reference: delete guard, e.g. healthcheck_controller.go:1169,:1242
    hc = make_hc(remedy_sa="remedy-sa")
    await prov.create_rbac_for_workflow(hc, "remedy")
    # plant a user-owned object with the same name pattern
    backend.objects[("ClusterRole", "", "user-role")] = RBACObject(
        kind="ClusterRole", name="user-role", labels={}
    )
    await prov.delete_rbac_for_workflow(hc)
    assert ("ServiceAccount", "health", "remedy-sa") not in backend.objects
    assert ("ClusterRole", "", "remedy-sa-cluster-role") not in backend.objects
    assert ("ClusterRoleBinding", "", "remedy-sa-cluster-role-binding") not in backend.objects


@pytest.mark.asyncio
async def test_delete_skips_unmanaged_objects(prov, backend):
    hc = make_hc(remedy_sa="remedy-sa")
    # object exists but without our label -> left alone
    backend.objects[("ServiceAccount", "health", "remedy-sa")] = RBACObject(
        kind="ServiceAccount", name="remedy-sa", namespace="health", labels={}
    )
    await prov.delete_rbac_for_workflow(hc)
    assert ("ServiceAccount", "health", "remedy-sa") in backend.objects


@pytest.mark.asyncio
async def test_delete_with_nil_remedy_resource_is_noop(prov):
    hc = make_hc()  # empty remedy
    await prov.delete_rbac_for_workflow(hc)  # must not raise


def test_no_wildcards_in_default_rules():
    # reference invariant (healthcheck_controller_unit_test.go:447-457)
    for rules in (DEFAULT_HEALTHCHECK_RULES, DEFAULT_REMEDY_RULES):
        for rule in rules:
            assert "*" not in rule.verbs
            assert "*" not in rule.resources
            assert "*" not in rule.api_groups


def test_resolve_rules_prefers_custom():
    custom = [PolicyRule(api_groups=["x"], resources=["y"], verbs=["get"])]
    assert resolve_rbac_rules(custom, DEFAULT_HEALTHCHECK_RULES) is custom
    assert resolve_rbac_rules([], DEFAULT_HEALTHCHECK_RULES) is DEFAULT_HEALTHCHECK_RULES


@pytest.mark.asyncio
async def test_managed_by_labels_on_created_objects(prov, backend):
    await prov.create_rbac_for_workflow(make_hc(), "healthCheck")
    for obj in backend.objects.values():
        assert obj.labels[MANAGED_BY_LABEL_KEY] == MANAGED_BY_VALUE
