"""Decode-step probe — serving-path health.

Times the autoregressive hot loop (single-token decode with a KV cache)
that inference workloads live in. Training-shaped probes can look
healthy while the serving path is broken or slow — small matmuls, cache
scatter updates, and per-token dispatch stress entirely different parts
of the stack than big batched matmuls.

Exports per-token latency and decoded tokens/s; the correctness gate is
greedy-decode consistency: the same prompt must reproduce the same
continuation as the batched forward pass (cache vs no-cache agreement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    tiny_config,
)
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    tiny: bool = False,
    batch: int = 8,
    prompt_len: int = 16,
    decode_tokens: int = 32,
    iters: int = 5,
) -> ProbeResult:
    cfg = tiny_config() if tiny else ProbeModelConfig()
    if prompt_len < 1 or decode_tokens < 1:
        raise ValueError("prompt_len and decode_tokens must be >= 1")
    if prompt_len + 2 > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} leaves no decode room in "
            f"max_seq_len {cfg.max_seq_len}"
        )
    max_seq = min(cfg.max_seq_len, prompt_len + decode_tokens + 1)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size
    )

    step = jax.jit(lambda p, c, t, pos: decode_step(p, c, t, pos, cfg))

    # correctness: greedy continuation via the cache must match the
    # batched forward pass run over the growing sequence
    cache = init_kv_cache(cfg, batch, max_seq)
    # prefill token-by-token (simple and exercises the cache path)
    for i in range(prompt_len):
        logits, cache = step(params, cache, prompt[:, i], jnp.asarray(i))
    # the cache has room for max_seq - prompt_len generated positions
    n_check = min(4, max_seq - prompt_len - 1)
    cached_tokens = []
    token = jnp.argmax(logits, axis=-1)
    for i in range(n_check):
        cached_tokens.append(token)
        logits, cache = step(
            params, cache, token, jnp.asarray(prompt_len + i)
        )
        token = jnp.argmax(logits, axis=-1)

    full = prompt
    for i in range(n_check):
        logits_full = forward(params, full, cfg)[:, -1]
        full = jnp.concatenate(
            [full, jnp.argmax(logits_full, axis=-1)[:, None]], axis=1
        )
    consistent = bool(jnp.array_equal(full[:, prompt_len:], jnp.stack(cached_tokens, 1)))

    # throughput: a lax.scan of decode steps (token feeds the next step;
    # one traced step, so long chains compile as fast as short ones).
    # Single decode steps are microseconds on TPU — the k spread must be
    # wide enough for the delta to tower over dispatch/tunnel jitter.
    def make_chain(k):
        @jax.jit
        def chain(params, cache, token):
            def body(carry, i):
                cache, token = carry
                # wrap position so long chains never overrun the cache
                pos = jnp.asarray(prompt_len, jnp.int32) + jnp.mod(
                    i, max_seq - prompt_len
                )
                logits, cache = decode_step(params, cache, token, pos, cfg)
                return (cache, jnp.argmax(logits, axis=-1)), logits[0, 0]

            (_, _), outs = jax.lax.scan(
                body, (cache, token), jnp.arange(k, dtype=jnp.int32)
            )
            return outs.sum()

        return chain

    cache2 = init_kv_cache(cfg, batch, max_seq)
    token0 = prompt[:, 0]
    seconds = chain_delta_seconds(
        make_chain, params, cache2, token0, k1=32, k2=288, iters=iters
    )
    tokens_per_second = batch / seconds

    metrics = [
        ProbeMetric(
            "decode-step-milliseconds",
            seconds * 1e3,
            help="Per-token decode latency with KV cache",
        ),
        ProbeMetric(
            "decode-tokens-per-second",
            tokens_per_second,
            help="Aggregate decoded tokens/s across the batch",
        ),
        ProbeMetric(
            "decode-consistency",
            1.0 if consistent else 0.0,
            help="1 when cached greedy decode matches the batched forward",
        ),
    ]
    return ProbeResult(
        ok=consistent,
        summary=(
            f"decode {seconds * 1e3:.2f}ms/token, {tokens_per_second:,.0f} tok/s, "
            f"cache consistency {'OK' if consistent else 'MISMATCH'}"
        ),
        metrics=metrics,
        details={
            "batch": batch,
            "prompt_len": prompt_len,
            "max_seq": max_seq,
            "seconds_per_token": seconds,
        },
    )
