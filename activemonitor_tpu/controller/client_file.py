"""File-backed HealthCheck client — the durable local-mode store.

Single-host deployments (a TPU VM with no Kubernetes) keep HealthCheck
specs as YAML files in a directory; the controller watches the
directory the way it would watch the API server. Status lives in a
sidecar JSON per check, preserving the reference's checkpoint semantics
(SURVEY.md §5.4: durable state only in the CR status; timers rebuilt
from ``finishedAt`` on boot) across controller restarts.

Layout::

    <dir>/<anything>.yaml          # HealthCheck manifests (user-owned)
    <dir>/.status/<ns>__<name>.json  # status subresource (controller-owned)
"""

from __future__ import annotations

import asyncio
import json
import logging
from pathlib import Path
from typing import AsyncIterator, Dict, List, Optional

import yaml

from activemonitor_tpu.api.types import HealthCheck, HealthCheckStatus
from activemonitor_tpu.controller.client import ConflictError, NotFoundError, WatchEvent

log = logging.getLogger(__name__)


class FileHealthCheckClient:
    def __init__(self, directory: str, poll_seconds: float = 0.5):
        self._dir = Path(directory)
        self._status_dir = self._dir / ".status"
        self._status_dir.mkdir(parents=True, exist_ok=True)
        self._poll = poll_seconds
        self._rv = 0

    # -- loading --------------------------------------------------------
    def _status_path(self, namespace: str, name: str) -> Path:
        return self._status_dir / f"{namespace}__{name}.json"

    def _load_all(self) -> Dict[str, HealthCheck]:
        out: Dict[str, HealthCheck] = {}
        for path in sorted(self._dir.glob("*.yaml")) + sorted(self._dir.glob("*.yml")):
            try:
                docs = list(yaml.safe_load_all(path.read_text()))
            except yaml.YAMLError as e:
                log.error("%s: invalid YAML skipped: %s", path, e)
                continue
            for doc in docs:
                if not isinstance(doc, dict) or doc.get("kind") != "HealthCheck":
                    continue
                try:
                    hc = HealthCheck.from_dict(doc)
                except Exception as e:
                    # one invalid check must not take down the store
                    log.error(
                        "%s: invalid HealthCheck %r skipped: %s",
                        path,
                        doc.get("metadata", {}).get("name"),
                        e,
                    )
                    continue
                if not hc.metadata.name:
                    log.warning("%s: HealthCheck without metadata.name skipped", path)
                    continue
                if not hc.metadata.namespace:
                    hc.metadata.namespace = "default"
                if not hc.metadata.uid:
                    hc.metadata.uid = f"file-{hc.key}"
                self._merge_status(hc)
                out[hc.key] = hc
        return out

    def _merge_status(self, hc: HealthCheck) -> None:
        # every read surfaces a resourceVersion — "0" before the first
        # status write. An EMPTY rv would disarm update_status's CAS
        # guard entirely (both-sides-non-empty check), so a snapshot
        # taken before any status write could never conflict: the
        # staleness the contract suite requires every client to detect
        hc.metadata.resource_version = "0"
        path = self._status_path(hc.metadata.namespace, hc.metadata.name)
        if path.exists():
            try:
                doc = json.loads(path.read_text())
                hc.status = HealthCheckStatus.model_validate(doc.get("status", {}))
                hc.metadata.resource_version = str(doc.get("resourceVersion", "0"))
            except (json.JSONDecodeError, ValueError) as e:
                log.error("%s: corrupt status sidecar ignored: %s", path, e)

    # -- client API -------------------------------------------------------
    async def get(self, namespace: str, name: str) -> Optional[HealthCheck]:
        return self._load_all().get(f"{namespace}/{name}")

    async def list(self, namespace: Optional[str] = None) -> List[HealthCheck]:
        return [
            hc
            for key, hc in sorted(self._load_all().items())
            if namespace is None or hc.metadata.namespace == namespace
        ]

    async def apply(self, hc: HealthCheck) -> HealthCheck:
        hc = hc.deepcopy()
        if not hc.metadata.namespace:
            hc.metadata.namespace = "default"
        if not hc.metadata.name:
            from activemonitor_tpu.engine.base import generate_name

            hc.metadata.name = generate_name(hc.metadata.generate_name or "hc-")
        doc = hc.to_dict()
        doc.pop("status", None)  # status lives in the sidecar
        # update in place if the check already lives in a user-named
        # file: writing a second copy elsewhere would leave the
        # alphabetically-later (possibly stale) doc winning _load_all
        if not self._rewrite_in_place(hc.metadata.namespace, hc.metadata.name, doc):
            path = self._dir / f"{hc.metadata.namespace}__{hc.metadata.name}.yaml"
            path.write_text(yaml.safe_dump(doc, sort_keys=False))
        # a spec apply BUMPS the durable rv like the other clients (the
        # in-memory store and a k8s PUT both do), so a snapshot taken
        # before the spec change conflicts on its next status write on
        # every backend. Hand-edits to the YAML files bypass this —
        # inherent to a user-editable store, and the watch poll still
        # surfaces them as MODIFIED events.
        self._bump_rv(hc.metadata.namespace, hc.metadata.name)
        # like the other clients, apply returns an rv-bearing object so
        # an apply→mutate→update_status sequence still CAS-protects
        self._merge_status(hc)
        return hc

    def _bump_rv(self, namespace: str, name: str) -> None:
        """Advance the durable rv in the status sidecar, preserving any
        recorded status."""
        path = self._status_path(namespace, name)
        status: dict = {}
        durable = 0
        if path.exists():
            try:
                doc = json.loads(path.read_text())
                status = doc.get("status", {})
                durable = int(doc.get("resourceVersion", 0))
            except (json.JSONDecodeError, ValueError):
                pass
        self._rv = max(self._rv, durable) + 1
        path.write_text(
            json.dumps(
                {"status": status, "resourceVersion": str(self._rv)},
                default=str,
            )
        )

    def _rewrite_in_place(self, namespace: str, name: str, new_doc: dict) -> bool:
        for path in list(self._dir.glob("*.yaml")) + list(self._dir.glob("*.yml")):
            try:
                docs = list(yaml.safe_load_all(path.read_text()))
            except yaml.YAMLError:
                continue
            replaced = False
            for i, doc in enumerate(docs):
                if (
                    isinstance(doc, dict)
                    and doc.get("kind") == "HealthCheck"
                    and doc.get("metadata", {}).get("name") == name
                    and doc.get("metadata", {}).get("namespace", "default") == namespace
                ):
                    docs[i] = new_doc
                    replaced = True
            if replaced:
                path.write_text(yaml.safe_dump_all(docs, sort_keys=False))
                return True
        return False

    async def update_status(self, hc: HealthCheck) -> HealthCheck:
        existing = await self.get(hc.metadata.namespace, hc.metadata.name)
        if existing is None:
            raise NotFoundError(hc.key)
        if (
            hc.metadata.resource_version
            and existing.metadata.resource_version
            and hc.metadata.resource_version != existing.metadata.resource_version
        ):
            raise ConflictError(hc.key)
        # the next rv derives from the DURABLE one, not just the
        # in-memory counter: a restarted controller (or a second client
        # instance on the same store) starts its counter at 0, and a
        # regressed rv would let genuinely stale snapshots compare
        # equal — silently clobbering newer status
        try:
            durable = int(existing.metadata.resource_version or 0)
        except ValueError:
            durable = 0
        self._rv = max(self._rv, durable) + 1
        payload = {
            "status": hc.status.to_json_dict(),
            "resourceVersion": str(self._rv),
        }
        self._status_path(hc.metadata.namespace, hc.metadata.name).write_text(
            json.dumps(payload, default=str)
        )
        hc = hc.deepcopy()
        hc.metadata.resource_version = str(self._rv)
        return hc

    async def delete(self, namespace: str, name: str) -> None:
        found = False
        for path in list(self._dir.glob("*.yaml")) + list(self._dir.glob("*.yml")):
            try:
                docs = list(yaml.safe_load_all(path.read_text()))
            except yaml.YAMLError:
                continue
            keep = [
                d
                for d in docs
                if not (
                    isinstance(d, dict)
                    and d.get("kind") == "HealthCheck"
                    and d.get("metadata", {}).get("name") == name
                    and d.get("metadata", {}).get("namespace", "default") == namespace
                )
            ]
            if len(keep) != len(docs):
                found = True
                if keep:
                    path.write_text(yaml.safe_dump_all(keep, sort_keys=False))
                else:
                    path.unlink()
        status = self._status_path(namespace, name)
        if status.exists():
            status.unlink()
        if not found:
            raise NotFoundError(f"{namespace}/{name}")

    # -- watch --------------------------------------------------------------
    def watch(self) -> AsyncIterator[WatchEvent]:
        """Poll the directory; emits ADDED/MODIFIED/DELETED.

        MODIFIED covers spec AND status changes — the in-memory client
        and a real apiserver both emit for status-subresource writes,
        so the file backend must too or a manager reacting to MODIFIED
        behaves differently per store (the reconciler's dedupe absorbs
        the self-churn from its own status writes, same as cluster
        mode). The baseline snapshot is taken SYNCHRONOUSLY at call
        time: specs existing now are the manager's boot-resync job;
        anything that changes after this call is a watch event — no gap
        between the two (list-then-watch ordering)."""

        def snapshot():
            return {
                k: (hc.spec.to_json_dict(), hc.metadata.resource_version)
                for k, hc in self._load_all().items()
            }

        known: Dict[str, tuple] = snapshot()

        async def gen() -> AsyncIterator[WatchEvent]:
            nonlocal known
            while True:
                await asyncio.sleep(self._poll)
                specs = snapshot()
                for key in specs.keys() - known.keys():
                    ns, _, name = key.partition("/")
                    yield WatchEvent(type="ADDED", namespace=ns, name=name)
                for key in known.keys() - specs.keys():
                    ns, _, name = key.partition("/")
                    yield WatchEvent(type="DELETED", namespace=ns, name=name)
                for key in specs.keys() & known.keys():
                    if specs[key] != known[key]:
                        ns, _, name = key.partition("/")
                        yield WatchEvent(type="MODIFIED", namespace=ns, name=name)
                known = specs

        return gen()
