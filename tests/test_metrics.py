"""Metrics tests (reference test model: internal/metrics/collector_test.go —
malformed custom-metric table against a private registry)."""

import pytest

from activemonitor_tpu.metrics import (
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)


@pytest.fixture()
def collector():
    return MetricsCollector()


def labels(name, wf=WORKFLOW_LABEL_HEALTHCHECK):
    return {"healthcheck_name": name, "workflow": wf}


def test_record_success_sets_all_vecs(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 107.5)
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) == 1
    assert collector.sample_value("healthcheck_runtime_seconds", labels("hc-a")) == 7.5
    assert collector.sample_value("healthcheck_starttime", labels("hc-a")) == 100.0
    assert collector.sample_value("healthcheck_finishedtime", labels("hc-a")) == 107.5


def test_record_failure_increments_error(collector):
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 101.0)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 102.0, 103.0)
    assert collector.sample_value("healthcheck_error_count", labels("hc-a")) == 2
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) is None


def test_remedy_label_dimension(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_REMEDY, 0, 1)
    assert (
        collector.sample_value(
            "healthcheck_success_count", labels("hc-a", WORKFLOW_LABEL_REMEDY)
        )
        == 1
    )


def test_exposition_contains_reference_metric_names(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    text = collector.exposition().decode()
    # exact names, no _total suffix (scrape contract of the reference)
    assert "healthcheck_success_count{" in text
    assert "healthcheck_runtime_seconds{" in text


def test_custom_metrics_from_outputs(collector):
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-gbps", '
                    '"value": 123.4, "metrictype": "gauge", "help": "ICI bw"}]}',
                }
            ]
        }
    }
    n = collector.record_custom_metrics("tpu-probe", status)
    assert n == 1
    # both hc name and metric name sanitized: "-" -> "_"
    assert (
        collector.sample_value(
            "tpu_probe_ici_allreduce_gbps", {"healthcheck_name": "tpu-probe"}
        )
        == 123.4
    )


def test_custom_metric_name_overlap_deduped(collector):
    # deliberate divergence from collector.go:90 (design.md #12): the
    # hc-name prefix merges with the metric name's leading overlap
    # instead of stuttering
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-busbw-gbps", '
                    '"value": 600.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("tpu-ici-allreduce", status) == 1
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        == 600.0
    )
    # the stuttered reference name must NOT exist
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        is None
    )


def test_same_check_merged_name_collision_skipped(collector):
    # check a-b emitting b-c and c: both merge to a_b_c — the second
    # must be skipped (logged), never silently overwrite the first
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "b-c", "value": 1.0}, '
                    '{"name": "c", "value": 2.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("a-b", status) == 1
    assert collector.sample_value("a_b_c", {"healthcheck_name": "a-b"}) == 1.0


def test_prefix_dedupe_rules():
    from activemonitor_tpu.metrics.collector import _prefix_dedupe

    assert _prefix_dedupe("tpu_ici_allreduce", "ici_allreduce_busbw_gbps") == (
        "tpu_ici_allreduce_busbw_gbps"
    )
    assert _prefix_dedupe("hc", "bw") == "hc_bw"  # no overlap: plain join
    assert _prefix_dedupe("hc", "hc") == "hc"  # full overlap
    # overlap matches whole tokens only — "al" vs "allreduce" is no match
    assert _prefix_dedupe("tpu_al", "allreduce_gbps") == "tpu_al_allreduce_gbps"


def test_custom_metrics_updates_existing_gauge(collector):
    def status(v):
        return {
            "outputs": {
                "parameters": [
                    {"name": "m", "value": '{"metrics": [{"name": "bw", "value": %f}]}' % v}
                ]
            }
        }

    collector.record_custom_metrics("hc", status(1.0))
    collector.record_custom_metrics("hc", status(2.0))
    assert collector.sample_value("hc_bw", {"healthcheck_name": "hc"}) == 2.0


@pytest.mark.parametrize(
    "value",
    [
        "not json at all",
        '{"metrics": "not-a-list"}',
        '{"metrics": [{"value": 1.0}]}',  # missing name
        '{"metrics": [{"name": "x", "value": "NaN-ish-string"}]}',
        '{"metrics": [42]}',
        '{"other": []}',
        "",
    ],
)
def test_malformed_custom_metrics_are_skipped(collector, value):
    status = {"outputs": {"parameters": [{"name": "m", "value": value}]}}
    assert collector.record_custom_metrics("hc", status) == 0


def test_no_outputs_is_noop(collector):
    assert collector.record_custom_metrics("hc", {}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": None}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": {"parameters": None}}) == 0


REFERENCE_SCRAPE_NAMES = (
    # the exact names the reference exposes (collector.go:19-48) —
    # dashboards and alerts scrape these verbatim
    "healthcheck_success_count",
    "healthcheck_error_count",
    "healthcheck_runtime_seconds",
    "healthcheck_starttime",
    "healthcheck_finishedtime",
)

# EVERY static family the collector constructs, by declared name —
# the exposition contract. tests/test_lint.py walks collector.py's AST
# and rejects any Gauge/Counter/Histogram/Summary constructed there
# whose name is missing from this table, so a new family cannot ship
# unpinned. Values are the prometheus type (drives which sample suffix
# the scrape assertion looks for).
PINNED_FAMILIES = {
    "healthcheck_success_count": "gauge",
    "healthcheck_error_count": "gauge",
    "healthcheck_runtime_seconds": "gauge",
    "healthcheck_starttime": "gauge",
    "healthcheck_finishedtime": "gauge",
    "healthcheck_runtime_histogram_seconds": "histogram",
    "healthcheck_phase_seconds": "histogram",
    "healthcheck_cadence_goodput": "gauge",
    "healthcheck_fleet_goodput_ratio": "gauge",
    # goodput attribution families (ISSUE 7: lost-goodput decomposition
    # — docs/observability.md "Goodput attribution")
    "healthcheck_goodput_lost_ratio": "gauge",
    "healthcheck_goodput_attribution_info": "gauge",
    "healthcheck_phase_timings_skipped_total": "counter",
    # roofline families (ISSUE 9: cost-model evidence under every
    # fraction — docs/observability.md "Reading a roofline")
    "healthcheck_probe_roofline_fraction": "gauge",
    "healthcheck_probe_arithmetic_intensity": "gauge",
    "healthcheck_hbm_peak_bytes": "gauge",
    "healthcheck_probe_roofline_runs_total": "counter",
    "healthcheck_slo_availability_ratio": "gauge",
    "healthcheck_error_budget_remaining": "gauge",
    "healthcheck_slo_burn_rate": "gauge",
    "workflow_watch_healthy": "gauge",
    # resilience families (ISSUE 3: degraded mode, per-check state
    # machine, remedy storm control — docs/resilience.md)
    "healthcheck_controller_degraded": "gauge",
    "healthcheck_status_write_queue_depth": "gauge",
    "healthcheck_check_state": "gauge",
    "healthcheck_remedy_runs_total": "counter",
    # analysis families (ISSUE 4: baseline & anomaly detection —
    # docs/analysis.md)
    "healthcheck_metric_baseline": "gauge",
    "healthcheck_metric_zscore": "gauge",
    "healthcheck_anomaly_state": "gauge",
    # scenario-matrix families (ISSUE 12: declarative bench/probe
    # matrix — docs/observability.md "Reading the matrix")
    "healthcheck_matrix_cell_value": "gauge",
    "healthcheck_matrix_cell_state": "gauge",
    "healthcheck_matrix_cell_roofline_fraction": "gauge",
    "healthcheck_matrix_cells": "gauge",
    "healthcheck_matrix_bisect_runs_total": "counter",
    # front-door families (ISSUE 15: probe-as-a-service ingestion —
    # docs/operations.md "Probe-as-a-service front door")
    "healthcheck_frontdoor_requests_total": "counter",
    "healthcheck_frontdoor_refusals_total": "counter",
    "healthcheck_frontdoor_coalesce_ratio": "gauge",
    "healthcheck_frontdoor_queue_depth": "gauge",
    "healthcheck_frontdoor_admission_seconds": "histogram",
    # critical-path families (ISSUE 17: cross-layer waterfall
    # decomposition — docs/observability.md "Reading a waterfall")
    "healthcheck_critical_path_seconds": "gauge",
    "healthcheck_profile_captures_total": "counter",
    # adaptive-control families (ISSUE 18: closed-loop goodput control
    # — docs/resilience.md "Adaptive control loop")
    "healthcheck_adaptive_cadence_factor": "gauge",
    "healthcheck_adaptive_lever_active": "gauge",
    "healthcheck_adaptive_transitions_total": "counter",
    "healthcheck_adaptive_freshness_ceiling_seconds": "gauge",
    "healthcheck_frontdoor_freshness_clamped_total": "counter",
    # durable-journal families (ISSUE 16: restart-proof telemetry
    # journal — docs/observability.md "Durable telemetry journal")
    "healthcheck_journal_appended_total": "counter",
    "healthcheck_journal_replayed_total": "counter",
    "healthcheck_journal_dropped_total": "counter",
    "healthcheck_journal_segments": "gauge",
    "healthcheck_journal_lag_seconds": "gauge",
    # federation families (ISSUE 19: planet-scale federation —
    # docs/operations.md "Federating clusters")
    "healthcheck_federation_clusters": "gauge",
    "healthcheck_federation_cluster_healthy": "gauge",
    "healthcheck_federation_transitions_total": "counter",
    "healthcheck_federation_requests_total": "counter",
    "healthcheck_federation_refusals_total": "counter",
    "healthcheck_federation_routes_total": "counter",
    "healthcheck_federation_goodput_ratio": "gauge",
    # disaggregated-serving families (ISSUE 20: prefill/decode pool
    # split, prefix caching, speculative decoding — docs/serving.md
    # "Disaggregated serving")
    "healthcheck_serving_prefix_cache_events_total": "counter",
    "healthcheck_serving_kv_migration_bytes_total": "counter",
    "healthcheck_serving_spec_accept_fraction": "gauge",
    "healthcheck_serving_pool_ttft_seconds": "gauge",
    # sharding families (ISSUE 6: sharded controller fleet —
    # docs/operations.md "Sharded controller fleet")
    "healthcheck_shard_owned": "gauge",
    "healthcheck_shard_checks": "gauge",
    "healthcheck_shard_handoffs_total": "counter",
    "healthcheck_shard_fenced_writes_total": "counter",
    "controller_runtime_reconcile_total": "counter",
    "controller_runtime_reconcile_time_seconds": "histogram",
    "controller_runtime_active_workers": "gauge",
    "controller_runtime_max_concurrent_reconciles": "gauge",
    "workqueue_depth": "gauge",
    "workqueue_adds_total": "counter",
    "workqueue_queue_duration_seconds": "histogram",
    "workqueue_work_duration_seconds": "histogram",
    "engine_submit_total": "counter",
    "engine_poll_total": "counter",
    "workflow_watch_restarts_total": "counter",
}


def exercise_every_family(collector):
    """Touch every static family so each one has at least one sample."""
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 1, 2)
    collector.record_reconcile("success", 0.25)
    collector.record_queue_add(1)
    collector.record_queue_get(0, 0.05)
    collector.record_work_duration(0.2)
    collector.set_active_workers(1)
    collector.set_max_concurrent(10)
    collector.record_engine_submit("fake")
    collector.record_engine_poll("fake")
    collector.record_watch_restart("health")
    collector.record_watch_health("health", True)
    collector.set_degraded(False)
    collector.set_status_write_queue_depth(0)
    # a non-healthy state materializes the trio (healthy-only checks
    # deliberately carry no state series — cardinality contract)
    collector.set_check_state("hc-a", "health", "Flapping")
    collector.record_remedy_run("hc-a", "health", "admitted")
    # analysis families; a non-ok state materializes the anomaly trio
    # (same laziness contract as check_state)
    collector.set_metric_baseline(
        "hc-a", "health", "m", mean=1.0, std=0.1, median=1.0, mad=0.05, count=5
    )
    collector.set_metric_zscore("hc-a", "health", "m", -2.0)
    collector.set_anomaly_state("hc-a", "health", "warning")
    # front-door families (ISSUE 15)
    collector.record_frontdoor_request("tenant-a", "cache_hit")
    collector.record_frontdoor_refusal("tenant-a", "quota")
    collector.set_frontdoor_coalesce(hit=0.5, miss=0.25, join=0.25)
    collector.set_frontdoor_queue_depth(2)
    collector.observe_frontdoor_admission(0.0004)
    # critical-path families (ISSUE 17)
    collector.set_critical_path(
        "hc-a",
        "health",
        {
            "stages": {
                "queue_wait": {"p50": 0.1, "p95": 0.2, "p99": 0.3},
            }
        },
    )
    collector.record_profile_capture("degraded")
    # adaptive-control families (ISSUE 18)
    collector.set_adaptive_cadence("hc-a", "health", 0.5)
    collector.set_adaptive_lever("cadence", True)
    collector.record_adaptive_transition("cadence", "engage")
    collector.set_adaptive_freshness_ceiling(120.0)
    collector.record_frontdoor_clamp("tenant-a", "degraded")
    # durable-journal families (ISSUE 16)
    collector.record_journal_append("result")
    collector.record_journal_replayed("result", 2)
    collector.record_journal_dropped()
    collector.set_journal_segments(1)
    collector.set_journal_lag(0.5)
    # federation families (ISSUE 19)
    collector.set_federation_clusters(2, 1)
    collector.set_federation_cluster_health("us-east1", True)
    collector.record_federation_transition("us-east1", "cluster-join")
    collector.record_federation_request("us-east1", "run")
    collector.record_federation_refusal("tenant-a", "no_capable_cluster")
    collector.record_federation_route("us-east1", "capability")
    collector.set_federation_goodput(0.97)
    # sharding families
    collector.set_shard_owned(0, True)
    collector.set_shard_checks(0, 3)
    collector.record_shard_handoff(0, "acquired")
    collector.record_fenced_write(0)
    collector.cadence_goodput.set(1.0)
    collector.set_fleet_goodput(1.0)
    # goodput attribution families
    collector.set_goodput_attribution({"ici": 0.0, "unknown": 0.0}, None)
    collector.record_phase_timing_skipped("bad_value")
    collector.set_slo(
        "hc-a",
        "health",
        availability=0.9,
        error_budget_remaining=0.5,
        burn_rate=0.5,
    )
    contract = (
        '{"metrics": [], "timings": {"p": 1.0}, "roofline": {"mxu": '
        '{"bound": "compute", "intensity": 2048.0, "fraction": 0.9, '
        '"ceiling_flops": 1.97e14, "achieved_flops": 1.77e14, '
        '"ridge": 240.5, "cost_source": "xla", "flops": 1.0e11, '
        '"hbm_bytes": 5.0e7, "hbm_peak_bytes": 2.0e9}}, '
        # disaggregated-serving block (ISSUE 20): the probe's
        # serving_disagg details verbatim — prefix-cache traffic, the
        # migration channel's per-tier bytes, the acceptance fraction
        # and both topologies' TTFT p99
        '"serving_disagg": {"prefix_counters": {"hits": 4, "misses": '
        '30, "inserted": 23, "evictions": 14}, "migration_by_tier": '
        '{"ici": {"transfers": 10, "bytes": 69632.0, "hops": 10}}, '
        '"spec_acceptance": 0.09, "disagg_ttft_p99_ms": 131.9, '
        '"colocated_ttft_p99_ms": 165.2}}'
    )
    collector.record_custom_metrics(
        "hc-a",
        {"outputs": {"parameters": [{"name": "m", "value": contract}]}},
    )
    # scenario-matrix families (ISSUE 12): one round summary with a
    # non-ok verdict (materializes the lazy state trio), a roofline
    # stamp, a skipped cell, and a bisect record
    collector.record_matrix_round(
        {
            "cells": {
                "flash/1chip/bf16": {
                    "status": "ok",
                    "metric": "seconds",
                    "value": 0.004,
                    "verdict": "degraded",
                    "roofline": {"bound": "compute", "fraction": 0.4},
                },
                "decode/1chip/bf16": {
                    "status": "skipped",
                    "reason": "unsupported-dtype: decode is float32-only",
                },
            },
            "bisects": [{"cell": "flash/1chip/bf16", "outcome": "reproduced"}],
        }
    )


def test_check_state_series_are_lazy_for_healthy_checks(collector):
    """Cardinality contract: a check that never leaves healthy carries
    NO state series (absence = healthy); once degraded, the one-hot
    trio persists so the recovery transition is visible; deletion
    drops it (and re-arms the laziness)."""
    labels = lambda state: {  # noqa: E731 - tiny local shorthand
        "healthcheck_name": "hc-a",
        "namespace": "health",
        "state": state,
    }
    collector.set_check_state("hc-a", "health", "Healthy")
    for state in ("healthy", "flapping", "quarantined"):
        assert collector.sample_value("healthcheck_check_state", labels(state)) is None
    collector.set_check_state("hc-a", "health", "Flapping")
    assert collector.sample_value("healthcheck_check_state", labels("flapping")) == 1.0
    assert collector.sample_value("healthcheck_check_state", labels("healthy")) == 0.0
    collector.set_check_state("hc-a", "health", "Healthy")
    assert collector.sample_value("healthcheck_check_state", labels("healthy")) == 1.0
    assert collector.sample_value("healthcheck_check_state", labels("flapping")) == 0.0
    collector.clear_check_state("hc-a", "health")
    for state in ("healthy", "flapping", "quarantined"):
        assert collector.sample_value("healthcheck_check_state", labels(state)) is None
    collector.set_check_state("hc-a", "health", "Healthy")
    assert collector.sample_value("healthcheck_check_state", labels("healthy")) is None


def test_every_pinned_family_appears_in_the_scrape(collector):
    """The pinned table and the scrape text agree: every declared
    family yields samples under its declared name (counters keep their
    declared `_total`; histograms expose `_bucket`)."""
    exercise_every_family(collector)
    lines = collector.exposition().decode().splitlines()

    def scraped(prefix):
        return any(line.startswith(prefix) for line in lines)

    for name, kind in PINNED_FAMILIES.items():
        if kind == "histogram":
            assert scraped(name + "_bucket{"), f"{name} missing from scrape"
        else:
            # labeled or unlabeled sample, exact declared name
            assert scraped(name + "{") or scraped(name + " "), (
                f"{name} missing from scrape"
            )


def test_scrape_text_pins_reference_names_without_total_suffix(collector):
    """The exposition contract, asserted on the scrape text itself:
    prometheus_client appends `_total` to Counter samples, so the two
    reference counters are deliberately Gauges (collector.py) — this
    test is the tripwire that keeps that workaround from regressing."""
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 1, 2)
    lines = collector.exposition().decode().splitlines()
    for name in REFERENCE_SCRAPE_NAMES:
        assert any(
            line.startswith(name + "{") for line in lines
        ), f"reference metric {name} missing from scrape"
        assert not any(
            line.startswith(name + "_total{") for line in lines
        ), f"{name} grew a _total suffix — scrape contract broken"


def test_scrape_text_exposes_controller_runtime_parity_families(collector):
    collector.record_reconcile("success", 0.25)
    collector.record_queue_add(1)
    collector.record_queue_get(0, 0.05)
    collector.record_work_duration(0.2)
    collector.set_active_workers(1)
    collector.set_max_concurrent(10)
    collector.record_engine_submit("fake")
    collector.record_engine_poll("fake")
    collector.record_watch_restart("health")
    lines = collector.exposition().decode().splitlines()

    def sample(prefix):
        return any(line.startswith(prefix) for line in lines)

    assert sample('controller_runtime_reconcile_total{controller="healthcheck",result="success"}')
    assert sample("controller_runtime_reconcile_time_seconds_bucket{")
    assert sample("controller_runtime_reconcile_time_seconds_count{")
    assert sample('controller_runtime_active_workers{controller="healthcheck"}')
    assert sample("controller_runtime_max_concurrent_reconciles{")
    assert sample('workqueue_depth{name="healthcheck"}')
    assert sample('workqueue_adds_total{name="healthcheck"}')
    assert sample("workqueue_queue_duration_seconds_bucket{")
    assert sample("workqueue_work_duration_seconds_bucket{")
    assert sample('engine_submit_total{engine="fake"}')
    assert sample('engine_poll_total{engine="fake"}')
    assert sample('workflow_watch_restarts_total{namespace="health"}')


def test_reconcile_and_queue_recorders_accumulate(collector):
    collector.record_reconcile("success", 0.5)
    collector.record_reconcile("success", 1.5)
    collector.record_reconcile("error", 0.1)
    assert (
        collector.sample_value(
            "controller_runtime_reconcile_total",
            {"controller": "healthcheck", "result": "success"},
        )
        == 2
    )
    assert (
        collector.sample_value(
            "controller_runtime_reconcile_time_seconds_sum",
            {"controller": "healthcheck"},
        )
        == 2.1
    )
    collector.record_queue_add(3)
    assert collector.sample_value("workqueue_depth", {"name": "healthcheck"}) == 3
    collector.record_queue_get(2, 0.25)
    assert collector.sample_value("workqueue_depth", {"name": "healthcheck"}) == 2
    assert (
        collector.sample_value(
            "workqueue_queue_duration_seconds_sum", {"name": "healthcheck"}
        )
        == 0.25
    )
    # negative wait (clock skew) is clamped, never raises
    collector.record_queue_get(1, -5.0)
    assert (
        collector.sample_value(
            "workqueue_queue_duration_seconds_sum", {"name": "healthcheck"}
        )
        == 0.25
    )


def custom_status(*entries, timings=None):
    import json as _json

    doc = {"metrics": list(entries)}
    if timings is not None:
        doc["timings"] = timings
    return {
        "outputs": {"parameters": [{"name": "m", "value": _json.dumps(doc)}]}
    }


def test_custom_counter_metrictype_is_honored(collector):
    """metrictype=counter increments a real Counter (per-run delta ->
    monotonic total) instead of being coerced into a settable gauge."""
    entry = {"name": "probe-errors", "value": 2, "metrictype": "counter"}
    assert collector.record_custom_metrics("hc", custom_status(entry)) == 1
    entry["value"] = 3
    assert collector.record_custom_metrics("hc", custom_status(entry)) == 1
    assert (
        collector.sample_value(
            "hc_probe_errors_total", {"healthcheck_name": "hc"}
        )
        == 5
    )
    # the scrape shows counter semantics: _total suffix + TYPE counter
    text = collector.exposition().decode()
    assert 'hc_probe_errors_total{healthcheck_name="hc"} 5.0' in text
    assert "# TYPE hc_probe_errors_total counter" in text


def test_unknown_metrictype_is_rejected_not_coerced(collector, caplog):
    import logging as _logging

    entry = {"name": "bw", "value": 1.0, "metrictype": "summary"}
    with caplog.at_level(_logging.WARNING):
        assert collector.record_custom_metrics("hc", custom_status(entry)) == 0
    assert collector.sample_value("hc_bw", {"healthcheck_name": "hc"}) is None
    assert any("unknown metrictype" in r.message for r in caplog.records)


def test_custom_metric_type_conflict_is_skipped(collector):
    gauge = {"name": "bw", "value": 1.0, "metrictype": "gauge"}
    assert collector.record_custom_metrics("hc", custom_status(gauge)) == 1
    retyped = {"name": "bw", "value": 2.0, "metrictype": "counter"}
    assert collector.record_custom_metrics("hc", custom_status(retyped)) == 0
    assert collector.sample_value("hc_bw", {"healthcheck_name": "hc"}) == 1.0


def test_negative_counter_increment_is_skipped(collector):
    entry = {"name": "errs", "value": -1, "metrictype": "counter"}
    assert collector.record_custom_metrics("hc", custom_status(entry)) == 0


def test_same_run_id_records_custom_metrics_exactly_once(collector):
    """Regression (ISSUE 4 satellite): the reconciler can reach one
    run's terminal status through more than one path (live poll AND a
    replayed/requeued status) — counter metrics are per-run increments,
    so a second recording keyed by the same workflow run id must be a
    no-op, while a NEW run id records normally."""
    entry = {"name": "probe-errors", "value": 2, "metrictype": "counter"}
    status = custom_status(entry, timings={"p": 1.0})
    labels = {"healthcheck_name": "hc"}
    assert collector.record_custom_metrics("hc", status, run_id="wf-1") == 1
    # the duplicate path replays the same run: nothing recorded
    assert collector.record_custom_metrics("hc", status, run_id="wf-1") == 0
    assert collector.sample_value("hc_probe_errors_total", labels) == 2
    # the timings block is deduped on the same key
    assert (
        collector.sample_value(
            "healthcheck_phase_seconds_count",
            {"healthcheck_name": "hc", "phase": "p"},
        )
        == 1
    )
    # the next run increments again; no run id keeps legacy semantics
    assert collector.record_custom_metrics("hc", status, run_id="wf-2") == 1
    assert collector.record_custom_metrics("hc", status) == 1
    assert collector.sample_value("hc_probe_errors_total", labels) == 6
    # same run id under a DIFFERENT check is a different run
    assert collector.record_custom_metrics("hc2", status, run_id="wf-1") == 1


def test_recorded_run_memory_is_bounded(collector):
    cap = collector.RECORDED_RUN_CAPACITY
    status = custom_status({"name": "v", "value": 1.0})
    for i in range(cap + 50):
        collector.record_custom_metrics("hc", status, run_id=f"wf-{i}")
    assert len(collector._recorded_runs) == cap
    # the oldest ids were evicted, so (only) they would record again
    assert collector.record_custom_metrics("hc", status, run_id="wf-0") == 1
    assert (
        collector.record_custom_metrics("hc", status, run_id=f"wf-{cap + 49}")
        == 0
    )


def test_parse_custom_samples_reads_without_recording(collector):
    status = custom_status(
        {"name": "bw-gbps", "value": 123.5},
        {"name": "errs", "value": 2, "metrictype": "counter"},
        {"name": "bad", "value": "not-a-number"},
    )
    samples = MetricsCollector.parse_custom_samples(status)
    assert samples == {"bw-gbps": 123.5, "errs": 2.0}
    # pure read: nothing landed in the registry
    assert collector.sample_value("hc_bw_gbps", {"healthcheck_name": "hc"}) is None
    assert MetricsCollector.parse_custom_samples({}) == {}
    assert MetricsCollector.parse_custom_samples({"outputs": None}) == {}


def test_malformed_timings_entries_are_skipped(collector):
    status = custom_status(
        timings={"good": 2.0, "bad": "NaN-ish", "": 1.0}
    )
    collector.record_custom_metrics("hc", status)
    assert (
        collector.sample_value(
            "healthcheck_phase_seconds_sum",
            {"healthcheck_name": "hc", "phase": "good"},
        )
        == 2.0
    )
    assert (
        collector.sample_value(
            "healthcheck_phase_seconds_count",
            {"healthcheck_name": "hc", "phase": "bad"},
        )
        is None
    )
    # a non-object timings block is ignored wholesale, never raised
    bad = {"outputs": {"parameters": [{"name": "m", "value": '{"metrics": [], "timings": [1, 2]}'}]}}
    assert collector.record_custom_metrics("hc", bad) == 0
    # the drops are COUNTED per reason (ISSUE 7 satellite): contract
    # drift between probe and controller versions must be visible on
    # /metrics, not only as a log warning
    skipped = lambda reason: collector.sample_value(  # noqa: E731
        "healthcheck_phase_timings_skipped_total", {"reason": reason}
    )
    assert skipped("bad_value") == 1.0
    assert skipped("unnamed") == 1.0
    assert skipped("not_object") == 1.0


def test_parse_phase_timings_reads_without_recording(collector):
    """The pure timings reader (feeds the result ring + attribution):
    same skip policy as the recording path, zero registry effects."""
    status = custom_status(timings={"compile": 30.0, "bad": "x", "": 1.0})
    timings = MetricsCollector.parse_phase_timings(status)
    assert timings == {"compile": 30.0}
    assert (
        collector.sample_value(
            "healthcheck_phase_seconds_count",
            {"healthcheck_name": "hc", "phase": "compile"},
        )
        is None
    )
    assert (
        collector.sample_value(
            "healthcheck_phase_timings_skipped_total", {"reason": "bad_value"}
        )
        is None
    )
    assert MetricsCollector.parse_phase_timings({}) == {}


def test_goodput_attribution_info_series_follows_the_top_bucket(collector):
    """The info series is one-hot on (version, top): a change of the
    dominant bucket drops the stale series rather than leaving two 1s
    on the scrape."""
    labels = lambda top: {"version": "1", "top": top}  # noqa: E731
    collector.set_goodput_attribution({"ici": 0.25, "hbm": 0.0}, "ici")
    assert (
        collector.sample_value("healthcheck_goodput_lost_ratio", {"subsystem": "ici"})
        == 0.25
    )
    assert collector.sample_value(
        "healthcheck_goodput_attribution_info", labels("ici")
    ) == 1.0
    collector.set_goodput_attribution({"ici": 0.0, "hbm": 0.1}, "hbm")
    assert collector.sample_value(
        "healthcheck_goodput_attribution_info", labels("ici")
    ) is None
    assert collector.sample_value(
        "healthcheck_goodput_attribution_info", labels("hbm")
    ) == 1.0
    # nothing lost: the top label reads "none"
    collector.set_goodput_attribution({"ici": 0.0, "hbm": 0.0}, None)
    assert collector.sample_value(
        "healthcheck_goodput_attribution_info", labels("none")
    ) == 1.0


def test_runtime_buckets_are_log_spaced_and_cover_multi_minute_probes(collector):
    """The satellite fix: the default client buckets cap at 10 s; TPU
    probe workflows run minutes. Boundaries pinned here."""
    from activemonitor_tpu.metrics.collector import _PROBE_RUNTIME_BUCKETS

    finite = [b for b in _PROBE_RUNTIME_BUCKETS if b != float("inf")]
    assert _PROBE_RUNTIME_BUCKETS[-1] == float("inf")
    assert finite[0] <= 1
    assert finite[-1] >= 1800  # 30 minutes of resolution
    assert finite == sorted(finite)
    # log-spaced: adjacent boundaries grow by a bounded factor, so
    # resolution neither collapses nor explodes anywhere in the range
    ratios = [b / a for a, b in zip(finite, finite[1:])]
    assert all(1.5 <= r <= 5.0 for r in ratios), ratios
    # a 10-minute run lands in a real bucket, not +Inf
    collector.record_success("hc", WORKFLOW_LABEL_HEALTHCHECK, 0, 600)
    assert (
        collector.sample_value(
            "healthcheck_runtime_histogram_seconds_bucket",
            {**labels("hc"), "le": "900.0"},
        )
        == 1
    )
    assert (
        collector.sample_value(
            "healthcheck_runtime_histogram_seconds_bucket",
            {**labels("hc"), "le": "300.0"},
        )
        == 0
    )
    # the phase histogram shares the probe-scale buckets
    assert collector.phase_seconds._kwargs["buckets"] == _PROBE_RUNTIME_BUCKETS


def test_openmetrics_exposition_carries_exemplars(collector):
    """Exemplars render only in the OpenMetrics format; the default
    text format (the reference scrape contract) stays exemplar-free."""
    from activemonitor_tpu.obs import Tracer
    from activemonitor_tpu.utils.clock import FakeClock

    tracer = Tracer(FakeClock())
    with tracer.span("poll") as span:
        collector.record_success("hc", WORKFLOW_LABEL_HEALTHCHECK, 0, 7)
    om_text = collector.exposition(openmetrics=True).decode()
    assert f'# {{trace_id="{span.trace_id}"}}' in om_text
    assert "trace_id" not in collector.exposition().decode()
    assert "openmetrics-text" in collector.OPENMETRICS_CONTENT_TYPE


def test_two_collectors_do_not_share_registries():
    # the reference's global registry caused a documented race
    # (collector_test.go:82-88); per-instance registries avoid it
    a = MetricsCollector()
    b = MetricsCollector()
    a.record_success("hc", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    assert b.sample_value("healthcheck_success_count", labels("hc")) is None
