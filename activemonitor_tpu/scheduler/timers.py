"""Named timer wheel for self-rescheduled runs.

The reference keeps ``map[string]*time.Timer`` guarded by a RWMutex and
reschedules each check via ``time.AfterFunc``
(reference: healthcheck_controller.go:139-141,745-754). Here each timer
is an asyncio task owned by the wheel — single-owner state on one event
loop, so no lock is needed (SURVEY.md §5.2's discipline: scheduler state
in a single-owner task instead of a shared map).

Entries stay in the map after firing, so ``exists(name)`` means "this
check has been scheduled at least once", not "a run is pending". The
reconciler's dedupe deliberately uses ``pending(name)`` (a live, unfired
timer): trusting a fired-but-bailed entry would wedge a check's schedule
forever. ``exists`` remains for delete-time bookkeeping and tests.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict

from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger(__name__)


class TimerWheel:
    def __init__(self, clock: Clock | None = None):
        self._clock = clock or Clock()
        self._timers: Dict[str, asyncio.Task] = {}
        # monotonic fire deadline per PENDING timer — the serializable
        # owed-run state a shard handoff carries to the adopting owner
        self._deadlines: Dict[str, float] = {}

    def schedule(
        self, name: str, delay_seconds: float, fn: Callable[[], Awaitable[None]]
    ) -> None:
        """(Re)schedule ``fn`` to run after ``delay_seconds``.

        Any pending timer with the same name is stopped first
        (reference: healthcheck_controller.go:747-752).
        """
        self.stop(name)
        self._deadlines[name] = self._clock.monotonic() + max(0.0, delay_seconds)
        self._timers[name] = asyncio.create_task(
            self._fire(name, delay_seconds, fn), name=f"timer:{name}"
        )

    async def _fire(
        self, name: str, delay_seconds: float, fn: Callable[[], Awaitable[None]]
    ) -> None:
        from activemonitor_tpu.obs.trace import detached

        try:
            await self._clock.sleep(delay_seconds)
            # consumed: the entry stays (exists semantics) but no run is
            # pending anymore, so the deadline must not outlive it.
            # Identity-guarded: if this task was REPLACED while asleep
            # (schedule() raced its wake-up), the deadline now belongs
            # to the replacement and must survive
            if self._timers.get(name) is asyncio.current_task():
                self._deadlines.pop(name, None)
            # the timer task's context snapshot was taken when the timer
            # was ARMED (usually inside the previous cycle's trace) —
            # fire trace-clean so the callback's spans never adopt into
            # a long-finished trace
            with detached():
                await fn()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("timer %s callback failed", name)

    def exists(self, name: str) -> bool:
        """True if the check has ever been scheduled (fired entries remain)."""
        return name in self._timers

    def pending(self, name: str) -> bool:
        """True only while a run is still queued (not yet fired/cancelled)."""
        t = self._timers.get(name)
        return t is not None and not t.done()

    def names(self) -> list:
        """Every known timer name (fired entries included) — shard
        handoff iterates this to release a dead shard's schedules."""
        return list(self._timers)

    def remaining(self, name: str) -> float | None:
        """Seconds until a PENDING timer fires (None when nothing is
        pending): the owed-run state a handoff serializes."""
        if not self.pending(name):
            return None
        deadline = self._deadlines.get(name)
        if deadline is None:
            return None
        return max(0.0, deadline - self._clock.monotonic())

    def snapshot(self) -> Dict[str, float]:
        """``{name: seconds until fire}`` for every pending timer — the
        portable form of this wheel's owed-run state, for IN-PROCESS
        wheel migrations (and the handoff contract tests). Cross-process
        shard handoff deliberately does not ship snapshots: the adopting
        owner rebuilds from durable status (reconciler divergence 10),
        and fired/cancelled entries are absent here for the same reason
        — no pending run, nothing to carry."""
        out: Dict[str, float] = {}
        for name in self._timers:
            left = self.remaining(name)
            if left is not None:
                out[name] = left
        return out

    def restore(
        self,
        snapshot: Dict[str, float],
        fn_factory: Callable[[str], Callable[[], Awaitable[None]]],
    ) -> int:
        """Rebuild pending timers from a :meth:`snapshot` — the adopted
        shard's owed runs fire at their original deadlines on the new
        owner's wheel (no dropped, no duplicated runs: each restored
        name replaces any same-named pending entry). Returns how many
        timers were restored."""
        for name, left in snapshot.items():
            self.schedule(name, max(0.0, left), fn_factory(name))
        return len(snapshot)

    def stop(self, name: str) -> bool:
        """Cancel a pending run if any; keeps no map entry. Returns True
        if a pending timer was actually cancelled. A timer task stopping
        itself from within its own callback (the reschedule-at-watch-end
        path) is popped but never cancelled mid-flight."""
        t = self._timers.pop(name, None)
        self._deadlines.pop(name, None)
        if t is None:
            return False
        if not t.done() and t is not asyncio.current_task():
            t.cancel()
            return True
        return False

    async def shutdown(self) -> None:
        names = list(self._timers)
        for name in names:
            self.stop(name)
        await asyncio.sleep(0)
