"""Argo workflow engine — real Workflow CRs via the Kubernetes API.

Capability-parity backend for cluster deployments
(reference: healthcheck_controller.go:502-534 create, :617 dynamic-client
poll). Import of the ``kubernetes`` package is deferred to construction
so the rest of the framework works where it isn't installed.
"""

from __future__ import annotations

from typing import Optional

from activemonitor_tpu.errors import MissingDependencyError

WF_GROUP = "argoproj.io"
WF_VERSION = "v1alpha1"
WF_PLURAL = "workflows"


def _is_api_not_found(e: Exception, stub_mode: bool) -> bool:
    """True only for a genuine API-server 404. In real-client mode the
    type check is strict (an arbitrary exception carrying status=404
    must not masquerade as not-found); injected test stubs get the
    duck-typed check regardless of what packages are installed."""
    if stub_mode:
        return getattr(e, "status", None) == 404
    from kubernetes.client.rest import ApiException  # type: ignore

    return isinstance(e, ApiException) and e.status == 404


class ArgoWorkflowEngine:
    def __init__(self, api_client=None, custom_objects_api=None):
        """``custom_objects_api`` lets tests inject a stub implementing
        the CustomObjectsApi surface; otherwise the real client is
        constructed from in-cluster/kubeconfig credentials."""
        self._stub_mode = custom_objects_api is not None
        if custom_objects_api is not None:
            self._api = custom_objects_api
            return
        try:
            from kubernetes import client, config  # type: ignore
        except ImportError as e:  # pragma: no cover - depends on environment
            raise MissingDependencyError(
                "the 'kubernetes' package is required for ArgoWorkflowEngine; "
                "use LocalProcessEngine or FakeWorkflowEngine instead"
            ) from e
        if api_client is None:  # pragma: no cover - needs a cluster
            try:
                config.load_incluster_config()
            except Exception:
                config.load_kube_config()
        self._api = client.CustomObjectsApi(api_client)

    async def submit(self, manifest: dict) -> str:
        import asyncio

        namespace = manifest.get("metadata", {}).get("namespace", "default")
        created = await asyncio.to_thread(
            self._api.create_namespaced_custom_object,
            WF_GROUP,
            WF_VERSION,
            namespace,
            WF_PLURAL,
            manifest,
        )
        return created["metadata"]["name"]

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        import asyncio

        try:
            return await asyncio.to_thread(
                self._api.get_namespaced_custom_object,
                WF_GROUP,
                WF_VERSION,
                namespace,
                WF_PLURAL,
                name,
            )
        except Exception as e:
            if _is_api_not_found(e, self._stub_mode):
                return None
            raise
