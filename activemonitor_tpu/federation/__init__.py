"""Planet-scale federation: the multi-cluster control plane.

ROADMAP item 2 grown to its fleet-of-fleets form: every surface in
this repo — sharded scheduling, attribution-conserving rollups, the
probe-as-a-service front door — stops at one cluster, while the ML
Productivity Goodput paper (PAPERS.md) frames measurement fleet-wide
and Maple argues the control plane must be portable across
heterogeneous clusters (v5e vs v5p) the way the data plane already is
after the DCN×ICI collectives. This package is that control plane:

- :mod:`registry` — per-cluster capability descriptors (derived from
  the ``probes/rated.py`` rated tables) with health judged by observed
  ``/statusz`` movement, the same locally-observed-liveness discipline
  as sharding's member leases.
- :mod:`routing` — capability-aware routing: a check lands on the
  cluster owning its target slice or best matching its declared
  requirements (generation, mesh shape, dcn tier), with a structured
  ``no_capable_cluster`` refusal otherwise.
- :mod:`rollup` — the federated rollup: ``obs/slo.rollup_statusz``
  generalized from replicas to clusters (two-level merge, run-weighted
  goodput, attribution conservation preserved exactly; an old-binary
  cluster folds its lost share into ``unknown``).
- :mod:`globaldoor` — one submit surface in front of the per-cluster
  front doors: coalescing works ACROSS clusters (N tenants asking
  about one pod share one run and one trace id), per-tenant quota is
  enforced once globally, and the conservation ledger
  ``submitted == hits + joins + runs + parked + refused + forwarded``
  is exact per tenant per cluster and sums at the federation level.
- :mod:`plane` — the manager-facing façade wiring the pieces into the
  ``/statusz`` ``federation`` block, the pinned
  ``healthcheck_federation_*`` families, and the flight recorder.

Everything timed runs on the injectable Clock; ``hack/lint.py`` bans
bare wall-clock reads in this package like ``frontdoor/`` and
``resilience/``.
"""

from activemonitor_tpu.federation.globaldoor import (  # noqa: F401
    FEDERATION_TENANT,
    OUTCOME_FORWARDED,
    GlobalFrontDoor,
    GlobalTicket,
    federation_quota,
)
from activemonitor_tpu.federation.plane import FederationPlane  # noqa: F401
from activemonitor_tpu.federation.registry import (  # noqa: F401
    STATE_HEALTHY,
    STATE_UNHEALTHY,
    ClusterDescriptor,
    ClusterRegistry,
)
from activemonitor_tpu.federation.rollup import federate_statusz  # noqa: F401
from activemonitor_tpu.federation.routing import (  # noqa: F401
    NO_CAPABLE_CLUSTER,
    CapabilityRouter,
    Requirement,
    RouteDecision,
)
