"""ICI all-reduce bandwidth probe — the north-star check.

Measures achieved all-reduce bus bandwidth over the chip mesh and
compares against the rated ICI link bandwidth (BASELINE.md: ≥90 % of
rated on a GKE v5e-8). Exports:

- ``ici-allreduce-busbw-gbps`` — measured bus bandwidth (NCCL convention)
- ``ici-allreduce-fraction-of-rated`` — measured / rated
- ``ici-ring-hop-gbps`` — single-hop ppermute bandwidth (one direction)
- ``ici-ring-hop-bidir-gbps`` — bidirectional hop (halves permuted
  clockwise/counter-clockwise at once — the ring-attention
  ``variant="bidir"`` wire pattern)
- ``ici-ring-hop-fraction-of-rated`` / ``ici-ring-hop-bidir-fraction-of-rated``
  — each hop flavor against its link-model ceiling (1x unidir for the
  single direction, 2x unidir full-duplex for bidirectional), the same
  model behind the all-reduce comparator below

With ``schedules=(...)`` (zoo tokens from parallel/schedules.py:
"rsag", "recdouble", "tree") the probe also measures each explicit
all-reduce schedule and exports, per schedule:

- ``ici-allreduce-<sched>-busbw-gbps``
- ``ici-allreduce-<sched>-fraction-of-rated`` — against that
  schedule's OWN transfer-volume ceiling
  (probes/collectives._rated_busbw), so a latency-optimal schedule
  sitting at its low bandwidth ceiling reads healthy while the same
  busbw from the XLA ring would read as a sick link.
"""

from __future__ import annotations

from typing import Sequence

import jax

from activemonitor_tpu.parallel.collectives import (
    all_reduce_bandwidth,
    ppermute_bidir_bandwidth,
    ppermute_ring_bandwidth,
)
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.parallel.schedules import (
    all_reduce_recdouble_bandwidth,
    all_reduce_rsag_bandwidth,
    all_reduce_tree_bandwidth,
)
from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for

# zoo-schedule gauge names, declared (not f-string-built) so the
# contract-spelling gates (tests/test_lint) see them as constants
_SCHEDULE_GAUGES = {
    "rsag": (
        "ici-allreduce-rsag-busbw-gbps",
        "ici-allreduce-rsag-fraction-of-rated",
        all_reduce_rsag_bandwidth,
    ),
    "recdouble": (
        "ici-allreduce-recdouble-busbw-gbps",
        "ici-allreduce-recdouble-fraction-of-rated",
        all_reduce_recdouble_bandwidth,
    ),
    "tree": (
        "ici-allreduce-tree-busbw-gbps",
        "ici-allreduce-tree-fraction-of-rated",
        all_reduce_tree_bandwidth,
    ),
}


def run(
    size_mb: float = 64.0,
    iters: int = 10,
    threshold: float = 0.9,
    include_ring: bool = True,
    schedules: Sequence[str] = (),
    roofline: bool = True,
) -> ProbeResult:
    unknown = [s for s in schedules if s not in _SCHEDULE_GAUGES]
    if unknown:
        raise ValueError(
            f"unknown all-reduce schedules {unknown}; pick from "
            f"{tuple(_SCHEDULE_GAUGES)}"
        )
    devices = jax.devices()
    n = len(devices)
    mesh = make_1d_mesh()
    result = all_reduce_bandwidth(mesh, size_mb=size_mb, iters=iters)
    rated = rated_for(devices[0].device_kind)

    metrics = [
        ProbeMetric(
            "ici-allreduce-busbw-gbps",
            result.busbw_gbps,
            help="Measured all-reduce bus bandwidth (NCCL busbw convention), GB/s",
        ),
        ProbeMetric(
            "ici-allreduce-algbw-gbps",
            result.algbw_gbps,
            help="Measured all-reduce algorithm bandwidth, GB/s",
        ),
    ]
    details = {
        "devices": n,
        "device_kind": devices[0].device_kind,
        "payload_mb": result.payload_bytes / 1e6,
        "seconds_per_op": result.seconds_per_op,
        "busbw_gbps": round(result.busbw_gbps, 2),
    }

    sched_results = {}
    if n > 1:
        for sched in schedules:
            bw_name, _frac_name, bench = _SCHEDULE_GAUGES[sched]
            res = bench(mesh, size_mb=size_mb, iters=iters)
            sched_results[sched] = res
            metrics.append(
                ProbeMetric(
                    bw_name,
                    res.busbw_gbps,
                    help=f"all-reduce via the explicit {sched} schedule "
                    "(parallel/schedules.py), busbw GB/s",
                )
            )
            details[f"allreduce_{sched}_busbw_gbps"] = round(res.busbw_gbps, 2)

    ring = ring_bidir = None
    if include_ring and n > 1:
        ring = ppermute_ring_bandwidth(mesh, size_mb=size_mb, iters=iters)
        metrics.append(
            ProbeMetric(
                "ici-ring-hop-gbps",
                ring.algbw_gbps,
                help="Single-hop ppermute (ring neighbor shift) bandwidth, GB/s",
            )
        )
        details["ring_hop_gbps"] = round(ring.algbw_gbps, 2)
        ring_bidir = ppermute_bidir_bandwidth(mesh, size_mb=size_mb, iters=iters)
        metrics.append(
            ProbeMetric(
                "ici-ring-hop-bidir-gbps",
                ring_bidir.algbw_gbps,
                help="Bidirectional ring hop (cw+ccw halves per round) "
                "bandwidth, GB/s",
            )
        )
        details["ring_hop_bidir_gbps"] = round(ring_bidir.algbw_gbps, 2)

    ok = True
    if rated is not None and n > 1 and devices[0].platform == "tpu":
        # rated comparator: a 1D ring all-reduce is limited by one
        # bidirectional link pair per hop ⇒ 2 × unidirectional link bw
        rated_busbw = 2 * rated.ici_unidir_gbps
        fraction = result.busbw_gbps / rated_busbw
        metrics.append(
            ProbeMetric(
                "ici-allreduce-fraction-of-rated",
                fraction,
                help="Measured busbw / rated ring bandwidth (target ≥ 0.9)",
            )
        )
        details["rated_busbw_gbps"] = rated_busbw
        details["fraction_of_rated"] = round(fraction, 3)
        if ring is not None:
            # the hop flavors against the same link model: one direction
            # of one link, and both directions of one link (full duplex)
            metrics.append(
                ProbeMetric(
                    "ici-ring-hop-fraction-of-rated",
                    ring.algbw_gbps / rated.ici_unidir_gbps,
                    help="Single-hop bandwidth / rated unidirectional link",
                )
            )
            metrics.append(
                ProbeMetric(
                    "ici-ring-hop-bidir-fraction-of-rated",
                    ring_bidir.algbw_gbps / rated_busbw,
                    help="Bidirectional-hop bandwidth / 2x rated link "
                    "(full-duplex ceiling)",
                )
            )
            details["ring_hop_fraction_of_rated"] = round(
                ring.algbw_gbps / rated.ici_unidir_gbps, 3
            )
            details["ring_hop_bidir_fraction_of_rated"] = round(
                ring_bidir.algbw_gbps / rated_busbw, 3
            )
        if sched_results:
            # each zoo schedule against its OWN transfer-volume ceiling
            # (probes/collectives._rated_busbw): a schedule losing to
            # its algorithm is not a slow link
            from activemonitor_tpu.probes.collectives import (
                _rated_busbw as _schedule_ceiling,
            )

            for sched, res in sched_results.items():
                _bw_name, frac_name, _bench = _SCHEDULE_GAUGES[sched]
                ceiling = _schedule_ceiling(
                    f"allreduce-{sched}", rated.ici_unidir_gbps, n
                )
                metrics.append(
                    ProbeMetric(
                        frac_name,
                        res.busbw_gbps / ceiling,
                        help=f"{sched} busbw / its own schedule ceiling "
                        f"({ceiling:.0f} GB/s here) — informational, "
                        "not part of the north-star verdict",
                    )
                )
                details[f"allreduce_{sched}_fraction_of_rated"] = round(
                    res.busbw_gbps / ceiling, 3
                )
        ok = fraction >= threshold
        summary = (
            f"all-reduce busbw {result.busbw_gbps:.1f} GB/s = "
            f"{fraction:.0%} of rated {rated_busbw:.0f} GB/s over {n}x {rated.generation}"
        )
        ceiling = rated_busbw
    else:
        summary = (
            f"all-reduce busbw {result.busbw_gbps:.1f} GB/s over {n} device(s)"
            " (no rated comparison: single device or unknown hardware)"
        )
        ceiling = None
    # ICI-roofline verdict under the north-star fraction
    # (obs/roofline.py): comm-bound by construction, the ceiling is the
    # same 2x-unidir ring model the fraction already divides by — so
    # attribution/why lines can cite "0.41 of comm-bound ceiling"
    # instead of a bare fraction. The intensity is the all-reduce's one
    # add per wire byte.
    probe_result = ProbeResult(
        ok=ok, summary=summary, metrics=metrics, details=details
    )
    roofline_model.apply(
        probe_result,
        roofline_model.comm_capture(
            "ici-allreduce",
            busbw_gbps=result.busbw_gbps,
            rated_busbw_gbps=ceiling,
            payload_bytes=float(result.payload_bytes),
            flops=float(result.payload_bytes) / 2.0,  # bf16: one add/elem
            enabled=roofline,
        ),
    )
    return probe_result
