"""Cluster credential discovery.

Mirrors client-go's loading rules in the order the reference relies on
(reference: cmd/main.go:70 ``ctrl.GetConfigOrDie`` — in-cluster service
account first, kubeconfig otherwise): the mounted service-account
token/CA when running in a pod, else the file named by ``$KUBECONFIG``,
else ``~/.kube/config``.
"""

from __future__ import annotations

import base64
import os
import ssl
import tempfile
from dataclasses import dataclass, field
from typing import Optional

import yaml

from activemonitor_tpu.errors import MissingDependencyError

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeConfigError(MissingDependencyError):
    """No usable cluster credentials were found."""


@dataclass
class KubeConfig:
    server: str  # e.g. https://10.0.0.1:443 or http://127.0.0.1:8001
    token: str = ""
    # bound service-account tokens rotate (~1h); when set, the token is
    # re-read from this file with a short TTL instead of cached forever
    # (client-go re-reads per request for the same reason)
    token_file: str = ""
    ca_data: bytes = b""  # PEM; empty means system trust store
    client_cert_data: bytes = b""  # PEM pair for mTLS kubeconfigs
    client_key_data: bytes = b""
    verify_tls: bool = True
    namespace: str = "default"
    # kubeconfig user.exec credential plugin (gke-gcloud-auth-plugin,
    # aws eks get-token, ...): run on demand, cached until expiry
    exec_spec: Optional[dict] = None
    _tempfiles: list = field(default_factory=list, repr=False)
    _file_token: object = field(default=None, repr=False)
    _exec_valid_until: float = field(default=0.0, repr=False)

    def cached_token(self) -> Optional[str]:
        """The token WITHOUT any refresh, or None when a (potentially
        slow, blocking) refresh is needed — the async client's lock-free
        fast path. Owns the freshness rule so callers never touch the
        internals."""
        import time

        if self.exec_spec is not None:
            if time.monotonic() < self._exec_valid_until:
                return self.token
            return None
        return None  # non-exec refreshes are cheap; take the slow path

    def bearer_token(self) -> str:
        """The current token, honoring file rotation and exec plugins."""
        import time

        if self.exec_spec is not None:
            if time.monotonic() >= self._exec_valid_until:
                self._run_exec_plugin()
            return self.token
        if self.token_file:
            if self._file_token is None:
                from activemonitor_tpu.utils.tokenfile import FileToken

                self._file_token = FileToken(self.token_file, initial=self.token)
            self.token = self._file_token.get() or self.token
        return self.token

    def _run_exec_plugin(self) -> None:
        """client-go exec credential protocol: run the plugin, parse the
        ExecCredential JSON it prints, cache the token until its
        expirationTimestamp (minus slack), or for the default token TTL
        when the plugin reports no expiry."""
        import datetime
        import json
        import subprocess
        import time

        spec = self.exec_spec or {}
        cmd = [spec.get("command", "")] + list(spec.get("args") or [])
        env = dict(os.environ)
        for entry in spec.get("env") or []:
            env[entry.get("name", "")] = entry.get("value", "")
        env["KUBERNETES_EXEC_INFO"] = json.dumps(
            {
                "apiVersion": spec.get(
                    "apiVersion", "client.authentication.k8s.io/v1beta1"
                ),
                "kind": "ExecCredential",
                "spec": {"interactive": False},
            }
        )
        try:
            proc = subprocess.run(
                cmd, capture_output=True, env=env, timeout=60, check=False
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise KubeConfigError(f"credential plugin {cmd[0]!r} failed: {e}") from e
        if proc.returncode != 0:
            raise KubeConfigError(
                f"credential plugin {cmd[0]!r} exited {proc.returncode}: "
                f"{proc.stderr.decode(errors='replace')[:300]}"
            )
        try:
            status = (json.loads(proc.stdout) or {}).get("status") or {}
        except json.JSONDecodeError as e:
            raise KubeConfigError(
                f"credential plugin {cmd[0]!r} printed invalid JSON"
            ) from e
        if status.get("clientCertificateData"):
            raise KubeConfigError(
                f"credential plugin {cmd[0]!r} returned client certificates, "
                "which this client does not support; use a token-issuing "
                "plugin (e.g. gke-gcloud-auth-plugin) or static credentials"
            )
        token = status.get("token", "")
        if not token:
            raise KubeConfigError(
                f"credential plugin {cmd[0]!r} returned no token"
            )
        from activemonitor_tpu.utils.tokenfile import DEFAULT_TTL

        self.token = token
        valid = DEFAULT_TTL
        expiry_raw = status.get("expirationTimestamp")
        if expiry_raw:
            try:
                expiry = datetime.datetime.fromisoformat(
                    str(expiry_raw).replace("Z", "+00:00")
                )
                now = datetime.datetime.now(datetime.timezone.utc)
                valid = max(0.0, (expiry - now).total_seconds() - 60.0)
            except ValueError:
                pass
        self._exec_valid_until = time.monotonic() + valid

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        """An SSLContext for https servers; None for plain http (the
        stub server / kubectl proxy)."""
        if not self.server.startswith("https"):
            return None
        if not self.verify_tls:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_data:
            ctx = ssl.create_default_context(cadata=self.ca_data.decode())
        else:
            ctx = ssl.create_default_context()
        if self.client_cert_data and self.client_key_data:
            # load_cert_chain only takes paths — stage the PEMs in files
            # that live as long as this config object
            cert = tempfile.NamedTemporaryFile(suffix=".pem", delete=False)
            cert.write(self.client_cert_data)
            cert.close()
            key = tempfile.NamedTemporaryFile(suffix=".pem", delete=False)
            key.write(self.client_key_data)
            key.close()
            self._tempfiles.extend([cert.name, key.name])
            ctx.load_cert_chain(cert.name, key.name)
        return ctx

    def __del__(self):
        for path in self._tempfiles:
            try:
                os.unlink(path)
            except OSError:
                pass


def _read_maybe(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _b64_or_file(entry: dict, data_key: str, file_key: str) -> bytes:
    if entry.get(data_key):
        return base64.b64decode(entry[data_key])
    if entry.get(file_key):
        return _read_maybe(entry[file_key]) or b""
    return b""


def incluster_config() -> Optional[KubeConfig]:
    """The mounted service-account credentials, if running in a pod."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(SERVICEACCOUNT_DIR, "token")
    token = _read_maybe(token_path)
    if not host or token is None:
        return None
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # IPv6 literal must be bracketed in a URL
    ca = _read_maybe(os.path.join(SERVICEACCOUNT_DIR, "ca.crt")) or b""
    namespace = _read_maybe(os.path.join(SERVICEACCOUNT_DIR, "namespace")) or b"default"
    return KubeConfig(
        server=f"https://{host}:{port}",
        token=token.decode().strip(),
        token_file=token_path,
        ca_data=ca,
        namespace=namespace.decode().strip(),
    )


def kubeconfig_file_config(path: Optional[str] = None) -> Optional[KubeConfig]:
    """Parse a kubeconfig file (current-context only). Without an
    explicit path, $KUBECONFIG is honored as kubectl defines it — a
    colon-separated list, first file with a usable current-context wins —
    then ~/.kube/config."""
    if path is None:
        candidates = [
            p for p in os.environ.get("KUBECONFIG", "").split(os.pathsep) if p
        ] or [os.path.expanduser("~/.kube/config")]
        first_error: KubeConfigError | None = None
        for candidate in candidates:
            try:
                cfg = kubeconfig_file_config(candidate)
            except KubeConfigError as e:
                first_error = first_error or e
                continue  # unusable credentials: try the next file
            if cfg is not None:
                return cfg
        if first_error is not None:
            # a file EXISTED but its credentials are unusable: silently
            # falling through to other credential sources would connect
            # to a different cluster than the operator named
            raise first_error
        return None
    raw = _read_maybe(path)
    if raw is None:
        return None
    try:
        doc = yaml.safe_load(raw) or {}
        contexts = {c["name"]: c.get("context", {}) for c in doc.get("contexts", [])}
        clusters = {c["name"]: c.get("cluster", {}) for c in doc.get("clusters", [])}
        users = {u["name"]: u.get("user", {}) for u in doc.get("users", [])}
        current = doc.get("current-context")
        if not current or current not in contexts:
            return None
        ctx = contexts[current]
        cluster = clusters.get(ctx.get("cluster", ""), {})
        user = users.get(ctx.get("user", ""), {})
        server = cluster.get("server", "")
        if not server:
            return None
        cfg = KubeConfig(
            server=server,
            token=user.get("token", ""),
            ca_data=_b64_or_file(
                cluster, "certificate-authority-data", "certificate-authority"
            ),
            client_cert_data=_b64_or_file(
                user, "client-certificate-data", "client-certificate"
            ),
            client_key_data=_b64_or_file(user, "client-key-data", "client-key"),
            verify_tls=not cluster.get("insecure-skip-tls-verify", False),
            namespace=ctx.get("namespace", "default"),
            exec_spec=user.get("exec"),
        )
        if (
            server.startswith("https")
            and not cfg.token
            and not cfg.client_cert_data
            and cfg.exec_spec is None
        ):
            # fail at load time with an explanation, not at runtime with
            # anonymous 401s (http servers — kubectl proxy, test stubs —
            # are legitimately unauthenticated)
            auth_provider = (user.get("auth-provider") or {}).get("name", "none")
            raise KubeConfigError(
                f"kubeconfig user has no usable credentials (auth-provider "
                f"{auth_provider!r} is not supported; supported: token, "
                "client certificates, exec plugins)"
            )
        return cfg
    except (KeyError, AttributeError, TypeError, yaml.YAMLError) as e:
        # structurally malformed is NOT the same as missing: the operator
        # named this file, so silently falling through to other
        # credential sources could connect to the wrong cluster
        raise KubeConfigError(
            f"malformed kubeconfig at {path!r}: {type(e).__name__}: {e}"
        ) from e


def load_kube_config(kubeconfig: Optional[str] = None) -> KubeConfig:
    """client-go / controller-runtime precedence: explicit path, then
    $KUBECONFIG, then in-cluster credentials, then ~/.kube/config — a
    pod that deliberately sets KUBECONFIG (hosted-control-plane pattern)
    must reach THAT cluster, not its local one."""
    if kubeconfig:
        cfg = kubeconfig_file_config(kubeconfig)
        if cfg is None:
            raise KubeConfigError(f"unusable kubeconfig at {kubeconfig!r}")
        return cfg
    if os.environ.get("KUBECONFIG"):
        # delegate the colon-separated-list iteration (first usable wins)
        cfg = kubeconfig_file_config(None)
        if cfg is not None:
            return cfg
    cfg = incluster_config() or kubeconfig_file_config(
        os.path.expanduser("~/.kube/config")
    )
    if cfg is None:
        raise KubeConfigError(
            "no Kubernetes credentials found (not in a pod, and no kubeconfig "
            "at $KUBECONFIG or ~/.kube/config); cluster mode needs one of these"
        )
    return cfg
